// Native runtime kernels — the C++ substrate for host-side hot paths.
//
// Parity role (SURVEY.md §1 L7, §2.3): the reference implements its data
// pipeline (dmlc recordio chunk reader, src/io/iter_image_recordio_2.cc)
// and gradient compression (src/kvstore/gradient_compression.cc) in C++.
// The TPU build keeps XLA for device compute; these are the host-side
// equivalents, exposed through a plain C ABI consumed via ctypes
// (python/mxnet_tpu/_native). No pybind11 — the ABI stays compiler-stable.
//
// Format notes:
//   recordio framing (dmlc-core): [magic 0xced7230a][u32 len word] payload,
//   padded to 4-byte alignment; the upper 3 bits of the length word are the
//   continuation flag for split records (unused by im2rec output).
//   2-bit compression: 16 values per 32-bit word, element j in bits
//   (31-2j, 30-2j); 11=+threshold, 10=-threshold, 00=below.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {
constexpr uint32_t kMagic = 0xced7230au;
}

extern "C" {

int mxio_version() { return 1; }

// Scan a .rec file, filling offsets[i] (payload start) and lengths[i].
// Returns the number of records found, or -1 on IO/format error. Pass
// capacity=0 to count only.
long mxio_scan_records(const char* path, long* offsets, long* lengths,
                       long capacity) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  long count = 0;
  uint32_t head[2];
  for (;;) {
    long pos = std::ftell(fp);
    size_t got = std::fread(head, sizeof(uint32_t), 2, fp);
    if (got == 0) break;               // clean EOF
    if (got != 2 || head[0] != kMagic) {
      std::fclose(fp);
      return -1;                        // corrupt framing
    }
    uint32_t len = head[1] & ((1u << 29) - 1);
    if (offsets && count < capacity) {
      offsets[count] = pos + 2 * static_cast<long>(sizeof(uint32_t));
      lengths[count] = static_cast<long>(len);
    }
    ++count;
    long skip = static_cast<long>((len + 3u) & ~3u);
    if (std::fseek(fp, skip, SEEK_CUR) != 0) {
      std::fclose(fp);
      return -1;
    }
  }
  std::fclose(fp);
  return count;
}

// Gather many records into one contiguous buffer (the chunk-read role of
// iter_image_recordio_2.cc). dst must hold sum(lengths). Returns 0 on
// success.
int mxio_read_records(const char* path, const long* offsets,
                      const long* lengths, long n, unsigned char* dst) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  long written = 0;
  for (long i = 0; i < n; ++i) {
    if (std::fseek(fp, offsets[i], SEEK_SET) != 0 ||
        std::fread(dst + written, 1, static_cast<size_t>(lengths[i]), fp) !=
            static_cast<size_t>(lengths[i])) {
      std::fclose(fp);
      return -1;
    }
    written += lengths[i];
  }
  std::fclose(fp);
  return 0;
}

// 2-bit quantization with error feedback (gradient_compression-inl.h:40).
// grad[n], residual[n] (updated in place), out[ceil(n/16)] packed words.
void mxio_quantize_2bit(const float* grad, float* residual, uint32_t* out,
                        long n, float threshold) {
  const long nwords = (n + 15) / 16;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long w = 0; w < nwords; ++w) {
    uint32_t word = 0;
    const long start = w * 16;
    const long end = start + 16 < n ? start + 16 : n;
    for (long i = start; i < end; ++i) {
      float r = residual[i] + grad[i];
      const int shift = 30 - 2 * static_cast<int>(i - start);
      if (r >= threshold) {
        word |= 3u << shift;
        r -= threshold;
      } else if (r <= -threshold) {
        word |= 2u << shift;
        r += threshold;
      }
      residual[i] = r;
    }
    out[w] = word;
  }
}

// Inverse: packed words -> {-threshold, 0, +threshold} floats.
void mxio_dequantize_2bit(const uint32_t* in, float* out, long n,
                          float threshold) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long i = 0; i < n; ++i) {
    const uint32_t word = in[i / 16];
    const int shift = 30 - 2 * static_cast<int>(i % 16);
    const uint32_t code = (word >> shift) & 3u;
    out[i] = code == 3u ? threshold : (code == 2u ? -threshold : 0.0f);
  }
}

// CHW float conversion + normalization of an interleaved HWC uint8 image —
// the inner loop of batch assembly (image_aug_default.cc role).
void mxio_hwc_u8_to_chw_f32(const unsigned char* src, float* dst, long h,
                            long w, long c, const float* mean,
                            const float* stdinv) {
  for (long ch = 0; ch < c; ++ch) {
    const float m = mean ? mean[ch] : 0.0f;
    const float s = stdinv ? stdinv[ch] : 1.0f;
    float* plane = dst + ch * h * w;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
    for (long i = 0; i < h * w; ++i) {
      plane[i] = (static_cast<float>(src[i * c + ch]) - m) * s;
    }
  }
}

}  // extern "C"
