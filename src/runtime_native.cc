// Native runtime kernels — the C++ substrate for host-side hot paths.
//
// Parity role (SURVEY.md §1 L7, §2.3): the reference implements its data
// pipeline (dmlc recordio chunk reader, src/io/iter_image_recordio_2.cc)
// and gradient compression (src/kvstore/gradient_compression.cc) in C++.
// The TPU build keeps XLA for device compute; these are the host-side
// equivalents, exposed through a plain C ABI consumed via ctypes
// (python/mxnet_tpu/_native). No pybind11 — the ABI stays compiler-stable.
//
// Format notes:
//   recordio framing (dmlc-core): [magic 0xced7230a][u32 len word] payload,
//   padded to 4-byte alignment; the upper 3 bits of the length word are the
//   continuation flag for split records (unused by im2rec output).
//   2-bit compression: 16 values per 32-bit word, element j in bits
//   (31-2j, 30-2j); 11=+threshold, 10=-threshold, 00=below.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {
constexpr uint32_t kMagic = 0xced7230au;
}

extern "C" {

int mxio_version() { return 1; }

// Scan a .rec file, filling offsets[i] (payload start) and lengths[i].
// Returns the number of records found, or -1 on IO/format error. Pass
// capacity=0 to count only.
long mxio_scan_records(const char* path, long* offsets, long* lengths,
                       long capacity) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  long count = 0;
  uint32_t head[2];
  for (;;) {
    long pos = std::ftell(fp);
    size_t got = std::fread(head, sizeof(uint32_t), 2, fp);
    if (got == 0) break;               // clean EOF
    if (got != 2 || head[0] != kMagic) {
      std::fclose(fp);
      return -1;                        // corrupt framing
    }
    uint32_t len = head[1] & ((1u << 29) - 1);
    if (offsets && count < capacity) {
      offsets[count] = pos + 2 * static_cast<long>(sizeof(uint32_t));
      lengths[count] = static_cast<long>(len);
    }
    ++count;
    long skip = static_cast<long>((len + 3u) & ~3u);
    if (std::fseek(fp, skip, SEEK_CUR) != 0) {
      std::fclose(fp);
      return -1;
    }
  }
  std::fclose(fp);
  return count;
}

// Gather many records into one contiguous buffer (the chunk-read role of
// iter_image_recordio_2.cc). dst must hold sum(lengths). Returns 0 on
// success.
int mxio_read_records(const char* path, const long* offsets,
                      const long* lengths, long n, unsigned char* dst) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return -1;
  long written = 0;
  for (long i = 0; i < n; ++i) {
    if (std::fseek(fp, offsets[i], SEEK_SET) != 0 ||
        std::fread(dst + written, 1, static_cast<size_t>(lengths[i]), fp) !=
            static_cast<size_t>(lengths[i])) {
      std::fclose(fp);
      return -1;
    }
    written += lengths[i];
  }
  std::fclose(fp);
  return 0;
}

// 2-bit quantization with error feedback (gradient_compression-inl.h:40).
// grad[n], residual[n] (updated in place), out[ceil(n/16)] packed words.
void mxio_quantize_2bit(const float* grad, float* residual, uint32_t* out,
                        long n, float threshold) {
  const long nwords = (n + 15) / 16;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long w = 0; w < nwords; ++w) {
    uint32_t word = 0;
    const long start = w * 16;
    const long end = start + 16 < n ? start + 16 : n;
    for (long i = start; i < end; ++i) {
      float r = residual[i] + grad[i];
      const int shift = 30 - 2 * static_cast<int>(i - start);
      if (r >= threshold) {
        word |= 3u << shift;
        r -= threshold;
      } else if (r <= -threshold) {
        word |= 2u << shift;
        r += threshold;
      }
      residual[i] = r;
    }
    out[w] = word;
  }
}

// Inverse: packed words -> {-threshold, 0, +threshold} floats.
void mxio_dequantize_2bit(const uint32_t* in, float* out, long n,
                          float threshold) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (long i = 0; i < n; ++i) {
    const uint32_t word = in[i / 16];
    const int shift = 30 - 2 * static_cast<int>(i % 16);
    const uint32_t code = (word >> shift) & 3u;
    out[i] = code == 3u ? threshold : (code == 2u ? -threshold : 0.0f);
  }
}

// CHW float conversion + normalization of an interleaved HWC uint8 image —
// the inner loop of batch assembly (image_aug_default.cc role).
void mxio_hwc_u8_to_chw_f32(const unsigned char* src, float* dst, long h,
                            long w, long c, const float* mean,
                            const float* stdinv) {
  for (long ch = 0; ch < c; ++ch) {
    const float m = mean ? mean[ch] : 0.0f;
    const float s = stdinv ? stdinv[ch] : 1.0f;
    float* plane = dst + ch * h * w;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
    for (long i = 0; i < h * w; ++i) {
      plane[i] = (static_cast<float>(src[i * c + ch]) - m) * s;
    }
  }
}

}  // extern "C"

// ===========================================================================
// Native image pipeline — threaded record->decode->augment->batch engine.
//
// Parity role: src/io/iter_image_recordio_2.cc (chunk read + OMP-parallel
// JPEG decode + augment + batch assembly) and iter_prefetcher.h (double
// buffering). Worker threads claim batch sequence numbers, decode whole
// batches into pooled buffers, and a consumer drains them IN ORDER, so
// results are deterministic for a fixed (seed, epoch, order).
// ===========================================================================

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#if defined(MXIO_HAS_JPEG)
#include <csetjmp>
#include <jpeglib.h>
#endif

namespace {

#if defined(MXIO_HAS_JPEG)
struct JpegErr {
  jpeg_error_mgr pub;
  std::jmp_buf jmp;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  std::longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jmp, 1);
}

// Single JPEG decode core shared by the dims-query/caller-buffer ABI
// (mxio_jpeg_decode) and the pipeline's growable-scratch path. Decodes
// interleaved RGB u8. Modes: out==null && scratch==null -> dims query;
// out!=null -> capacity-checked write; scratch!=null -> resized to fit.
// The 64MP dimension-bomb cap applies only to scratch mode, where WE
// allocate; the dims query allocates nothing (callers apply their own
// policy) and the caller-buffer mode is bounded by `capacity`.
// Returns 0 on success, -1 on error.
int DecodeJpegCore(const unsigned char* data, long len, unsigned char* out,
                   long capacity, std::vector<unsigned char>* scratch,
                   long* h, long* w) {
  constexpr long kMaxPixels = 64L * 1024 * 1024;  // 64 MP sanity cap
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const long oh = cinfo.output_height, ow = cinfo.output_width;
  if (oh <= 0 || ow <= 0 || (scratch && oh * ow > kMaxPixels)) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = oh;
  *w = ow;
  const long stride = 3L * ow;
  unsigned char* dst = out;
  if (scratch) {
    scratch->resize(static_cast<size_t>(oh) * ow * 3);
    dst = scratch->data();
  } else if (!out) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 0;  // dims query
  } else if (stride * oh > capacity) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int DecodeJpegRGB(const unsigned char* data, long len, unsigned char* out,
                  long capacity, long* h, long* w) {
  return DecodeJpegCore(data, len, out, capacity, nullptr, h, w);
}

int DecodeJpegRGBScratch(const unsigned char* data, long len,
                         std::vector<unsigned char>& out, long* h, long* w) {
  return DecodeJpegCore(data, len, nullptr, 0, &out, h, w);
}

#endif  // MXIO_HAS_JPEG

// Bilinear resize of interleaved RGB u8 (align_corners=false convention,
// matching cv2.INTER_LINEAR / PIL BILINEAR up to rounding).
void ResizeBilinearRGB(const unsigned char* src, long sh, long sw,
                       unsigned char* dst, long dh, long dw) {
  const float ys = static_cast<float>(sh) / dh;
  const float xs = static_cast<float>(sw) / dw;
  for (long y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * ys - 0.5f;
    if (fy < 0) fy = 0;
    long y0 = static_cast<long>(fy);
    if (y0 > sh - 1) y0 = sh - 1;
    long y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    const float wy = fy - y0;
    for (long x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * xs - 0.5f;
      if (fx < 0) fx = 0;
      long x0 = static_cast<long>(fx);
      if (x0 > sw - 1) x0 = sw - 1;
      long x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      const float wx = fx - x0;
      for (long ch = 0; ch < 3; ++ch) {
        const float v00 = src[(y0 * sw + x0) * 3 + ch];
        const float v01 = src[(y0 * sw + x1) * 3 + ch];
        const float v10 = src[(y1 * sw + x0) * 3 + ch];
        const float v11 = src[(y1 * sw + x1) * 3 + ch];
        const float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                        v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * 3 + ch] =
            static_cast<unsigned char>(v + 0.5f);
      }
    }
  }
}

// xorshift64* — deterministic per-(seed,epoch,record) augmentation RNG
inline uint64_t NextRand(uint64_t* s) {
  uint64_t x = *s;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *s = x;
  return x * 0x2545F4914F6CDD1DULL;
}

struct PipeConfig {
  long batch, C, H, W;
  long resize_short;          // 0 = no resize
  int rand_crop, rand_mirror;
  std::vector<float> mean, stdinv;  // size C or empty
  long label_width;
  uint64_t seed;
  // uint8 output mode: raw CHW bytes, no mean/std — 4x less data for the
  // host->device transfer; normalization runs on-device (the TPU-native
  // input regime: ship bytes, normalize in the compiled step)
  int out_u8 = 0;
};

struct BatchBuf {
  std::vector<float> data;        // batch*C*H*W (f32 mode)
  std::vector<unsigned char> u8;  // batch*C*H*W (u8 mode)
  std::vector<float> label;       // batch*label_width
  long pad = 0;
};

struct Pipe {
  PipeConfig cfg;
  FILE* fp = nullptr;
  std::mutex fp_mu;
  std::vector<long> offsets, lengths;   // full record table
  std::vector<long> order;              // epoch order (indices into table)
  uint64_t epoch = 0;

  long nthreads = 1;
  long nbatches = 0;
  std::atomic<long> next_claim{0};
  long next_deliver = 0;

  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::map<long, BatchBuf*> ready;
  std::vector<BatchBuf*> freelist;
  std::vector<BatchBuf*> all_bufs;
  std::atomic<int> error{0};
  bool stopping = false;

  std::vector<std::thread> workers;

  ~Pipe() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_free.notify_all();
    for (auto& t : workers) t.join();
    for (auto* b : all_bufs) delete b;
    if (fp) std::fclose(fp);
  }
};

// Decode + augment one record payload into batch slot i. `raw`/`resized`
// are per-worker scratch buffers reused across records. Returns 0/-1.
int ProcessRecord(Pipe* p, const unsigned char* payload, long len,
                  uint64_t rng_seed, float* data_slot,
                  unsigned char* u8_slot, float* label_slot,
                  std::vector<unsigned char>& raw,
                  std::vector<unsigned char>& resized) {
#if !defined(MXIO_HAS_JPEG)
  (void)p; (void)payload; (void)len; (void)rng_seed; (void)data_slot;
  (void)u8_slot; (void)label_slot; (void)raw; (void)resized;
  return -1;
#else
  const PipeConfig& c = p->cfg;
  if (len < 24) return -1;
  uint32_t flag;
  float flabel;
  std::memcpy(&flag, payload, 4);
  std::memcpy(&flabel, payload + 4, 4);
  const unsigned char* img = payload + 24;
  long img_len = len - 24;
  for (long j = 0; j < c.label_width; ++j) label_slot[j] = 0.0f;
  if (flag > 0) {
    if (img_len < static_cast<long>(flag) * 4) return -1;
    const long ncopy = flag < static_cast<uint32_t>(c.label_width)
                           ? flag : c.label_width;
    std::memcpy(label_slot, img, ncopy * 4);
    img += flag * 4;
    img_len -= flag * 4;
  } else {
    label_slot[0] = flabel;
  }
  if (img_len < 2 || img[0] != 0xFF || img[1] != 0xD8) return -1;  // not JPEG

  long sh = 0, sw = 0;
  if (DecodeJpegRGBScratch(img, img_len, raw, &sh, &sw) != 0) return -1;

  const unsigned char* cur = raw.data();
  long ch_ = sh, cw = sw;
  if (c.resize_short > 0 && (sh < sw ? sh : sw) != c.resize_short) {
    const long short_side = sh < sw ? sh : sw;
    const double scale = static_cast<double>(c.resize_short) / short_side;
    long nh = static_cast<long>(sh * scale + 0.5);
    long nw = static_cast<long>(sw * scale + 0.5);
    if (sh < sw) nh = c.resize_short; else nw = c.resize_short;
    resized.resize(static_cast<size_t>(nh) * nw * 3);
    ResizeBilinearRGB(raw.data(), sh, sw, resized.data(), nh, nw);
    cur = resized.data();
    ch_ = nh;
    cw = nw;
  }
  if (ch_ < c.H || cw < c.W) return -1;  // too small to crop (reference errors)

  uint64_t rs = rng_seed;
  long y0 = (ch_ - c.H) / 2, x0 = (cw - c.W) / 2;
  if (c.rand_crop) {
    y0 = ch_ == c.H ? 0 : static_cast<long>(NextRand(&rs) % (ch_ - c.H + 1));
    x0 = cw == c.W ? 0 : static_cast<long>(NextRand(&rs) % (cw - c.W + 1));
  }
  const bool mirror = c.rand_mirror && (NextRand(&rs) & 1);

  const long plane = c.H * c.W;
  if (c.out_u8) {
    for (long ch = 0; ch < c.C; ++ch) {
      unsigned char* out_plane = u8_slot + ch * plane;
      for (long y = 0; y < c.H; ++y) {
        const unsigned char* row = cur + ((y0 + y) * cw + x0) * 3;
        unsigned char* orow = out_plane + y * c.W;
        if (!mirror) {
          for (long x = 0; x < c.W; ++x) orow[x] = row[x * 3 + ch];
        } else {
          for (long x = 0; x < c.W; ++x)
            orow[x] = row[(c.W - 1 - x) * 3 + ch];
        }
      }
    }
    return 0;
  }
  for (long ch = 0; ch < c.C; ++ch) {
    const float m = ch < static_cast<long>(c.mean.size()) ? c.mean[ch] : 0.0f;
    const float si = ch < static_cast<long>(c.stdinv.size())
                         ? c.stdinv[ch] : 1.0f;
    float* out_plane = data_slot + ch * plane;
    for (long y = 0; y < c.H; ++y) {
      const unsigned char* row = cur + ((y0 + y) * cw + x0) * 3;
      float* orow = out_plane + y * c.W;
      if (!mirror) {
        for (long x = 0; x < c.W; ++x)
          orow[x] = (static_cast<float>(row[x * 3 + ch]) - m) * si;
      } else {
        for (long x = 0; x < c.W; ++x)
          orow[x] = (static_cast<float>(row[(c.W - 1 - x) * 3 + ch]) - m) * si;
      }
    }
  }
  return 0;
#endif
}

bool g_pipe_debug = std::getenv("MXIO_PIPE_DEBUG") != nullptr;

void WorkerLoop(Pipe* p) {
  const PipeConfig& c = p->cfg;
  const long slot_sz = c.C * c.H * c.W;
  std::vector<unsigned char> rec_buf, raw_scratch, resized_scratch;
  constexpr long kMaxRecordBytes = 256L * 1024 * 1024;
  for (;;) {
    // Acquire a buffer BEFORE claiming a sequence number. Claiming first
    // deadlocks: with all buffers holding batches AHEAD of the in-order
    // delivery point, the worker that claimed the next-needed batch waits
    // for a buffer the consumer will never free (it is waiting for that
    // very batch). Buffer-first, every claimed batch is processable.
    BatchBuf* buf = nullptr;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv_free.wait(lk, [&] {
        return p->stopping || p->error.load() || !p->freelist.empty();
      });
      if (p->stopping || p->error.load()) return;
      buf = p->freelist.back();
      p->freelist.pop_back();
    }
    const long seq = p->next_claim.fetch_add(1);
    if (g_pipe_debug)
      std::fprintf(stderr, "[mxio] worker claimed seq %ld (has buffer)\n",
                   seq);
    if (seq >= p->nbatches || p->error.load()) {
      {
        std::lock_guard<std::mutex> lk(p->mu);
        p->freelist.push_back(buf);
      }
      p->cv_free.notify_all();
      return;
    }
    const long start = seq * c.batch;
    const long n_items = static_cast<long>(p->order.size());
    buf->pad = start + c.batch > n_items ? start + c.batch - n_items : 0;
    int rc = 0;
    // contain allocation failures (corrupt length tables / dimension
    // bombs) to this batch: error flag + IOError in python, not terminate
    try {
      for (long i = 0; i < c.batch && rc == 0; ++i) {
        // round_batch semantics: wrap into the epoch head for the tail pad
        const long idx = p->order[(start + i) % n_items];
        long off = p->offsets[idx], ln = p->lengths[idx];
        if (ln <= 0 || ln > kMaxRecordBytes) {
          rc = -1;
          break;
        }
        rec_buf.resize(ln);
        {
          std::lock_guard<std::mutex> lk(p->fp_mu);
          if (std::fseek(p->fp, off, SEEK_SET) != 0 ||
              std::fread(rec_buf.data(), 1, ln, p->fp) !=
                  static_cast<size_t>(ln)) {
            rc = -1;
            break;
          }
        }
        const uint64_t rseed =
            (p->cfg.seed * 1000003ULL + p->epoch) * 0x9E3779B97F4A7C15ULL +
            static_cast<uint64_t>(idx) + 1;
        uint64_t rs = rseed;
        NextRand(&rs);
        rc = ProcessRecord(
            p, rec_buf.data(), ln, rs,
            c.out_u8 ? nullptr : buf->data.data() + i * slot_sz,
            c.out_u8 ? buf->u8.data() + i * slot_sz : nullptr,
            buf->label.data() + i * c.label_width,
            raw_scratch, resized_scratch);
      }
    } catch (...) {
      rc = -1;
    }
    {
      std::lock_guard<std::mutex> lk(p->mu);
      if (rc != 0) {
        p->error.store(1);
        p->freelist.push_back(buf);
      } else {
        p->ready[seq] = buf;
      }
    }
    if (g_pipe_debug)
      std::fprintf(stderr, "[mxio] worker pushed seq %ld rc=%d\n", seq, rc);
    p->cv_ready.notify_all();
    if (rc != 0) {
      p->cv_free.notify_all();
      return;
    }
  }
}

}  // namespace

extern "C" {

int mxio_has_jpeg() {
#if defined(MXIO_HAS_JPEG)
  return 1;
#else
  return 0;
#endif
}

// Decode one JPEG to RGB u8. Query dims with out=null. Returns 0 / -1.
int mxio_jpeg_decode(const unsigned char* data, long len, unsigned char* out,
                     long capacity, long* h, long* w) {
#if defined(MXIO_HAS_JPEG)
  try {
    return DecodeJpegRGB(data, len, out, capacity, h, w);
  } catch (...) {
    return -1;  // never let a C++ exception cross the C ABI
  }
#else
  (void)data; (void)len; (void)out; (void)capacity; (void)h; (void)w;
  return -1;
#endif
}

void* mxio_pipe_create(const char* rec_path, const long* offsets,
                       const long* lengths, long n_records, long batch,
                       long C, long H, long W, long resize_short,
                       int rand_crop, int rand_mirror, const float* mean,
                       const float* stdinv, long label_width, long nthreads,
                       long depth, uint64_t seed, int out_u8) {
#if !defined(MXIO_HAS_JPEG)
  return nullptr;
#endif
  if (C != 3 || n_records <= 0 || batch <= 0) return nullptr;
  Pipe* p = new Pipe();
  p->fp = std::fopen(rec_path, "rb");
  if (!p->fp) {
    delete p;
    return nullptr;
  }
  p->cfg = PipeConfig{batch, C, H, W, resize_short, rand_crop, rand_mirror,
                      mean ? std::vector<float>(mean, mean + C)
                           : std::vector<float>(),
                      stdinv ? std::vector<float>(stdinv, stdinv + C)
                             : std::vector<float>(),
                      label_width, seed, out_u8};
  p->offsets.assign(offsets, offsets + n_records);
  p->lengths.assign(lengths, lengths + n_records);
  if (depth < 2) depth = 2;
  for (long i = 0; i < depth; ++i) {
    BatchBuf* b = new BatchBuf();
    if (out_u8)
      b->u8.resize(static_cast<size_t>(batch) * C * H * W);
    else
      b->data.resize(static_cast<size_t>(batch) * C * H * W);
    b->label.resize(static_cast<size_t>(batch) * label_width);
    p->all_bufs.push_back(b);
    p->freelist.push_back(b);
  }
  // workers are (re)spawned per epoch by mxio_pipe_reset
  p->nthreads = nthreads < 1 ? 1 : nthreads;
  p->nbatches = 0;
  p->next_claim.store(0);
  return p;
}

// Start an epoch over `order` (indices into the record table). Spawns the
// worker pool. Must be called before the first next(); subsequent calls
// re-arm after EOF.
int mxio_pipe_reset(void* handle, const long* order, long n) {
  Pipe* p = static_cast<Pipe*>(handle);
  if (!p || n <= 0) return -1;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = true;
  }
  p->cv_free.notify_all();
  for (auto& t : p->workers) t.join();
  p->workers.clear();
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stopping = false;
    p->error.store(0);
    for (auto& kv : p->ready) p->freelist.push_back(kv.second);
    p->ready.clear();
  }
  p->order.assign(order, order + n);
  p->epoch += 1;
  p->nbatches = (n + p->cfg.batch - 1) / p->cfg.batch;
  p->next_claim.store(0);
  p->next_deliver = 0;
  long spawn = p->nthreads < p->nbatches ? p->nthreads : p->nbatches;
  for (long i = 0; i < spawn; ++i)
    p->workers.emplace_back(WorkerLoop, p);
  return 0;
}

// Fill data[batch*C*H*W] and label[batch*label_width]; *pad = #wrapped
// tail records in this batch. Returns 0 ok, 1 epoch done, -1 error.
int mxio_pipe_next(void* handle, float* data, float* label, long* pad) {
  Pipe* p = static_cast<Pipe*>(handle);
  if (!p) return -1;
  if (p->next_deliver >= p->nbatches) return 1;
  BatchBuf* buf = nullptr;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_ready.wait(lk, [&] {
      return p->error.load() ||
             p->ready.count(p->next_deliver) > 0;
    });
    if (p->error.load() && p->ready.count(p->next_deliver) == 0) return -1;
    buf = p->ready[p->next_deliver];
    p->ready.erase(p->next_deliver);
  }
  if (p->cfg.out_u8)
    std::memcpy(data, buf->u8.data(), buf->u8.size());
  else
    std::memcpy(data, buf->data.data(), buf->data.size() * sizeof(float));
  std::memcpy(label, buf->label.data(), buf->label.size() * sizeof(float));
  if (pad) *pad = buf->pad;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->freelist.push_back(buf);
  }
  if (g_pipe_debug)
    std::fprintf(stderr, "[mxio] consumer freed buffer after seq %ld\n",
                 p->next_deliver);
  p->cv_free.notify_all();
  p->next_deliver += 1;
  return 0;
}

void mxio_pipe_destroy(void* handle) {
  delete static_cast<Pipe*>(handle);
}

}  // extern "C"
