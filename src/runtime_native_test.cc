// Native-side unit tests for src/runtime_native.cc (role of the
// reference's tests/cpp gtest tier — here a dependency-free assert
// harness so the image needs no gtest). Build+run via
// tests/test_native.py::test_cpp_unit_harness:
//
//   g++ -O2 -std=c++17 -DMXIO_HAS_JPEG runtime_native_test.cc \
//       runtime_native.cc -ljpeg -lpthread -o t && ./t
//
// Exercises, from C++ (no python in the loop): recordio framing
// round-trip, 2-bit quantization numerics + error feedback, the CHW
// conversion kernel, and the threaded pipe's ordering/reset/error
// behavior against a synthetic JPEG record file.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <string>
#include <vector>

extern "C" {
long mxio_scan_records(const char*, long*, long*, long);
int mxio_read_records(const char*, const long*, const long*, long,
                      unsigned char*);
void mxio_quantize_2bit(const float*, float*, uint32_t*, long, float);
void mxio_dequantize_2bit(const uint32_t*, float*, long, float);
void mxio_hwc_u8_to_chw_f32(const unsigned char*, float*, long, long, long,
                            const float*, const float*);
int mxio_has_jpeg();
int mxio_jpeg_decode(const unsigned char*, long, unsigned char*, long,
                     long*, long*);
void* mxio_pipe_create(const char*, const long*, const long*, long, long,
                       long, long, long, long, int, int, const float*,
                       const float*, long, long, long, uint64_t);
int mxio_pipe_reset(void*, const long*, long);
int mxio_pipe_next(void*, float*, float*, long*);
void mxio_pipe_destroy(void*);
}

#if defined(MXIO_HAS_JPEG)
#include <jpeglib.h>
#endif

static int g_failures = 0;
#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      ++g_failures;                                                     \
    }                                                                   \
  } while (0)

namespace {
constexpr uint32_t kMagic = 0xced7230au;

void WriteRec(FILE* fp, const unsigned char* payload, long len) {
  uint32_t head[2] = {kMagic, static_cast<uint32_t>(len)};
  std::fwrite(head, sizeof(uint32_t), 2, fp);
  std::fwrite(payload, 1, len, fp);
  static const unsigned char pad[4] = {0, 0, 0, 0};
  std::fwrite(pad, 1, (4 - (len & 3)) & 3, fp);
}

void TestRecordioRoundTrip(const std::string& dir) {
  const std::string path = dir + "/t.rec";
  FILE* fp = std::fopen(path.c_str(), "wb");
  std::vector<std::vector<unsigned char>> payloads;
  for (int i = 0; i < 7; ++i) {
    payloads.emplace_back(5 + 11 * i, static_cast<unsigned char>(i));
    WriteRec(fp, payloads.back().data(),
             static_cast<long>(payloads.back().size()));
  }
  std::fclose(fp);
  long n = mxio_scan_records(path.c_str(), nullptr, nullptr, 0);
  CHECK(n == 7);
  std::vector<long> offs(n), lens(n);
  CHECK(mxio_scan_records(path.c_str(), offs.data(), lens.data(), n) == n);
  long total = 0;
  for (long i = 0; i < n; ++i) total += lens[i];
  std::vector<unsigned char> buf(total);
  CHECK(mxio_read_records(path.c_str(), offs.data(), lens.data(), n,
                          buf.data()) == 0);
  long pos = 0;
  for (long i = 0; i < n; ++i) {
    CHECK(lens[i] == static_cast<long>(payloads[i].size()));
    CHECK(std::memcmp(buf.data() + pos, payloads[i].data(), lens[i]) == 0);
    pos += lens[i];
  }
}

void Test2BitNumerics() {
  const long n = 37;
  std::vector<float> grad(n), residual(n, 0.0f), out(n);
  for (long i = 0; i < n; ++i) grad[i] = 0.11f * (i % 7) - 0.3f;
  std::vector<uint32_t> packed((n + 15) / 16);
  const float thr = 0.25f;
  mxio_quantize_2bit(grad.data(), residual.data(), packed.data(), n, thr);
  mxio_dequantize_2bit(packed.data(), out.data(), n, thr);
  for (long i = 0; i < n; ++i) {
    // decode is in {-thr, 0, +thr} and error feedback holds exactly:
    // residual == grad - decoded
    CHECK(out[i] == 0.0f || out[i] == thr || out[i] == -thr);
    CHECK(std::fabs(residual[i] - (grad[i] - out[i])) < 1e-6f);
  }
}

void TestChwConversion() {
  const long h = 3, w = 5, c = 3;
  std::vector<unsigned char> img(h * w * c);
  for (size_t i = 0; i < img.size(); ++i)
    img[i] = static_cast<unsigned char>((i * 7) % 251);
  const float mean[3] = {1.0f, 2.0f, 3.0f};
  const float stdinv[3] = {0.5f, 0.25f, 2.0f};
  std::vector<float> out(c * h * w);
  mxio_hwc_u8_to_chw_f32(img.data(), out.data(), h, w, c, mean, stdinv);
  for (long ch = 0; ch < c; ++ch)
    for (long i = 0; i < h * w; ++i)
      CHECK(std::fabs(out[ch * h * w + i] -
                      (static_cast<float>(img[i * c + ch]) - mean[ch]) *
                          stdinv[ch]) < 1e-5f);
}

#if defined(MXIO_HAS_JPEG)
std::vector<unsigned char> EncodeGrayJpeg(int h, int w, int seed) {
  // encode a smooth RGB image via libjpeg into memory
  std::vector<unsigned char> rgb(static_cast<size_t>(h) * w * 3);
  for (int y = 0; y < h; ++y)
    for (int x = 0; x < w; ++x)
      for (int ch = 0; ch < 3; ++ch)
        rgb[(static_cast<size_t>(y) * w + x) * 3 + ch] =
            static_cast<unsigned char>((y * 3 + x * 2 + ch * 40 + seed * 17) %
                                       256);
  jpeg_compress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jpeg_create_compress(&cinfo);
  unsigned char* mem = nullptr;
  unsigned long mem_size = 0;
  jpeg_mem_dest(&cinfo, &mem, &mem_size);
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, 95, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row = rgb.data() + static_cast<size_t>(cinfo.next_scanline) *
                                    w * 3;
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  std::vector<unsigned char> out(mem, mem + mem_size);
  std::free(mem);
  return out;
}

void TestPipeOrderingAndReset(const std::string& dir) {
  const std::string path = dir + "/imgs.rec";
  FILE* fp = std::fopen(path.c_str(), "wb");
  for (int i = 0; i < 10; ++i) {
    auto jpg = EncodeGrayJpeg(40, 50, i);
    // IRHeader: flag=0, label=i, id=i, id2=0  (recordio.py "IfQQ")
    std::vector<unsigned char> payload(24 + jpg.size());
    uint32_t flag = 0;
    float label = static_cast<float>(i);
    uint64_t id = i, id2 = 0;
    std::memcpy(payload.data(), &flag, 4);
    std::memcpy(payload.data() + 4, &label, 4);
    std::memcpy(payload.data() + 8, &id, 8);
    std::memcpy(payload.data() + 16, &id2, 8);
    std::memcpy(payload.data() + 24, jpg.data(), jpg.size());
    WriteRec(fp, payload.data(), static_cast<long>(payload.size()));
  }
  std::fclose(fp);

  long n = mxio_scan_records(path.c_str(), nullptr, nullptr, 0);
  CHECK(n == 10);
  std::vector<long> offs(n), lens(n);
  mxio_scan_records(path.c_str(), offs.data(), lens.data(), n);

  void* pipe = mxio_pipe_create(path.c_str(), offs.data(), lens.data(), n,
                                /*batch=*/4, 3, 32, 32, /*resize=*/36,
                                /*rand_crop=*/0, /*rand_mirror=*/0, nullptr,
                                nullptr, /*label_width=*/1, /*threads=*/3,
                                /*depth=*/2, /*seed=*/1);
  CHECK(pipe != nullptr);
  std::vector<long> order(n);
  for (long i = 0; i < n; ++i) order[i] = i;
  std::vector<float> data(4 * 3 * 32 * 32), label(4);

  for (int epoch = 0; epoch < 2; ++epoch) {
    CHECK(mxio_pipe_reset(pipe, order.data(), n) == 0);
    int batches = 0;
    long pad = 0;
    float first_label = -1;
    while (true) {
      int rc = mxio_pipe_next(pipe, data.data(), label.data(), &pad);
      if (rc == 1) break;
      CHECK(rc == 0);
      if (batches == 0) first_label = label[0];
      ++batches;
    }
    CHECK(batches == 3);       // ceil(10/4)
    CHECK(pad == 2);           // tail wraps 2 records
    CHECK(first_label == 0.0f);  // in-order delivery
  }

  // corrupt record -> error surfaces, not a hang/crash
  std::vector<long> bad_lens = lens;
  bad_lens[0] = 10;  // payload shorter than IRHeader
  void* bad = mxio_pipe_create(path.c_str(), offs.data(), bad_lens.data(),
                               n, 4, 3, 32, 32, 36, 0, 0, nullptr, nullptr,
                               1, 2, 2, 1);
  CHECK(bad != nullptr);
  CHECK(mxio_pipe_reset(bad, order.data(), n) == 0);
  int rc = 0;
  for (int i = 0; i < 3 && rc == 0; ++i)
    rc = mxio_pipe_next(bad, data.data(), label.data(), nullptr);
  CHECK(rc == -1);
  mxio_pipe_destroy(bad);
  mxio_pipe_destroy(pipe);
}
#endif  // MXIO_HAS_JPEG

}  // namespace

int main(int argc, char** argv) {
  const std::string dir = argc > 1 ? argv[1] : ".";
  TestRecordioRoundTrip(dir);
  Test2BitNumerics();
  TestChwConversion();
#if defined(MXIO_HAS_JPEG)
  if (mxio_has_jpeg()) TestPipeOrderingAndReset(dir);
#endif
  if (g_failures == 0) {
    std::printf("ALL NATIVE TESTS PASSED\n");
    return 0;
  }
  std::fprintf(stderr, "%d native test failures\n", g_failures);
  return 1;
}
