"""Learning-rate schedulers.

Parity surface: python/mxnet/lr_scheduler.py (SURVEY.md §2.4) —
FactorScheduler, MultiFactorScheduler, PolyScheduler keyed on num_update.

Own design: each schedule is a pure function of `num_update` (no stateful
catch-up loops) — the decay count is computed closed-form, which also makes
the schedulers trivially checkpoint-safe.
"""
from __future__ import annotations

import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler"]


class LRScheduler:
    """Base: maps the optimizer's update counter to a learning rate."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError

    def _log_if_changed(self, num_update, lr):
        last = getattr(self, "_last_lr", None)
        self._last_lr = lr
        if last is not None and lr != last:
            logging.info("Update[%d]: learning rate is now %0.5e",
                         num_update, lr)
        return lr


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^k after every `step` updates, floored at
    stop_factor_lr. Decay k happens once num_update exceeds k*step."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 (lr must not grow)")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def __call__(self, num_update):
        decays = max(0, (num_update - 1) // self.step)
        lr = max(self.base_lr * self.factor ** decays, self.stop_factor_lr)
        return self._log_if_changed(num_update, lr)


class MultiFactorScheduler(LRScheduler):
    """lr *= factor when num_update passes each milestone in `step`."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of milestones")
        if any(s < 1 for s in step):
            raise ValueError("milestones must be >= 1")
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError("milestones must be strictly increasing")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 (lr must not grow)")
        self.step = step
        self.factor = factor

    def __call__(self, num_update):
        decays = sum(1 for s in self.step if num_update > s)
        lr = self.base_lr * self.factor ** decays
        return self._log_if_changed(num_update, lr)


class PolyScheduler(LRScheduler):
    """Polynomial decay base_lr * (1 - t/T)^power down to 0 at T."""

    def __init__(self, max_update, base_lr=0.01, pwr=2):
        super().__init__(base_lr)
        if not isinstance(max_update, int) or max_update < 1:
            raise ValueError("max_update must be a positive int")
        self.max_update = max_update
        self.power = pwr

    def __call__(self, num_update):
        t = min(num_update, self.max_update)
        return self.base_lr * (1.0 - t / self.max_update) ** self.power
