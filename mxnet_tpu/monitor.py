"""Monitor — per-op output introspection during training.

Parity surface: python/mxnet/monitor.py (SURVEY.md §2.4); the tap point is
the Executor monitor callback (reference: graph_executor.cc:1451; here the
un-fused monitored forward path). Own design: the monitor is a window
recorder — `tic()` opens a recording window every `interval` steps,
executor callbacks append (step, name, stat) records while it is open, and
`toc()` closes the window, appends final-output stats, and renders.
"""
from __future__ import annotations

import logging
import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _mean_abs(x):
    """Default statistic: mean(|x|)."""
    return x.abs().mean()


def _render_stat(value):
    """Render a stat result (NDArray or list of NDArrays) to text."""
    values = value if isinstance(value, list) else [value]
    parts = []
    for v in values:
        if not isinstance(v, NDArray):
            parts.append(str(v))
        elif v.shape in ((1,), ()):
            parts.append(str(v.asscalar()))
        else:
            parts.append(str(v.asnumpy()))
    return "\t".join(parts) + "\t"


class Monitor:
    """Record statistics of intermediate outputs every `interval` batches.

    stat_func: NDArray -> NDArray (or list), default mean(|x|).
    pattern: regex filtering tapped entry names.
    sort: sort records by entry name before rendering.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        self.interval = interval
        self.stat_func = stat_func or _mean_abs
        self.sort = sort
        self.monitor_all = monitor_all
        self._name_filter = re.compile(pattern)
        self._records = []
        self._window_open = False
        self.step = 0
        self._executors = []

    # Executor callback contract: fn(entry_name, NDArray)
    def __call__(self, name, array):
        if self._window_open and self._name_filter.match(name):
            self._records.append((self.step, name, self.stat_func(array)))

    # legacy attribute alias (reference exposes .stat_helper)
    @property
    def stat_helper(self):
        return self

    def install(self, exe):
        """Attach to an executor (the monitor itself is the callback)."""
        exe.set_monitor_callback(self, self.monitor_all)
        self._executors.append(exe)

    def _drain(self):
        """Block until attached executors' params are materialized, so the
        stats reflect this step (the engine WaitToRead role)."""
        for exe in self._executors:
            for arr in exe.arg_arrays:
                arr.wait_to_read()

    def tic(self):
        """Open a recording window if this step is on the interval."""
        if self.step % self.interval == 0:
            self._drain()
            self._records = []
            self._window_open = True
        self.step += 1

    def toc(self):
        """Close the window; returns [(step, name, rendered_stat)]."""
        if not self._window_open:
            return []
        self._drain()
        for exe in self._executors:
            for name, out in zip(exe._output_names, exe.outputs):
                self._records.append((self.step, name, self.stat_func(out)))
        self._window_open = False
        records = sorted(self._records, key=lambda r: r[1]) if self.sort \
            else list(self._records)
        self._records = []
        return [(step, name, _render_stat(val))
                for (step, name, val) in records]

    def toc_print(self):
        """toc() + log each record."""
        for step, name, text in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, text)
