"""Native runtime bindings — loads (building on demand) the C++ library.

The reference's host-side runtime (recordio chunk reader, gradient
compression, image batch assembly) is C++; src/runtime_native.cc is the
TPU build's equivalent. Bound through ctypes over a plain C ABI (pybind11
is deliberately avoided — see the Environment constraints). Everything has
a pure-python fallback: `lib()` returns None when no compiler is
available, and callers degrade gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

import numpy as _np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src", "runtime_native.cc")


def _build_dir():
    d = os.environ.get("MXNET_TPU_NATIVE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "mxnet_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _compile():
    out = os.path.join(_build_dir(), "libmxnet_tpu_runtime.so")
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(_SRC):
        return out
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            _SRC, "-o", out, "-lpthread"]
    # most-capable first: JPEG pipeline + OpenMP, then degrade
    variants = [["-fopenmp", "-DMXIO_HAS_JPEG", "-ljpeg"],
                ["-DMXIO_HAS_JPEG", "-ljpeg"],
                ["-fopenmp"],
                []]
    for extra in variants:
        try:
            subprocess.run(base + extra, check=True, capture_output=True,
                           timeout=120)
            return out
        except (OSError, subprocess.SubprocessError):
            continue
    return None


def _bind(path):
    lib = ctypes.CDLL(path)
    L = ctypes.c_long
    P_L = ctypes.POINTER(ctypes.c_long)
    P_F = ctypes.POINTER(ctypes.c_float)
    P_U8 = ctypes.POINTER(ctypes.c_ubyte)
    P_U32 = ctypes.POINTER(ctypes.c_uint32)
    lib.mxio_version.restype = ctypes.c_int
    lib.mxio_scan_records.restype = L
    lib.mxio_scan_records.argtypes = [ctypes.c_char_p, P_L, P_L, L]
    lib.mxio_read_records.restype = ctypes.c_int
    lib.mxio_read_records.argtypes = [ctypes.c_char_p, P_L, P_L, L, P_U8]
    lib.mxio_quantize_2bit.restype = None
    lib.mxio_quantize_2bit.argtypes = [P_F, P_F, P_U32, L, ctypes.c_float]
    lib.mxio_dequantize_2bit.restype = None
    lib.mxio_dequantize_2bit.argtypes = [P_U32, P_F, L, ctypes.c_float]
    lib.mxio_hwc_u8_to_chw_f32.restype = None
    lib.mxio_hwc_u8_to_chw_f32.argtypes = [P_U8, P_F, L, L, L, P_F, P_F]
    lib.mxio_has_jpeg.restype = ctypes.c_int
    lib.mxio_jpeg_decode.restype = ctypes.c_int
    lib.mxio_jpeg_decode.argtypes = [P_U8, L, P_U8, L, P_L, P_L]
    lib.mxio_pipe_create.restype = ctypes.c_void_p
    lib.mxio_pipe_create.argtypes = [
        ctypes.c_char_p, P_L, P_L, L, L, L, L, L, L,
        ctypes.c_int, ctypes.c_int, P_F, P_F, L, L, L, ctypes.c_uint64,
        ctypes.c_int]
    lib.mxio_pipe_reset.restype = ctypes.c_int
    lib.mxio_pipe_reset.argtypes = [ctypes.c_void_p, P_L, L]
    lib.mxio_pipe_next.restype = ctypes.c_int
    lib.mxio_pipe_next.argtypes = [ctypes.c_void_p, P_F, P_F, P_L]
    lib.mxio_pipe_destroy.restype = None
    lib.mxio_pipe_destroy.argtypes = [ctypes.c_void_p]
    return lib


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        from .. import config
        if config.flag("MXNET_TPU_DISABLE_NATIVE"):
            return None
        try:
            path = _compile()
            if path:
                _lib = _bind(path)
        except OSError:
            _lib = None
    return _lib


# -- typed convenience wrappers (numpy in/out) ------------------------------

def scan_records(path):
    """Record (offset, length) table of a .rec file, or None if the native
    lib is unavailable. Raises IOError on corrupt framing."""
    L = lib()
    if L is None:
        return None
    n = L.mxio_scan_records(path.encode(), None, None, 0)
    if n < 0:
        raise IOError(f"corrupt recordio file: {path}")
    offsets = _np.zeros(n, _np.int64)
    lengths = _np.zeros(n, _np.int64)
    got = L.mxio_scan_records(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), n)
    if got != n:
        raise IOError(f"recordio file changed while scanning: {path}")
    return offsets, lengths


def read_records(path, offsets, lengths):
    """Gather records into a list of bytes objects (native chunk read)."""
    L = lib()
    if L is None:
        return None
    offsets = _np.ascontiguousarray(offsets, _np.int64)
    lengths = _np.ascontiguousarray(lengths, _np.int64)
    total = int(lengths.sum())
    buf = _np.zeros(total, _np.uint8)
    rc = L.mxio_read_records(
        path.encode(),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        len(offsets),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)))
    if rc != 0:
        raise IOError(f"recordio read failed: {path}")
    out, pos = [], 0
    for ln in lengths:
        out.append(buf[pos:pos + ln].tobytes())
        pos += int(ln)
    return out


def quantize_2bit(grad, residual, threshold):
    """Native packed 2-bit quantization; returns (packed_f32, residual) or
    None. `residual` is updated in place (must be float32 contiguous)."""
    L = lib()
    if L is None:
        return None
    grad = _np.ascontiguousarray(grad, _np.float32).ravel()
    # fresh residual buffer: the numpy fallback never mutates its input,
    # so the native path must not either
    residual = _np.array(residual, _np.float32)
    flat_res = residual.ravel()
    n = grad.size
    out = _np.zeros((n + 15) // 16, _np.uint32)
    L.mxio_quantize_2bit(
        grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        flat_res.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        n, threshold)
    return out.view(_np.float32), residual


def dequantize_2bit(packed, n, threshold):
    L = lib()
    if L is None:
        return None
    words = _np.ascontiguousarray(packed).view(_np.uint32)
    out = _np.zeros(n, _np.float32)
    L.mxio_dequantize_2bit(
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, threshold)
    return out


def hwc_u8_to_chw_f32(img, mean=None, std=None):
    """uint8 HWC image -> normalized float32 CHW (native loop), or None."""
    L = lib()
    if L is None:
        return None
    img = _np.ascontiguousarray(img, _np.uint8)
    h, w, c = img.shape
    out = _np.zeros((c, h, w), _np.float32)
    fptr = ctypes.POINTER(ctypes.c_float)
    mean_arr = None if mean is None else \
        _np.ascontiguousarray(mean, _np.float32)
    stdinv_arr = None if std is None else \
        _np.ascontiguousarray(1.0 / _np.asarray(std, _np.float32))
    L.mxio_hwc_u8_to_chw_f32(
        img.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        out.ctypes.data_as(fptr), h, w, c,
        mean_arr.ctypes.data_as(fptr) if mean_arr is not None else None,
        stdinv_arr.ctypes.data_as(fptr) if stdinv_arr is not None else None)
    return out


def has_jpeg():
    """True when the native lib was built with libjpeg (image pipeline)."""
    L = lib()
    return bool(L is not None and L.mxio_has_jpeg())


def jpeg_decode(data):
    """Decode JPEG bytes to an RGB uint8 HWC array, or None if the native
    decoder is unavailable. Raises ValueError on corrupt input."""
    L = lib()
    if L is None or not L.mxio_has_jpeg():
        return None
    buf = _np.frombuffer(data, _np.uint8)
    h = ctypes.c_long()
    w = ctypes.c_long()
    src = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))
    if L.mxio_jpeg_decode(src, len(buf), None, 0,
                          ctypes.byref(h), ctypes.byref(w)) != 0:
        raise ValueError("corrupt JPEG")
    if h.value * w.value > 64 * 1024 * 1024:
        raise ValueError(f"JPEG too large: {h.value}x{w.value} exceeds the "
                         "64MP native-decoder cap (decode with PIL/cv2)")
    out = _np.empty((h.value, w.value, 3), _np.uint8)
    if L.mxio_jpeg_decode(
            src, len(buf), out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            out.size, ctypes.byref(h), ctypes.byref(w)) != 0:
        raise ValueError("corrupt JPEG")
    return out


class NativeImagePipe:
    """Threaded C++ record->JPEG-decode->augment->batch pipeline
    (iter_image_recordio_2.cc role). Delivers batches in deterministic
    order for a fixed (seed, epoch order)."""

    def __init__(self, rec_path, offsets, lengths, batch, data_shape,
                 resize=0, rand_crop=False, rand_mirror=False, mean=None,
                 std=None, label_width=1, nthreads=4, depth=0, seed=0,
                 out_dtype="float32"):
        L = lib()
        if L is None or not L.mxio_has_jpeg():
            raise MXNetNativeUnavailable("native JPEG pipeline unavailable")
        c, h, w = data_shape
        self._lib = L
        self._batch = int(batch)
        self._shape = (int(c), int(h), int(w))
        self._label_width = int(label_width)
        if out_dtype not in ("float32", "uint8"):
            raise ValueError("out_dtype must be float32 or uint8")
        if out_dtype == "uint8" and (mean is not None or std is not None):
            # uint8 mode ships RAW bytes (4x less host->device traffic);
            # normalization belongs on-device then
            raise ValueError("uint8 output excludes host-side mean/std — "
                             "normalize on device instead")
        self._u8 = out_dtype == "uint8"
        offsets = _np.ascontiguousarray(offsets, _np.int64)
        lengths = _np.ascontiguousarray(lengths, _np.int64)
        P_L = ctypes.POINTER(ctypes.c_long)
        P_F = ctypes.POINTER(ctypes.c_float)
        def _per_channel(v, name):
            # C++ reads exactly `c` floats: broadcast scalars, reject other
            # lengths (a short array would read out of bounds)
            if v is None:
                return None
            arr = _np.asarray(v, _np.float32).ravel()
            if arr.size == 1:
                arr = _np.full(c, arr[0], _np.float32)
            elif arr.size != c:
                raise ValueError(f"{name} must be scalar or length {c}, "
                                 f"got {arr.size}")
            return _np.ascontiguousarray(arr)

        mean_arr = _per_channel(mean, "mean")
        std_arr = _per_channel(std, "std")
        stdinv_arr = None if std_arr is None else \
            _np.ascontiguousarray(1.0 / std_arr)
        self._handle = L.mxio_pipe_create(
            rec_path.encode(), offsets.ctypes.data_as(P_L),
            lengths.ctypes.data_as(P_L), len(offsets), self._batch,
            c, h, w, int(resize), int(bool(rand_crop)),
            int(bool(rand_mirror)),
            mean_arr.ctypes.data_as(P_F) if mean_arr is not None else None,
            stdinv_arr.ctypes.data_as(P_F)
            if stdinv_arr is not None else None,
            self._label_width, int(nthreads),
            # buffer-pool depth: each buffer is a full f32 batch (38MB at
            # batch 64 / 224^2), so default to the reference's
            # prefetch_buffer=4 rather than scaling with threads
            int(depth) or min(4, max(2, int(nthreads))), int(seed),
            int(self._u8))
        if not self._handle:
            raise MXNetNativeUnavailable("mxio_pipe_create failed")

    def reset(self, order):
        order = _np.ascontiguousarray(order, _np.int64)
        rc = self._lib.mxio_pipe_reset(
            self._handle,
            order.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), len(order))
        if rc != 0:
            raise IOError("mxio_pipe_reset failed")

    def next(self):
        """(data[b,c,h,w] f32, label[b,label_width] f32, pad) or None at
        epoch end. Raises IOError on decode/read errors."""
        c, h, w = self._shape
        data = _np.empty((self._batch, c, h, w),
                         _np.uint8 if self._u8 else _np.float32)
        label = _np.empty((self._batch, self._label_width), _np.float32)
        pad = ctypes.c_long()
        rc = self._lib.mxio_pipe_next(
            self._handle,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(pad))
        if rc == 1:
            return None
        if rc != 0:
            raise IOError("native image pipeline failed (bad record or "
                          "non-JPEG payload)")
        return data, label, int(pad.value)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.mxio_pipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MXNetNativeUnavailable(RuntimeError):
    """Raised when a native fast path cannot be used (no compiler/libjpeg)."""
