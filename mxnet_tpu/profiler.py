"""Profiler — chrome://tracing JSON emitter + aggregate op stats.

Parity target: src/profiler/profiler.h:87,437 (chrome-trace output,
aggregate stats) and python/mxnet/profiler.py:28,105 (`set_config`,
`set_state`, `dump`, `dumps`, pause/resume, Domain/Task/Counter/Marker).

TPU mapping (SURVEY.md §5): two complementary lanes.
  - The host-side op timeline here: when profiling is on, each imperative
    op / executor span is timed (blocking on its buffers, the role of the
    engine's profiling timestamps around ExecuteOprBlock,
    threaded_engine.cc:476) and emitted as a chrome-trace complete event.
  - The XLA/XPlane lane: `set_config(xplane_dir=...)` starts a
    jax.profiler trace on `set_state('run')` for TensorBoard-grade device
    timelines — the reference has no analog; it replaces nvprof.
Profiling perturbs async dispatch (ops are synchronized to be timed),
exactly like the reference's NaiveEngine-style profiling runs.
"""
from __future__ import annotations

import json
import os
import time
import threading

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "profiler_set_config", "profiler_set_state", "Domain", "Task",
           "Counter", "Marker", "Frame", "register_counter_export",
           "unregister_counter_export", "export_counters",
           "export_counter", "EventRing", "events_snapshot", "clear_events",
           "dropped_events", "set_max_events"]

_lock = threading.Lock()
_state = "stop"
_paused = False

# analysis/locklint annotation tables:
#  - Counter instances are handed to serving/telemetry code that ticks
#    them from request threads — locklint holds their writes to the
#    module _lock (see Counter.set_value/increment)
#  - _state/_paused/_xplane_active are control-plane toggles flipped from
#    the user's thread only (set_state/pause/resume are not request-path
#    APIs); readers tolerate a stale boolean for one event
__analysis_shared__ = {"Counter"}
__analysis_thread_safe__ = {"_state", "_paused", "_xplane_active"}


class EventRing:
    """Bounded chrome-event buffer with drop accounting.

    Shared by the profiler op lane and telemetry.tracing's span stream: a
    long-lived server or multi-day fit must not grow an unbounded _events
    list (the pre-ring behavior), so the ring keeps the most recent
    `capacity` events and counts what it evicted. All mutation happens
    under the module _lock (callers hold it), so the ring itself carries
    no lock.
    """

    def __init__(self, capacity):
        self._cap = max(1, int(capacity))
        from collections import deque
        self._dq = deque(maxlen=self._cap)
        self.dropped = 0          # evicted since last clear()
        self.total = 0            # appended since last clear()

    @property
    def capacity(self):
        return self._cap

    def append(self, ev):
        if len(self._dq) >= self._cap:
            self.dropped += 1
        self.total += 1
        self._dq.append(ev)

    def __len__(self):
        return len(self._dq)

    def snapshot(self):
        return list(self._dq)

    def clear(self):
        self._dq.clear()
        self.dropped = 0
        self.total = 0

    def set_capacity(self, capacity):
        from collections import deque
        self._cap = max(1, int(capacity))
        self._dq = deque(self._dq, maxlen=self._cap)


def _ring_capacity():
    try:
        return int(os.environ.get("MXNET_TRACE_MAX_EVENTS", "200000"))
    except ValueError:
        return 200000


_events = EventRing(_ring_capacity())   # chrome trace events (bounded ring)
_agg = {}               # name -> [count, total_us, min_us, max_us]
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": False,
    "continuous_dump": False,
    "xplane_dir": None,
}
_xplane_active = False


def set_config(**kwargs):
    """mx.profiler.set_config (python/mxnet/profiler.py:28)."""
    unknown = [k for k in kwargs if k not in _config]
    if unknown:
        raise ValueError(f"profiler.set_config: unknown options {unknown}")
    _config.update(kwargs)


profiler_set_config = set_config     # legacy alias (reference keeps both)


def is_running():
    return _state == "run" and not _paused


def imperative_enabled():
    """Gate for the per-imperative-op lane (profile_imperative flag)."""
    return is_running() and (_config["profile_all"] or
                             _config["profile_imperative"])


def symbolic_enabled():
    """Gate for executor Forward/Backward spans (profile_symbolic flag)."""
    return is_running() and (_config["profile_all"] or
                             _config["profile_symbolic"])


def set_state(state="stop", profile_process="worker"):
    """mx.profiler.set_state: 'run' | 'stop' (profiler.py:105)."""
    global _state, _xplane_active
    if state not in ("run", "stop"):
        raise ValueError("profiler state must be 'run' or 'stop'")
    prev = _state
    _state = state
    if state == "run" and prev != "run" and _config["xplane_dir"]:
        try:
            import jax
            jax.profiler.start_trace(_config["xplane_dir"])
            _xplane_active = True
        except Exception:
            _xplane_active = False
    if state == "stop" and _xplane_active:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
        _xplane_active = False
    if state == "stop" and prev == "run" and _config["continuous_dump"]:
        dump()


profiler_set_state = set_state


def pause(profile_process="worker"):
    global _paused
    _paused = True


def resume(profile_process="worker"):
    global _paused
    _paused = False


def _record_event(name, cat, ts_us, dur_us, pid=0, tid=None, args=None):
    ev = {"name": name, "cat": cat, "ph": "X", "ts": ts_us, "dur": dur_us,
          "pid": pid, "tid": tid if tid is not None else
          threading.get_ident() % 10000}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)
        st = _agg.get(name)
        if st is None:
            _agg[name] = [1, dur_us, dur_us, dur_us]
        else:
            st[0] += 1
            st[1] += dur_us
            st[2] = min(st[2], dur_us)
            st[3] = max(st[3], dur_us)


def _record_memory_counter():
    try:
        import jax
        live = sum(getattr(a, "nbytes", 0) for a in jax.live_arrays())
        with _lock:
            _events.append({"name": "live_device_bytes", "ph": "C",
                            "ts": time.perf_counter() * 1e6, "pid": 0,
                            "args": {"bytes": int(live)}})
    except Exception:
        pass


def _sync_result(out):
    import jax
    if isinstance(out, (list, tuple)):
        for o in out:
            _sync_result(o)
    elif hasattr(out, "wait_to_read"):       # NDArray
        out.wait_to_read()
    else:
        try:
            jax.block_until_ready(out)
        except Exception:
            pass


def profile_op(name, run):
    """Time `run()` (a thunk returning jax arrays or NDArrays),
    synchronizing so the span covers device execution — the engine-profiling
    role."""
    t0 = time.perf_counter()
    out = run()
    _sync_result(out)
    dur = (time.perf_counter() - t0) * 1e6
    _record_event(name, "operator", t0 * 1e6, dur)
    if _config["profile_memory"]:
        _record_memory_counter()
    return out


# -- counter export hooks ---------------------------------------------------
# Subsystems with their own live counters (e.g. mxnet_tpu.serving.metrics,
# mxnet_tpu.amp's amp_scale/amp_skipped_steps/amp_cast_bytes_saved) register
# a snapshot callable here; export_counters() merges every registered
# snapshot into one dict, and dump() embeds it in the trace file so a single
# profile JSON carries both the timeline and the counters.
_counter_exports = {}


def register_counter_export(name, fn):
    """Register `fn() -> dict` under `name`. Re-registering a name
    replaces the previous hook (latest owner wins)."""
    if not callable(fn):
        raise ValueError("register_counter_export: fn must be callable")
    with _lock:
        _counter_exports[name] = fn


def unregister_counter_export(name):
    with _lock:
        _counter_exports.pop(name, None)


def export_counter(name):
    """Snapshot ONE registered hook (or None): lets a consumer poll a
    single subsystem (telemetry.StepLogger reads "checkpoint" per step)
    without triggering every other hook's snapshot cost."""
    with _lock:
        fn = _counter_exports.get(name)
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:                           # pragma: no cover
        return {"error": f"{type(e).__name__}: {e}"}


def export_counters(format="dict"):
    """Snapshot every registered counter hook: {name: fn()}.
    A hook that raises is reported as {"error": ...} rather than taking
    the export down (serving keeps running while being observed)."""
    with _lock:
        hooks = list(_counter_exports.items())
    out = {}
    for name, fn in hooks:
        try:
            out[name] = fn()
        except Exception as e:                       # pragma: no cover
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    if format == "json":
        return json.dumps(out)
    return out


def events_snapshot():
    """Thread-safe snapshot of the buffered chrome events (tracing.dump
    builds per-rank trace shards from this without draining the ring)."""
    with _lock:
        return _events.snapshot()


def clear_events():
    with _lock:
        _events.clear()


def dropped_events():
    """Events evicted from the bounded ring since the last clear."""
    with _lock:
        return _events.dropped


def set_max_events(capacity):
    """Resize the shared event ring (MXNET_TRACE_MAX_EVENTS at import)."""
    with _lock:
        _events.set_capacity(capacity)


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace JSON (chrome://tracing / perfetto loadable)."""
    with _lock:
        trace = {"traceEvents": _events.snapshot(), "displayTimeUnit": "ms",
                 "metadata": {"dropped_events": _events.dropped,
                              "total_events": _events.total}}
    counters = export_counters()
    if counters:
        trace["counters"] = counters
    path = _config["filename"]
    with open(path, "w") as f:
        json.dump(trace, f)
    if finished:
        with _lock:
            _events.clear()
    return path


def dumps(reset=False, format="table"):
    """Aggregate per-op stats (profiler.h aggregate_stats role)."""
    with _lock:
        rows = [(name, st[0], st[1], st[1] / st[0], st[2], st[3])
                for name, st in sorted(_agg.items(),
                                       key=lambda kv: -kv[1][1])]
        if reset:
            _agg.clear()
    if format == "json":
        return json.dumps([{"name": r[0], "count": r[1], "total_us": r[2],
                            "avg_us": r[3], "min_us": r[4], "max_us": r[5]}
                           for r in rows])
    lines = [f"{'Name':<40}{'Count':>8}{'Total(us)':>14}{'Avg(us)':>12}"
             f"{'Min(us)':>12}{'Max(us)':>12}"]
    for r in rows:
        lines.append(f"{r[0]:<40}{r[1]:>8}{r[2]:>14.1f}{r[3]:>12.1f}"
                     f"{r[4]:>12.1f}{r[5]:>12.1f}")
    return "\n".join(lines)


# -- user-facing profiling objects (profiler.py Domain/Task/Counter etc.) ---

class Domain:
    def __init__(self, name):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class Task:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            dur = (time.perf_counter() - self._t0) * 1e6
            _record_event(self.name, f"task:{self.domain.name}",
                          self._t0 * 1e6, dur)
            self._t0 = None

    __enter__ = lambda self: (self.start(), self)[1]

    def __exit__(self, *exc):
        self.stop()
        return False


Frame = Task       # Frame has identical mechanics in the reference


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain = domain
        self.name = name
        self.value = 0
        if value is not None:
            self.set_value(value)

    def set_value(self, value):
        with _lock:
            self.value = value
            self._record(value)

    def _record(self, value):
        # call with _lock held. gate on is_running() like spans do:
        # long-lived counters (serving queue depth/shed) tick on every
        # request, and recording while stopped/paused grew _events
        # without bound on a server that never profiles
        if not is_running():
            return
        _events.append({"name": self.name, "ph": "C",
                        "ts": time.perf_counter() * 1e6, "pid": 0,
                        "args": {self.name: value}})

    def increment(self, delta=1):
        # read-modify-write under the lock: counters tick concurrently
        # from serving request threads, and a bare += loses updates
        with _lock:
            self.value = self.value + delta
            self._record(self.value)

    def decrement(self, delta=1):
        self.increment(-delta)


class Marker:
    def __init__(self, domain, name):
        self.domain = domain
        self.name = name

    def mark(self, scope="process"):
        if not is_running():            # same gate as spans/counters
            return
        with _lock:
            _events.append({"name": self.name, "ph": "i",
                            "ts": time.perf_counter() * 1e6, "pid": 0,
                            "s": "p" if scope == "process" else "t"})
