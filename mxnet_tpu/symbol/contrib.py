"""mx.sym.contrib namespace — symbolic twins of mx.nd.contrib.

Mirrors the reference's `_init_op_module('mxnet', 'symbol', ...)` contrib
sub-namespace (python/mxnet/symbol/register.py:202).
"""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry

_PREFIX = "_contrib_"


def __getattr__(name):
    from . import __getattr__ as _sym_getattr  # late: avoid import cycle
    full = _PREFIX + name
    if full in _registry._REGISTRY:
        fn = _sym_getattr(full)
    elif name in _registry._REGISTRY:
        fn = _sym_getattr(name)
    else:
        raise AttributeError(f"module 'mxnet_tpu.symbol.contrib' has no "
                             f"attribute {name!r}")
    setattr(_sys.modules[__name__], name, fn)
    return fn
