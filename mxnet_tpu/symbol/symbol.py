"""Symbol — the declarative graph IR.

Parity target: python/mxnet/symbol/symbol.py + the nnvm graph the reference
builds underneath (SURVEY.md §2.4, §3.4). A Symbol is a list of output entries
(node, out_index) over a DAG of _Node objects. Unlike the reference there is no
C++ graph object: the graph *is* the lowering input — `bind` walks it once to
emit a single jax function that XLA compiles whole (the analog of
GraphExecutor::Init's pass pipeline, graph_executor.cc:513-609, replaced by
jaxpr→StableHLO→XLA).

Missing op inputs auto-create variables named `{opname}_{input}` exactly like
the reference's symbol composition, so `simple_bind` finds fc1_weight etc.
"""
from __future__ import annotations

import json

from ..base import MXNetError, AttrScope, NameManager, attr_to_string
from ..ops.registry import get_op

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "user_attrs")

    def __init__(self, op, name, attrs, inputs, user_attrs=None):
        self.op = op            # OpSchema or None for variables
        self.name = name
        self.attrs = attrs      # raw kwargs (parsed lazily per use)
        self.inputs = inputs    # list of (node, out_idx)
        self.user_attrs = user_attrs or {}

    def num_outputs(self):
        if self.op is None:
            return 1
        parsed = self.op.parse_attrs(self.attrs)
        n = self.op.num_outputs
        return n(parsed) if callable(n) else n


class Symbol:
    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(node, idx)]

    # -- introspection ------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def _topo(self):
        order, seen = [], set()

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for (n2, _) in node.inputs:
                visit(n2)
            order.append(node)

        for (n, _) in self._outputs:
            visit(n)
        return order

    def _input_vars(self):
        """All variable nodes in topo order, split into (args, aux)."""
        args, aux = [], []
        seen = set()
        for node in self._topo():
            if node.op is not None:
                parsed = node.op.parse_attrs(node.attrs)
                aux_set = set(node.op.aux_indices)
                for i, (n2, _) in enumerate(node.inputs):
                    if n2.op is None and id(n2) not in seen and i in aux_set:
                        seen.add(id(n2))
                        aux.append(n2)
        for node in self._topo():
            if node.op is None and id(node) not in seen:
                seen.add(id(node))
                args.append(node)
        return args, aux

    def list_arguments(self):
        args, _ = self._input_vars()
        return [n.name for n in args]

    def list_auxiliary_states(self):
        _, aux = self._input_vars()
        return [n.name for n in aux]

    def list_outputs(self):
        names = []
        for (node, idx) in self._outputs:
            if node.op is None:
                names.append(node.name)
            elif node.num_outputs() == 1:
                names.append(f"{node.name}_output")
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    @property
    def num_outputs(self):
        return len(self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(f"no output named {index!r}")
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def get_internals(self):
        outs = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                outs.append((node, i))
        return Symbol(outs)

    def get_children(self):
        kids = []
        for (node, _) in self._outputs:
            kids.extend(node.inputs)
        return Symbol(kids) if kids else None

    def attr(self, key):
        node = self._outputs[0][0]
        return node.user_attrs.get(key)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node.user_attrs:
                out[node.name] = dict(node.user_attrs)
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0].user_attrs.update(
            {k: str(v) for k, v in kwargs.items()})

    def __repr__(self):
        name = self.name
        return f"<Symbol {name if name else 'Grouped'}>"

    # -- composition --------------------------------------------------------
    def __call__(self, *args, **kwargs):
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        """Replace variable placeholders with provided symbols by name."""
        name_map = {}
        if args:
            arg_names = self.list_arguments()
            for n, a in zip(arg_names, args):
                name_map[n] = a
        name_map.update(kwargs)
        mapping = {}
        for node in self._topo():
            if node.op is None and node.name in name_map:
                repl = name_map[node.name]
                mapping[id(node)] = repl._outputs[0]

        def rewrite(node, memo):
            if id(node) in memo:
                return memo[id(node)]
            if id(node) in mapping:
                memo[id(node)] = mapping[id(node)][0]
                return mapping[id(node)][0]
            new_inputs = [(rewrite(n2, memo), i2) for (n2, i2) in node.inputs]
            node.inputs = new_inputs
            memo[id(node)] = node
            return node

        memo = {}
        self._outputs = [(rewrite(n, memo), i) for (n, i) in self._outputs]

    def __copy__(self):
        # nodes are shared; Symbol copy is a new output list (reference
        # symbols are immutable handles, compose copies)
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return self.__copy__()

    # -- arithmetic sugar ---------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        from . import _create_symbol
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create_symbol(op, [a, b], {})
        if isinstance(other, (int, float, bool)):
            if reverse:
                rmap = {"_plus_scalar": "_plus_scalar",
                        "_minus_scalar": "_rminus_scalar",
                        "_mul_scalar": "_mul_scalar",
                        "_div_scalar": "_rdiv_scalar",
                        "_power_scalar": "_rpower_scalar",
                        "_mod_scalar": "_rmod_scalar"}
                scalar_op = rmap.get(scalar_op, scalar_op)
            return _create_symbol(scalar_op, [self], {"scalar": float(other)})
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar",
                           reverse=True)

    def __neg__(self):
        return self._binop(-1.0, None, "_mul_scalar")

    # ordering comparisons (eq/ne intentionally left to identity semantics —
    # Symbols must stay hashable dict keys, matching the reference)
    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal",
                           "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal",
                           "_lesser_equal_scalar")

    def __getattr__(self, name):
        # symbol method sugar: sym.reshape(...), sym.sum(...) etc
        if name.startswith("_"):
            raise AttributeError(name)
        from . import _SYM_FUNCS
        fn = _SYM_FUNCS.get(name)
        if fn is None:
            raise AttributeError(name)
        def method(*args, **kwargs):
            return fn(self, *args, **kwargs)
        return method

    # -- inference ----------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        known = {}
        if args:
            for n, s in zip(self.list_arguments(), args):
                if s is not None:
                    known[n] = tuple(s)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        shapes = {}  # id(node) -> list of out shapes (or None)
        var_shape = {}  # id(var node) -> shape

        topo = self._topo()
        for _ in range(3):  # fixed-point: weight fills can cascade
            changed = False
            for node in topo:
                if node.op is None:
                    s = var_shape.get(id(node)) or known.get(node.name)
                    if s is not None and shapes.get(id(node)) != [tuple(s)]:
                        shapes[id(node)] = [tuple(s)]
                        var_shape[id(node)] = tuple(s)
                        changed = True
                    elif id(node) not in shapes:
                        shapes[id(node)] = [None]
                    continue
                in_shapes = []
                for (n2, i2) in node.inputs:
                    s2 = shapes.get(id(n2))
                    in_shapes.append(s2[i2] if s2 and i2 < len(s2) else None)
                parsed = node.op.parse_attrs(node.attrs)
                out = None
                if node.op.infer_shape is not None:
                    filled, out = node.op.infer_shape(parsed, list(in_shapes))
                    for (n2, i2), fs in zip(node.inputs, filled):
                        if fs is not None and n2.op is None and \
                                var_shape.get(id(n2)) is None:
                            var_shape[id(n2)] = tuple(fs)
                            changed = True
                    in_shapes = filled
                if (out is None or any(o is None for o in out)) and \
                        all(s is not None for s in in_shapes):
                    out = _eval_shape(node, parsed, in_shapes)
                if out is not None and shapes.get(id(node)) != out:
                    shapes[id(node)] = out
                    changed = True
                elif id(node) not in shapes:
                    shapes[id(node)] = [None] * node.num_outputs()
            if not changed:
                break

        args_n, aux_n = self._input_vars()
        arg_shapes = [var_shape.get(id(n)) for n in args_n]
        aux_shapes = [var_shape.get(id(n)) for n in aux_n]
        out_shapes = []
        for (node, idx) in self._outputs:
            s = shapes.get(id(node))
            out_shapes.append(s[idx] if s and idx < len(s) else None)
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [n.name for n, s in zip(args_n, arg_shapes) if s is None]
            raise MXNetError(
                f"infer_shape: incomplete — cannot infer {missing}; "
                f"provide more input shapes")
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        import numpy as _np
        known = {}
        if args:
            for n, t in zip(self.list_arguments(), args):
                if t is not None:
                    known[n] = _np.dtype(t)
        known.update({k: _np.dtype(v) for k, v in kwargs.items()
                      if v is not None})
        default = _np.dtype("float32")
        args_n, aux_n = self._input_vars()
        arg_types = [known.get(n.name, default) for n in args_n]
        aux_types = [known.get(n.name, default) for n in aux_n]
        out_types = [default for _ in self._outputs]
        return arg_types, out_types, aux_types

    # -- serialization ------------------------------------------------------
    def tojson(self):
        topo = self._topo()
        nid = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            entry = {
                "op": "null" if n.op is None else n.op.name,
                "name": n.name,
                "inputs": [[nid[id(n2)], i2, 0] for (n2, i2) in n.inputs],
            }
            attrs = {k: attr_to_string(v) for k, v in n.attrs.items()
                     if v is not None}
            attrs.update(n.user_attrs)
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": [i for i, n in enumerate(topo) if n.op is None],
            "heads": [[nid[id(n)], i, 0] for (n, i) in self._outputs],
            "attrs": {"mxnet_tpu_version": "0.1.0"},
        }, indent=2)

    def save(self, fname):
        from ..base import atomic_write
        atomic_write(fname, self.tojson())

    # -- binding ------------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, mesh=None,
                    sharded_args=(), **kwargs):
        from ..executor import Executor
        return Executor._simple_bind(self, ctx, grad_req, type_dict, kwargs,
                                     mesh=mesh, sharded_args=sharded_args,
                                     group2ctx=group2ctx)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor
        return Executor._bind(self, ctx, args, args_grad, grad_req, aux_states,
                              group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def grad(self, wrt):
        raise MXNetError("Symbol.grad: use simple_bind + backward (the "
                         "reference's symbolic-grad helper is deprecated)")

    # -- misc parity helpers -------------------------------------------------
    def debug_str(self):
        lines = []
        for n in self._topo():
            op = "Variable" if n.op is None else n.op.name
            ins = ", ".join(f"{n2.name}[{i2}]" for (n2, i2) in n.inputs)
            lines.append(f"{op:>20s}  {n.name}({ins})")
        return "\n".join(lines)


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    if not isinstance(name, str):
        raise TypeError("Variable name must be a string")
    user_attrs = AttrScope.current().get(attr)
    if shape is not None:
        user_attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        user_attrs["__dtype__"] = str(dtype)
    if lr_mult is not None:
        user_attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        user_attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        user_attrs["__init__"] = init if isinstance(init, str) else \
            init.dumps()
    node = _Node(None, name, {}, [], user_attrs)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


# hidden node attrs the reference's C API strips/renames on save+load
# (c_api_symbolic.cc:40-42 kHiddenKeys)
_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
                "mirror_stage")
_CURRENT_REF_VERSION = 10100    # the reference fork is MXNet ~1.1.0


def _upgrade_legacy_json(data):
    """Upgrade reference-era symbol JSON in place so old model files keep
    loading (role of src/nnvm/legacy_json_util.cc:1-228 + the kHiddenKeys
    handling in c_api_symbolic.cc). Files written by THIS repo
    (mxnet_tpu_version graph attr) pass through untouched. Applied
    passes, mirroring the reference's upgrader_list (:187-193):

    - FixParsing (any reference version): raw hidden keys on op nodes
      become `__key__` user attrs; `{arg}_{key}` forms move onto the
      matching input variable (legacy_json_util.cc:49-110)
    - 0.8->0.9: aux variables were not stored — append the missing input
      variables, named `{node_name}_{arg_name}`
      (legacy_json_util.cc:134-151)
    - 0.9.4->0.9.5: argmin/argmax axis=-1 meant "flatten" — drop the
      attr to recover the default (legacy_json_util.cc:173-184)
    """
    import logging
    graph_attrs = data.get("attrs", {})
    if "mxnet_tpu_version" in graph_attrs:
        return data
    ver = graph_attrs.get("mxnet_version")
    if isinstance(ver, (list, tuple)):     # nnvm graph-attr form ["int", N]
        ver = ver[-1]
    # aux-in-json arrived in 0.9.0 (the reference assumes 0.8.0 when the
    # version attr is absent, legacy_json_util.cc:198)
    ver = int(ver) if ver is not None else 800
    if ver > _CURRENT_REF_VERSION:
        logging.info(
            "Warning: loading symbol saved by MXNet version %d with this "
            "framework's reference parity at v%d. May cause undefined "
            "behavior.", ver, _CURRENT_REF_VERSION)
    elif ver < _CURRENT_REF_VERSION:
        logging.info(
            "Loading symbol saved by previous version v%d.%d.%d. "
            "Attempting to upgrade...", ver // 10000, (ver // 100) % 100,
            ver % 100)

    nodes = data["nodes"]
    arg_nodes = set(data.get("arg_nodes", ()))

    def _attrs(entry):
        return entry.setdefault("attrs", entry.pop("param", None) or {})

    # -- FixParsing: hidden keys --------------------------------------------
    for entry in nodes:
        attrs = _attrs(entry)
        if entry["op"] == "null":
            for key in _HIDDEN_KEYS:
                if key in attrs:
                    attrs[f"__{key}__"] = attrs.pop(key)
            continue
        try:
            in_names = get_op(entry["op"]).input_names
        except MXNetError:
            in_names = []
        for k in list(attrs):
            for key in _HIDDEN_KEYS:
                if k == key:
                    attrs[f"__{key}__"] = attrs.pop(k)
                    break
                if k.endswith("_" + key):
                    arg = k[:-(len(key) + 1)]
                    if arg in in_names:
                        idx = in_names.index(arg)
                        if idx < len(entry["inputs"]):
                            tgt = nodes[entry["inputs"][idx][0]]
                            if tgt["op"] == "null":
                                _attrs(tgt)[f"__{key}__"] = attrs.pop(k)
                    if k in attrs:
                        # unrelocatable (aux input not yet materialized /
                        # non-variable input): keep the data as a HIDDEN
                        # attr — left raw it would reach parse_attrs and
                        # fail the load as an unknown op param
                        attrs[f"__{k}__"] = attrs.pop(k)
                    break

    # -- 0.8 -> 0.9: materialize missing aux-variable inputs ----------------
    if ver < 900:
        # new variables must precede their consumer (the node list is
        # topo-ordered), so rebuild the list with an index remap
        pending = {}        # consumer old-id -> [new var entries]
        n_new = 0
        for j, entry in enumerate(nodes):
            if entry["op"] == "null":
                continue
            try:
                schema = get_op(entry["op"])
            except MXNetError:
                continue
            in_names = schema.input_names
            missing = range(len(entry["inputs"]), len(in_names))
            # ONLY aux states were unstored pre-0.9; a short input list
            # from an optional input (no_bias FullyConnected) must NOT
            # grow a phantom bias variable
            if not missing or not all(i in schema.aux_indices
                                      for i in missing):
                continue
            for i in missing:
                name = f"{entry['name']}_{in_names[i]}" \
                    if entry["name"] else in_names[i]
                var = {"op": "null", "name": name, "inputs": []}
                pending.setdefault(j, []).append(var)
                n_new += 1
                entry["inputs"].append([("new", id(var)), 0, 0])
        if n_new:
            new_nodes, remap = [], {}
            for j, entry in enumerate(nodes):
                for var in pending.get(j, ()):
                    remap[("new", id(var))] = len(new_nodes)
                    new_nodes.append(var)
                remap[j] = len(new_nodes)
                new_nodes.append(entry)
            for entry in new_nodes:
                entry["inputs"] = [[remap[i], k, *rest] for (i, k, *rest)
                                   in entry["inputs"]]
            arg_nodes = {remap[i] for i in arg_nodes} | {
                i for i, e in enumerate(new_nodes) if e["op"] == "null"}
            data["heads"] = [[remap[i], k, *rest] for (i, k, *rest)
                             in data.get("heads", [])]
            data["nodes"] = nodes = new_nodes

    # -- 0.9.4 -> 0.9.5: argmin/argmax axis flag change ---------------------
    if ver < 905:
        for entry in nodes:
            if entry["op"] in ("argmin", "argmax") and \
                    _attrs(entry).get("axis") == "-1":
                del entry["attrs"]["axis"]

    data["arg_nodes"] = sorted(arg_nodes)
    return data


def load_json(json_str):
    data = _upgrade_legacy_json(json.loads(json_str))
    nodes = []
    for entry in data["nodes"]:
        attrs = dict(entry.get("attrs", entry.get("param", {})))
        user_attrs = {k: v for k, v in attrs.items() if k.startswith("__")}
        op_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}
        if entry["op"] == "null":
            node = _Node(None, entry["name"], {}, [], user_attrs)
        else:
            schema = get_op(entry["op"])
            inputs = [(nodes[i], j) for (i, j, *_k) in entry["inputs"]]
            node = _Node(schema, entry["name"], op_attrs, inputs, user_attrs)
        nodes.append(node)
    heads = data.get("heads") or [[len(nodes) - 1, 0, 0]]
    return Symbol([(nodes[i], j) for (i, j, *_k) in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def _eval_shape(node, parsed, in_shapes):
    """Forward-only shape inference via jax.eval_shape on the fcompute."""
    import jax
    import numpy as _np
    from ..ops.registry import OpCtx

    specs = [jax.ShapeDtypeStruct(tuple(s), _np.float32) for s in in_shapes]

    def f(*xs):
        octx = OpCtx(is_train=False, rng=None)
        if node.op.needs_rng:
            octx = OpCtx(is_train=False, rng=jax.random.PRNGKey(0))
        return node.op.fcompute(parsed, octx, *xs)

    try:
        out = jax.eval_shape(f, *specs)
    except Exception:
        return None
    if not isinstance(out, tuple):
        out = (out,)
    return [tuple(o.shape) for o in out[:node.num_outputs()]]
