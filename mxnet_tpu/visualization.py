"""Network visualization — plot_network (graphviz) + print_summary.

Parity target: python/mxnet/visualization.py (SURVEY.md §2.4 misc).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["plot_network", "print_summary"]


def _node_label(node):
    op = node.op.name if node.op is not None else "Variable"
    label = f"{node.name}\n{op}"
    for k in ("kernel", "num_filter", "num_hidden", "act_type", "pool_type"):
        v = node.attrs.get(k)
        if v is not None:
            label += f"\n{k}={v}"
    return label


_OP_COLORS = {
    "Convolution": "#fb8072", "Deconvolution": "#fb8072",
    "FullyConnected": "#fb8072",
    "BatchNorm": "#bebada", "LayerNorm": "#bebada",
    "Activation": "#ffffb3", "LeakyReLU": "#ffffb3",
    "Pooling": "#80b1d3",
    "Concat": "#fdb462", "Flatten": "#fdb462", "Reshape": "#fdb462",
    "SoftmaxOutput": "#b3de69", "softmax": "#b3de69",
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz.Digraph of the symbol (visualization.py
    plot_network). Requires the optional `graphviz` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError(
            "plot_network requires the python graphviz package") from e

    node_attrs = {"shape": "box", "fixedsize": "false",
                  **(node_attrs or {})}
    dot = Digraph(name=title, format=save_format)
    topo = symbol._topo()
    nid = {id(n): f"node{i}" for i, n in enumerate(topo)}

    def is_param(n):
        return n.op is None and (n.name.endswith(("_weight", "_bias",
                                                  "_gamma", "_beta",
                                                  "_moving_mean",
                                                  "_moving_var",
                                                  "_running_mean",
                                                  "_running_var")))

    for n in topo:
        if hide_weights and is_param(n):
            continue
        attrs = dict(node_attrs)
        if n.op is None:
            attrs.update(style="filled", fillcolor="#8dd3c7")
        else:
            attrs.update(style="filled",
                         fillcolor=_OP_COLORS.get(n.op.name, "#d9d9d9"))
        dot.node(nid[id(n)], label=_node_label(n), **attrs)
    for n in topo:
        if hide_weights and is_param(n):
            continue
        for (src, _) in n.inputs:
            if hide_weights and is_param(src):
                continue
            dot.edge(nid[id(src)], nid[id(n)])
    return dot


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-table summary with output shapes + parameter counts
    (visualization.py print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    shape_map = {}
    if shape is not None:
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        args, aux = symbol._input_vars()
        for n, s in zip(args, arg_shapes):
            shape_map[n.name] = s
        for n, s in zip(aux, aux_shapes):
            shape_map[n.name] = s

    def out_shape_of(node):
        if shape is None:
            return ""
        try:
            sub = __import__("mxnet_tpu").symbol.Symbol([(node, 0)])
            _, outs, _ = sub.infer_shape_partial(**shape)
            return str(outs[0]) if outs and outs[0] else ""
        except MXNetError:
            return ""

    def prod(s):
        p = 1
        for d in s:
            p *= d
        return p

    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    lines = ["_" * line_length]
    row = ""
    for f, p in zip(fields, positions):
        row = (row + f).ljust(p)
    lines.append(row)
    lines.append("=" * line_length)

    total = 0
    for node in symbol._topo():
        if node.op is None:
            continue
        params = 0
        for (src, _) in node.inputs:
            if src.op is None and src.name in shape_map and \
                    not src.name.startswith("data") and \
                    src.name not in ("data", "softmax_label", "label"):
                params += prod(shape_map[src.name])
        total += params
        prev = ",".join(s.name for (s, _) in node.inputs if s.op is not None)
        if not prev:
            prev = ",".join(s.name for (s, _) in node.inputs)
        cols = [f"{node.name} ({node.op.name})", out_shape_of(node),
                str(params), prev]
        row = ""
        for c, p in zip(cols, positions):
            row = (row + c).ljust(p)
        lines.append(row)
        lines.append("_" * line_length)
    lines.append(f"Total params: {total}")
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out
