"""RNN data iterators — bucketed sentence batching.

Parity surface: python/mxnet/rnn/io.py (BucketSentenceIter,
encode_sentences), feeding BucketingModule with per-bucket fixed shapes —
the TPU-honest answer to variable sequence length (SURVEY.md §5): one
compiled program per bucket length instead of dynamic shapes.

Own design: sentences are binned once into dense per-bucket matrices
(vectorized padding), language-model labels are the data shifted left by
one, and the epoch is a shuffled list of (bucket, row-offset) batch
cursors.
"""
from __future__ import annotations

import logging
import random as _pyrandom

import numpy as np

from ..base import MXNetError
from ..io import DataIter, DataBatch, DataDesc
from ..ndarray.ndarray import array

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Map token sequences to int id sequences, growing `vocab` when it was
    not supplied. Unknown words either extend the vocab (building mode),
    map to `unknown_token`, or error."""
    building = vocab is None
    if building:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sentence in sentences:
        ids = []
        for word in sentence:
            if word not in vocab:
                if not building and not unknown_token:
                    raise MXNetError(f"unknown token {word!r} and no "
                                     "unknown_token fallback")
                if unknown_token:
                    word = unknown_token
                if word not in vocab:
                    if next_id == invalid_label:
                        next_id += 1
                    vocab[word] = next_id
                    next_id += 1
            ids.append(vocab[word])
        encoded.append(ids)
    return encoded, vocab


def _auto_buckets(lengths, batch_size):
    """One bucket per sentence length that has at least a full batch."""
    counts = np.bincount(lengths)
    return [int(ln) for ln in np.nonzero(counts >= batch_size)[0] if ln > 0]


class BucketSentenceIter(DataIter):
    """Iterate fixed-shape batches of padded sentences, bucketed by length.

    Layout 'NT' yields (batch, time); 'TN' yields (time, batch). Labels are
    the next-token shift of the data (language-model convention).
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if layout not in ("NT", "TN"):
            raise MXNetError(f"layout must be 'NT' or 'TN', got {layout!r}")
        lengths = [len(s) for s in sentences]
        if not buckets:
            buckets = _auto_buckets(lengths, batch_size)
        self.buckets = sorted(buckets)
        if not self.buckets:
            raise MXNetError("no buckets: provide `buckets` explicitly")

        # bin sentences: smallest bucket that fits; overflow is dropped
        per_bucket = [[] for _ in self.buckets]
        dropped = 0
        for sent in sentences:
            slot = int(np.searchsorted(self.buckets, len(sent)))
            if slot == len(self.buckets):
                dropped += 1
                continue
            per_bucket[slot].append(sent)
        if dropped:
            logging.warning("BucketSentenceIter: dropped %d sentences "
                            "longer than the largest bucket", dropped)
        # dense padded matrix per bucket
        self._bucket_data = []
        for width, sents in zip(self.buckets, per_bucket):
            mat = np.full((len(sents), width), invalid_label, dtype=dtype)
            for r, sent in enumerate(sents):
                mat[r, :len(sent)] = sent
            self._bucket_data.append(mat)

        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(self.buckets)
        shape = (batch_size, self.default_bucket_key) \
            if layout == "NT" else (self.default_bucket_key, batch_size)
        self.provide_data = [DataDesc(data_name, shape, layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, layout=layout)]

        self._cursors = []
        self._pos = 0
        self.reset()

    def _shift_labels(self, mat):
        lab = np.roll(mat, -1, axis=1)
        lab[:, -1] = self.invalid_label
        return lab

    def reset(self):
        self._pos = 0
        for mat in self._bucket_data:
            np.random.shuffle(mat)
        self._labels = [self._shift_labels(m) for m in self._bucket_data]
        self._cursors = [
            (b, row)
            for b, mat in enumerate(self._bucket_data)
            for row in range(0, len(mat) - self.batch_size + 1,
                             self.batch_size)]
        _pyrandom.shuffle(self._cursors)

    def next(self):
        if self._pos >= len(self._cursors):
            raise StopIteration
        b, row = self._cursors[self._pos]
        self._pos += 1
        data = self._bucket_data[b][row:row + self.batch_size]
        label = self._labels[b][row:row + self.batch_size]
        if self.layout == "TN":
            data, label = data.T, label.T
        data, label = array(data), array(label)
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[b],
            provide_data=[DataDesc(self.data_name, data.shape,
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, label.shape,
                                    layout=self.layout)])
