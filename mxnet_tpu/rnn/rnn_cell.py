"""Symbolic RNN cells.

Parity target: python/mxnet/rnn/rnn_cell.py (978 LoC; SURVEY.md §2.4):
`BaseRNNCell.unroll` (:295), LSTM/GRU/RNN cells (:408), `FusedRNNCell`
(:536) wrapping the fused RNN op, residual/bidirectional/dropout/zoneout
modifiers. Cells compose Symbols; executors compile the unrolled graph
whole (bucketing compiles one executable per sequence length).
"""
from __future__ import annotations

from .. import symbol
from ..base import MXNetError
from ..ops.rnn_ops import rnn_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "ModifierCell"]


class RNNParams:
    """Container for holding variables (rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified, \
            "After applying modifier cells (e.g. DropoutCell) the base " \
            "cell cannot be called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is None:
                state = func(name=f"{self._prefix}begin_state_"
                             f"{self._init_counter}", **kwargs)
            else:
                kwargs.update(info)
                state = func(name=f"{self._prefix}begin_state_"
                             f"{self._init_counter}", **kwargs)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split fused parameter blobs into per-gate weights."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ["i2h", "h2h"]:
            weight = args.pop(f"{self._prefix}{group_name}_weight")
            bias = args.pop(f"{self._prefix}{group_name}_bias")
            for j, gate in enumerate(self._gate_names):
                wname = f"{self._prefix}{group_name}{gate}_weight"
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = f"{self._prefix}{group_name}{gate}_bias"
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        from ..ndarray.ndarray import concatenate
        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ["i2h", "h2h"]:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = f"{self._prefix}{group_name}{gate}_weight"
                weight.append(args.pop(wname))
                bname = f"{self._prefix}{group_name}{gate}_bias"
                bias.append(args.pop(bname))
            args[f"{self._prefix}{group_name}_weight"] = concatenate(weight)
            args[f"{self._prefix}{group_name}_bias"] = concatenate(bias)
        return args

    def _begin_state_like(self, first_input):
        """Symbolic zero states with batch size derived from the input
        symbol (static-shape realization of the reference's 0-unknown
        begin_state shapes)."""
        return [symbol._cell_state_zeros(first_input,
                                         dim=info["shape"][-1])
                for info in self.state_info]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell over `length` steps (rnn_cell.py:295)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._begin_state_like(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            assert len(inputs.list_outputs()) == 1, \
                "unroll doesn't allow grouped symbol as input. Please " \
                "convert to list with list(inputs) first or let unroll " \
                "handle splitting."
            inputs = list(symbol.SliceChannel(inputs, axis=in_axis,
                                              num_outputs=length,
                                              squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [i.expand_dims(axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden,
                                    name=f"{name}h2h")
        output = self._get_activation(i2h + h2h, self._activation,
                                      name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias
        self._iB = self.params.get("i2h_bias",
                                   init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name=f"{name}h2h")
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4, axis=-1,
                                          name=f"{name}slice")
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh")
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name=f"{name}i2h")
        h2h = symbol.FullyConnected(prev_state_h, self._hW, self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name=f"{name}h2h")
        i2h_s = symbol.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_s = symbol.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset_gate = symbol.Activation(i2h_s[0] + h2h_s[0],
                                       act_type="sigmoid")
        update_gate = symbol.Activation(i2h_s[1] + h2h_s[1],
                                        act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_s[2] + reset_gate * h2h_s[2],
                                       act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the RNN op (rnn_cell.py:536; the
    cuDNN path's role — here the lax.scan fused op)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = 2 if bidirectional else 1
        self._parameter = self.params.get("parameters")

    @property
    def state_info(self):
        b = self._num_layers * self._directions
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:  # NTC -> TNC for the op
            inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        if begin_state is None:
            n_state = 2 if self._mode == "lstm" else 1
            begin_state = [symbol._rnn_state_zeros(
                inputs, num=self._num_layers * self._directions,
                dim=self._num_hidden) for _ in range(n_state)]
        states = begin_state
        outs = symbol.RNN(inputs, self._parameter, *states,
                          state_size=self._num_hidden,
                          num_layers=self._num_layers,
                          bidirectional=self._bidirectional,
                          p=self._dropout, state_outputs=True,
                          mode=self._mode, name=f"{self._prefix}rnn")
        outputs = outs[0]
        if axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.SliceChannel(
                outputs, axis=axis, num_outputs=length, squeeze_axis=1))
        if self._get_next_state:
            states = [outs[i] for i in range(
                1, 3 if self._mode == "lstm" else 2)]
        else:
            states = []
        return outputs, states

    def unfuse(self):
        """Equivalent stack of unfused cells (rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell(f"{self._prefix}l{i}_"),
                    get_cell(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(get_cell(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child " \
                "cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n] if begin_state is not None \
                else None
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        assert not self._modified
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        l_cell, r_cell = self._cells
        if begin_state is None:
            l_begin, r_begin = None, None
        else:
            l_begin = begin_state[:len(l_cell.state_info)]
            r_begin = begin_state[len(l_cell.state_info):]
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=l_begin, layout=layout,
            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)), begin_state=r_begin,
            layout=layout, merge_outputs=False)
        outputs = [symbol.Concat(l_o, r_o, dim=1,
                                 name=f"{self._output_prefix}t{i}")
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs, _ = _normalize_sequence(length, outputs, layout,
                                             merge_outputs)
        states = l_states + r_states
        return outputs, states


class ModifierCell(BaseRNNCell):
    def __init__(self, base_cell):
        base_cell._modified = True
        super().__init__()
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        assert isinstance(dropout, (int, float))
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't " \
            "support step. Please add ZoneoutCell to the cells underneath " \
            "instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)
        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = (symbol.where(mask(p_outputs, next_output), next_output,
                               prev_output)
                  if p_outputs != 0. else next_output)
        states = ([symbol.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0. else next_states)
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = symbol.elemwise_add(output, inputs)
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(out, inp)
                       for out, inp in zip(outputs, inputs)]
        return outputs, states
