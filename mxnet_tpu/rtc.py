"""Runtime kernel compilation — mx.rtc.

Parity surface: python/mxnet/rtc.py CudaModule (NVRTC runtime-compiled
CUDA, src/common/rtc.cc:35). The TPU analog of runtime kernel authorship
is Pallas: `PallasModule` compiles a kernel from python SOURCE at runtime
(the role NVRTC plays for CUDA strings) and returns launchable kernels.
`CudaModule` is kept as an informative error — CUDA source cannot target
a TPU.

    mod = mx.rtc.PallasModule(r'''
    def scale_add(x_ref, y_ref, out_ref):
        out_ref[:] = x_ref[:] * 2.0 + y_ref[:]
    ''')
    k = mod.get_kernel("scale_add", num_inputs=2)
    out = k.launch(a, b)          # NDArrays in, NDArray out
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "PallasModule"]


class CudaModule:
    """NVRTC parity stub: CUDA source has no TPU lowering."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "CudaModule compiles CUDA C++ — there is no TPU lowering for "
            "CUDA source. Use mx.rtc.PallasModule with a Pallas kernel "
            "(jax.experimental.pallas) for runtime TPU kernels.")


class PallasKernel:
    """A launchable runtime-compiled kernel."""

    def __init__(self, fn, name, num_inputs, interpret):
        self._fn = fn
        self._name = name
        self._num_inputs = num_inputs
        self._interpret = interpret

    def launch(self, *arrays, out_shape=None, grid=None):
        """Run the kernel over NDArray/jax inputs; returns an NDArray.

        out_shape defaults to the first input's shape/dtype; `grid` is
        forwarded to pallas_call for tiled launches.
        """
        import jax
        import jax.experimental.pallas as pl
        from .ndarray.ndarray import NDArray

        if len(arrays) != self._num_inputs:
            raise MXNetError(
                f"kernel {self._name!r} expects {self._num_inputs} inputs, "
                f"got {len(arrays)}")
        vals = [a._data if isinstance(a, NDArray) else a for a in arrays]
        if out_shape is None:
            out_shape = jax.ShapeDtypeStruct(vals[0].shape, vals[0].dtype)
        # interpret follows the INPUT's device: cpu-resident arrays need
        # the interpreter even when an accelerator backend exists
        interpret = self._interpret
        devs = getattr(vals[0], "devices", None)
        if devs is not None:
            ds = devs()
            if len(ds) == 1:
                interpret = next(iter(ds)).platform == "cpu"
        kwargs = {"out_shape": out_shape, "interpret": interpret}
        if grid is not None:
            kwargs["grid"] = grid
        call = pl.pallas_call(self._fn, **kwargs)
        res = call(*vals)
        # wrap WITHOUT re-committing: array() would copy the result to the
        # default (cpu) context; the kernel output stays on its device
        return res if isinstance(res, NDArray) else NDArray(res)


class PallasModule:
    """Compile Pallas kernels from python source at runtime.

    The source may define any number of kernel functions (signature:
    ``f(*in_refs, out_ref)``); `jnp`, `jax`, `pl`, and `pltpu` are in
    scope. On non-TPU backends kernels run under the Pallas interpreter,
    so the same module works on the CPU test lane.
    """

    def __init__(self, source, exports=()):
        import jax
        import jax.numpy as jnp
        import jax.experimental.pallas as pl
        try:
            import jax.experimental.pallas.tpu as pltpu
        except ImportError:
            pltpu = None
        namespace = {"jax": jax, "jnp": jnp, "pl": pl, "pltpu": pltpu}
        try:
            exec(compile(source, "<rtc source>", "exec"), namespace)
        except Exception as e:
            raise MXNetError(
                f"PallasModule: source failed to compile: {e}") from e
        self._fns = {k: v for k, v in namespace.items()
                     if callable(v) and not k.startswith("_")
                     and k not in ("jax", "jnp", "pl", "pltpu")}
        if exports:
            missing = [e for e in exports if e not in self._fns]
            if missing:
                raise MXNetError(f"PallasModule: exports not found in "
                                 f"source: {missing}")
        try:
            self._interpret = jax.default_backend() == "cpu"
        except Exception:
            self._interpret = True

    def get_kernel(self, name, num_inputs=1, signature=None):
        """Look up a kernel by name. `signature` accepted for CudaModule
        API compatibility (ignored — Pallas refs are typed by launch)."""
        fn = self._fns.get(name)
        if fn is None:
            raise MXNetError(f"no kernel {name!r}; available: "
                             f"{sorted(self._fns)}")
        return PallasKernel(fn, name, num_inputs, self._interpret)
