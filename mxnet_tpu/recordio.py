"""RecordIO — binary record files.

Parity target: python/mxnet/recordio.py + dmlc-core's recordio format
(SURVEY.md §2.4; the dmlc submodule is re-implemented here in pure python,
format-compatible: magic 0xced7230a, uint32 length word with 3-bit
continuation flag, 4-byte alignment). MXIndexedRecordIO adds the .idx
seek table; pack/unpack carry the IRHeader (flag, label, id, id2) used by
im2rec-produced datasets.
"""
from __future__ import annotations

import numbers
import os
import struct
from collections import namedtuple

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_kMagic = 0xced7230a

try:
    import cv2 as _cv2
except ImportError:
    _cv2 = None
# Backend pack_img/unpack_img actually encode with. Exported so callers
# (tools/im2rec.py) can match its channel convention (cv2 = BGR) without
# re-probing and risking a desync.
USES_CV2 = _cv2 is not None


class MXRecordIO:
    """Sequential record reader/writer (recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.fp = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.fp = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fp = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        fp = d.pop("fp", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        is_open = d["is_open"]
        self.is_open = False
        self.fp = None
        if is_open:
            self.open()

    def close(self):
        if self.is_open and self.fp is not None:
            self.fp.close()
            self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if len(buf) >= (1 << 29):
            raise ValueError(
                f"record too large ({len(buf)} bytes): the dmlc recordio "
                "length word holds 29 bits (max 512MB per record)")
        self.fp.write(struct.pack("<I", _kMagic))
        self.fp.write(struct.pack("<I", len(buf)))
        self.fp.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fp.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        hdr = self.fp.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _kMagic:
            raise IOError("Invalid RecordIO magic number")
        length = lrec & ((1 << 29) - 1)
        buf = self.fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.read(pad)
        return buf

    def tell(self):
        return self.fp.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a text .idx seek table
    (recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.writable:
            return
        if self.idx_path and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        else:
            # no .idx: build the seek table by scanning the .rec framing
            # (native C++ scanner when available — iter_image_recordio_2.cc
            # chunk-reader role; python fallback otherwise)
            offsets, _ = scan_record_positions(self.uri)
            for i, off in enumerate(offsets):
                key = self.key_type(i)
                # stored offsets point at the record START (magic word)
                self.idx[key] = int(off) - 8
                self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fp.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload into a record string. Multi-label uses
    flag = label count and prepends float32 labels."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = header._replace(flag=0)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, *header) + s
    return s


def unpack(s):
    """Unpack a record into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        header = header._replace(
            label=np.frombuffer(s[:header.flag * 4], dtype=np.float32))
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array; requires cv2 or PIL for encoding."""
    encoded = _encode_img(img, quality, img_fmt)
    return pack(header, encoded)


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, decoded image array)."""
    header, s = unpack(s)
    img = _decode_img(s, iscolor)
    return header, img


def _encode_img(img, quality, img_fmt):
    if USES_CV2:
        flag = (_cv2.IMWRITE_JPEG_QUALITY
                if img_fmt.lower() in (".jpg", ".jpeg")
                else _cv2.IMWRITE_PNG_COMPRESSION)
        ret, buf = _cv2.imencode(img_fmt, img, [flag, quality])
        assert ret, "failed to encode image"
        return buf.tobytes()
    import io as _io
    from PIL import Image
    pil = Image.fromarray(np.asarray(img).astype(np.uint8))
    bio = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    pil.save(bio, format=fmt, quality=quality)
    return bio.getvalue()


def _decode_img(s, iscolor=-1):
    if USES_CV2:
        return _cv2.imdecode(np.frombuffer(s, dtype=np.uint8), iscolor)
    import io as _io
    from PIL import Image
    return np.asarray(Image.open(_io.BytesIO(s)))


def scan_record_positions(uri):
    """(payload_offsets, lengths) arrays for every record in a .rec file.

    Native fast path (src/runtime_native.cc mxio_scan_records via ctypes);
    pure-python framing walk as fallback.
    """
    from . import _native
    out = _native.scan_records(uri)
    if out is not None:
        return out
    offsets, lengths = [], []
    with open(uri, "rb") as fp:
        while True:
            pos = fp.tell()
            hdr = fp.read(8)
            if len(hdr) < 8:
                break
            magic, lrec = struct.unpack("<II", hdr)
            if magic != _kMagic:
                raise IOError(f"corrupt recordio file: {uri}")
            length = lrec & ((1 << 29) - 1)
            offsets.append(pos + 8)
            lengths.append(length)
            fp.seek((length + 3) & ~3, 1)
    return (np.asarray(offsets, np.int64), np.asarray(lengths, np.int64))
