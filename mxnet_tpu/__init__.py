"""mxnet_tpu — a TPU-native deep learning framework with MXNet capabilities.

Brand-new design on JAX/XLA/PJRT (see SURVEY.md at repo root for the blueprint
and reference citations): NDArrays wrap PJRT buffers with async-future
semantics, operators are jax-traceable functions compiled per (op, attrs,
shapes), symbolic graphs lower to single XLA modules, and distributed data
parallelism rides XLA collectives over ICI/DCN behind the kvstore API.

Conventional usage mirrors MXNet:

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
    net = mx.sym.FullyConnected(mx.sym.Variable('data'), num_hidden=10)
"""
from __future__ import annotations

__version__ = "0.1.0"

from .base import MXNetError, AttrScope, NameManager
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import ops
from . import ndarray
from . import ndarray as nd
from . import random
from . import autograd
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor
from .executor import Executor
from . import lr_scheduler
from . import optimizer
from .optimizer import Optimizer
from . import initializer
from . import initializer as init
from . import metric
from . import callback
from . import model
from . import io
from . import recordio
from . import kvstore
from . import kvstore as kv
from . import monitor
from . import contrib
from . import profiler
from . import visualization
from . import visualization as viz
from . import config
from . import operator
from . import rtc
from . import amp
config._apply_startup()
from .monitor import Monitor
from . import module
from . import module as mod
from . import parallel
from . import image
from . import gluon
from . import rnn
from . import serving
from . import pipeline
from . import checkpoint
from . import test_utils
