"""Async device-feed pipeline — hide host-side input cost behind compute.

The reference hides input cost behind compute twice over: iter_prefetcher.h
double-buffers batches on the host and the dependency engine overlaps the
host->device copy lane (kCopyToGPU) with kernels (SURVEY §1 rows 2/7).
io.PrefetchingIter reproduces the first half; this module is the device
boundary's half: `DeviceFeed` runs a background feeder thread that pulls
batch N+1 from the source iterator and *stages* it — commits it to the
device (jax.device_put with the consumer's sharding, parallel/mesh.py) —
while step N executes on the device. The consumer loop then finds its
next batch already resident and its per-step device_put collapses to a
no-op (device_put on a committed array with the same sharding returns it
unchanged, so results are bit-identical to the synchronous path).

Mechanics:
  - bounded ring (depth 2 by default, MXNET_DEVICE_FEED_DEPTH): the
    feeder stays at most `depth` batches ahead, so device memory holds a
    bounded number of staged batches no matter how fast the source is;
  - the stage function runs ON THE FEEDER THREAD and must copy out of
    the source item (device_put / np.stack both do), which is what makes
    prefetching safe over legacy buffer-reusing iterators — the very
    reason BaseModule.fit's fetch-after-update discipline exists;
  - feeder exceptions are re-raised in the consumer thread at the next
    __next__; close() drains and joins the thread (no leaked threads);
  - counters (`feed_wait_us`, `feed_stage_us`, `overlap_frac`, ...) are
    exported through profiler.register_counter_export under the
    "device_feed" key, so profiler.dump() traces carry them.

The loops threaded through it: Module/BaseModule.fit, the fused K-step
drivers (Module._fit_fused, gluon.trainer.fused_fit), BaseModule.score /
predict, and ServingEngine.warmup. `MXNET_DEVICE_FEED=0` restores the
fully synchronous path everywhere (the bench.py `pipeline` lane measures
the two against each other).
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .telemetry import tracing as _tracing

__all__ = ["DeviceFeed", "module_stage", "enabled", "default_depth",
           "stats", "reset_stats"]

# -- aggregate counters (exported via profiler.register_counter_export) -----

_STATS_LOCK = threading.Lock()
_TOTALS = {"feed_wait_us": 0, "feed_stage_us": 0, "feed_batches": 0,
           "feeds_opened": 0, "feeds_closed": 0}


def _bump(key, val):
    with _STATS_LOCK:
        _TOTALS[key] += val


def stats():
    """Snapshot of the aggregate device-feed counters. `overlap_frac` is
    the fraction of staging time hidden behind compute: 1 when consumers
    never blocked on the feed, 0 when every staged microsecond was waited
    for (fully serial)."""
    with _STATS_LOCK:
        out = dict(_TOTALS)
    stage = out["feed_stage_us"]
    out["overlap_frac"] = round(
        max(0.0, 1.0 - out["feed_wait_us"] / stage), 4) if stage else 0.0
    out["feeds_active"] = out["feeds_opened"] - out["feeds_closed"]
    return out


def reset_stats():
    with _STATS_LOCK:
        for k in _TOTALS:
            _TOTALS[k] = 0


def _register_export():
    from . import profiler
    profiler.register_counter_export("device_feed", stats)


_register_export()


# -- config knobs ------------------------------------------------------------

def enabled():
    """MXNET_DEVICE_FEED gate (default on; 0 restores synchronous feed)."""
    from . import config
    return bool(config.get("MXNET_DEVICE_FEED", 1))


def default_depth():
    from . import config
    return max(1, int(config.get("MXNET_DEVICE_FEED_DEPTH", 2)))


# -- the prefetcher ----------------------------------------------------------

_END = "end"
_ITEM = "item"
_ERR = "err"

# analysis/locklint: DeviceFeed's counters are single-writer by thread
# discipline — stage_us is written ONLY by the feeder thread, wait_us/
# batches/_done ONLY by the consumer thread (close() flips _done after
# the feeder is joined); += with one writer is safe under the GIL and
# readers (overlap_frac/stats) tolerate a one-item-stale value
__analysis_thread_safe__ = {"DeviceFeed.stage_us", "DeviceFeed.wait_us",
                            "DeviceFeed.batches", "DeviceFeed._done"}


class DeviceFeed:
    """Iterate `source` with staging one batch ahead on a feeder thread.

    `stage(item)` runs on the feeder thread and should return the
    device-committed form of `item` (it MUST copy out of any buffer the
    source reuses; jax.device_put and np.stack both do). Omitting it
    degrades gracefully to host-side prefetch of the raw items.

    Iterator contract: yields staged items in source order; StopIteration
    at exhaustion; a feeder-side exception (from the source or the stage
    fn) is re-raised here, in the consumer thread. Use as a context
    manager or call close() — close is idempotent, drains the ring, and
    joins the thread.
    """

    def __init__(self, source, stage=None, depth=None, name="device_feed"):
        self._source = iter(source)
        self._stage = stage if stage is not None else (lambda item: item)
        self._depth = depth if depth is not None else default_depth()
        self._q = queue.Queue(maxsize=max(1, int(self._depth)))
        self._stop = threading.Event()
        self._done = False
        self.name = name
        # per-instance counters (module totals aggregate across feeds)
        self.wait_us = 0
        self.stage_us = 0
        self.batches = 0
        _bump("feeds_opened", 1)
        self._thread = threading.Thread(
            target=self._feeder, name=f"{name}-feeder", daemon=True)
        self._thread.start()

    # -- feeder side --------------------------------------------------------
    def _put(self, msg):
        """Bounded put that gives up when the consumer closed the feed."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _feeder(self):
        # feed_stage_us is the full feeder-side cost per item — source
        # pull plus staging — i.e. exactly the host work the feed hides.
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                # feeder-side work records under "feed_stage", NOT
                # "feed": StepLogger's feed_us/overlap fraction counts
                # only consumer-blocked time (the "feed" phase below)
                with _tracing.span("feed.stage", phase="feed_stage",
                                   feed=self.name):
                    try:
                        item = next(self._source)
                    except StopIteration:
                        break
                    staged = self._stage(item)
                dt_us = int((time.perf_counter() - t0) * 1e6)
                self.stage_us += dt_us
                _bump("feed_stage_us", dt_us)
                if not self._put((_ITEM, staged)):
                    return
            self._put((_END, None))
        except BaseException as exc:   # noqa: BLE001 — re-raised consumer-side
            self._put((_ERR, exc))

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        with _tracing.span("feed.wait", phase="feed", feed=self.name):
            kind, val = self._q.get()
        dt_us = int((time.perf_counter() - t0) * 1e6)
        self.wait_us += dt_us
        _bump("feed_wait_us", dt_us)
        if kind == _ITEM:
            self.batches += 1
            _bump("feed_batches", 1)
            return val
        self._done = True
        self.close()
        if kind == _ERR:
            raise val
        raise StopIteration

    def overlap_frac(self):
        """Fraction of this feed's staging time hidden behind compute."""
        if not self.stage_us:
            return 0.0
        return max(0.0, 1.0 - self.wait_us / self.stage_us)

    def close(self):
        """Stop the feeder, drain the ring, join the thread. Idempotent."""
        if self._stop.is_set() and not self._thread.is_alive():
            return
        self._stop.set()
        # drain so a feeder blocked in put() wakes and sees the stop flag
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive() and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=10.0)
        self._done = True
        _bump("feeds_closed", 1)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -- stage builders ----------------------------------------------------------

def module_stage(module):
    """Stage function for DataBatch streams feeding a bound module: each
    data/label array is committed to the placement the module's executor
    will request in forward — batch-sharded inputs / per-context device
    (executor._arg_sharding) — so forward's own device_put is a no-op.

    Placement is resolved per batch through `module._exec` (rebind /
    reshape swap the executor mid-fit). Arrays whose batch axis doesn't
    divide the mesh are passed through unstaged so forward raises its
    documented divisibility error instead of a feeder-thread jax error;
    modules without a bound executor degrade to host-side prefetch.
    """
    import jax
    from .io import DataBatch
    from .ndarray.ndarray import NDArray

    def _put(ex, name, arr):
        if name not in ex.arg_dict:
            return arr
        data = arr._data if isinstance(arr, NDArray) else arr
        if not isinstance(data, jax.Array):
            data = np.asarray(data)
        if ex._mesh is not None:
            if name in ex._sharded_args and data.shape and \
                    data.shape[0] % ex._mesh.devices.size != 0:
                return arr      # forward owns the divisibility error
            target = ex._arg_sharding(name)
        else:
            target = ex._ctx.jax_device()
        return NDArray(jax.device_put(data, target))

    def stage(batch):
        ex = getattr(module, "_exec", None)
        if ex is None or getattr(ex, "arg_dict", None) is None:
            return batch
        data = [_put(ex, n, a)
                for n, a in zip(module.data_names, batch.data)]
        label = batch.label
        if label:
            lnames = list(getattr(module, "label_names", None) or [])
            label = [_put(ex, n, a) for n, a in zip(lnames, label)]
        return DataBatch(data=data, label=label, pad=batch.pad,
                         index=batch.index, bucket_key=batch.bucket_key,
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    return stage


def feed_or_inline(source, stage, name="device_feed"):
    """DeviceFeed when MXNET_DEVICE_FEED is on, else a lazy synchronous
    map of the SAME stage function — consumer loops get one code path
    whose math is identical either way (only the thread differs)."""
    if enabled():
        return DeviceFeed(source, stage=stage, name=name)
    return map(stage, source)


def close_feed(feed):
    """close() for DeviceFeed, no-op for the inline map fallback."""
    if isinstance(feed, DeviceFeed):
        feed.close()


# -- smoke entry (tools/ci.sh quick stage) -----------------------------------

def _selftest():
    """Overlap smoke: a source with real per-item host cost feeding a
    consumer with real per-item compute; asserts order + values survive
    the feed, the feeder thread exits, and staging actually overlapped."""
    import os
    import jax

    n, host_ms = 24, 4.0

    def source():
        for i in range(n):
            time.sleep(host_ms / 1e3)        # decode/read stand-in
            yield i, np.full((64,), i, np.float32)

    dev = jax.devices()[0]

    def stage(item):
        i, arr = item
        return i, jax.device_put(arr, dev)

    t0 = time.perf_counter()
    seen = []
    with DeviceFeed(source(), stage=stage, name="selftest") as feed:
        for i, arr in feed:
            time.sleep(host_ms / 1e3)        # device-step stand-in
            assert float(np.asarray(arr)[0]) == float(i)
            seen.append(i)
        thread = feed._thread
    wall = time.perf_counter() - t0
    assert seen == list(range(n)), "order not preserved"
    assert not thread.is_alive(), "feeder thread leaked"
    sync_est = 2 * n * host_ms / 1e3
    print(f"device-feed selftest: {n} items, wall {wall:.2f}s vs "
          f"~{sync_est:.2f}s synchronous, overlap_frac "
          f"{stats()['overlap_frac']}")
    if wall >= sync_est * 0.85:
        raise SystemExit("selftest FAILED: no overlap measured")
    print("PIPELINE-SELFTEST-OK")


def main(argv=None):
    import argparse
    import os
    ap = argparse.ArgumentParser(description="async device-feed pipeline")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    # the site hook may pin jax_platforms at interpreter start, overriding
    # the JAX_PLATFORMS env this smoke is launched with (ci.sh quick) —
    # re-pin via jax.config before the first backend touch
    if os.environ.get("JAX_PLATFORMS"):
        try:
            import jax
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except Exception:
            pass
    if args.selftest:
        _selftest()
        return 0
    ap.print_help()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
