"""mxnet_tpu.amp — automatic mixed precision for the whole stack.

Role of the reference's `mxnet.contrib.amp` (amp.init patches the op
namespace with casts; LossScaler guards fp16), rebuilt for the XLA
lowering: instead of rewriting symbols, the policy hooks the ONE place
every op call funnels through — `executor._build_runner`'s fcompute
dispatch — and casts op inputs at trace time per the ALLOW/WIDEN lists
(amp/policy.py). Since every execution route (Executor.bind, Module.fit,
gluon CachedOp, DataParallelTrainer, export) lowers through that runner,
one hook mixes precision everywhere, and `amp.init("float32")` (or
leaving amp off) is a literal no-op: the traced program is unchanged,
so fp32 results stay bit-identical.

    import mxnet_tpu as mx
    mx.amp.init("bfloat16")     # before bind/fit: jit caches by shape,
                                # not by amp state, so flip it first
    mod.fit(...)                # matmuls/convs in bf16, softmax/norm
                                # stats and the update in fp32

Master weights: parameters stay fp32 everywhere (NDArray args, the
DataParallelTrainer param pytree) — the policy casts them down at each
use site, XLA dedups the casts, and gradients flow back in the compute
dtype to be accumulated into the fp32 state. fp16 additionally needs
`DynamicLossScaler` (amp/scaler.py) — wired automatically into
DataParallelTrainer(dtype="float16").

Env wiring (config.py): MXNET_AMP=1 [MXNET_AMP_DTYPE=bfloat16|float16]
calls `init` at import. Counters (amp_scale, amp_skipped_steps,
amp_cast_bytes_saved) export through profiler.register_counter_export.
"""
from __future__ import annotations

import threading
import weakref

import numpy as _np

from .policy import ALLOW, LOSS_HEADS, WIDEN
from .scaler import DynamicLossScaler

__all__ = ["init", "disable", "is_enabled", "get_dtype", "compute_dtype",
           "reduce_dtype", "cast_op_inputs", "counters", "DynamicLossScaler",
           "ALLOW", "LOSS_HEADS", "WIDEN"]

_DTYPES = ("float32", "bfloat16", "float16")

_lock = threading.Lock()
_state = {"enabled": False, "dtype": "float32"}
_cast_bytes_saved = [0]      # trace-time accounting, see cast_op_inputs
_scale_sources = []          # weakrefs to objects with _amp_counters()
_export_registered = [False]
_tls = threading.local()     # trace-scoped loss scale, see below
_inject_vjp = [None]         # lazily-built custom_vjp (needs jax)


def init(dtype="bfloat16"):
    """Enable autocast with the given compute dtype ("bfloat16" or
    "float16"); "float32" disables (explicit no-op policy). Call BEFORE
    binding/compiling: already-jitted programs do not retrace on amp
    state changes (jax caches by input avals). Returns the active dtype.
    """
    dtype = str(dtype)
    if dtype not in _DTYPES:
        raise ValueError(f"amp.init: dtype must be one of {_DTYPES}, "
                         f"got {dtype!r}")
    with _lock:
        _state["dtype"] = dtype
        _state["enabled"] = dtype != "float32"
    _ensure_counter_export()
    return dtype


def disable():
    with _lock:
        _state["enabled"] = False
        _state["dtype"] = "float32"


def is_enabled():
    return _state["enabled"]


def get_dtype():
    """Active compute dtype name ("float32" when disabled)."""
    return _state["dtype"]


def compute_dtype():
    """Active compute dtype as a jnp dtype, or None when disabled."""
    if not _state["enabled"]:
        return None
    import jax.numpy as jnp
    return jnp.bfloat16 if _state["dtype"] == "bfloat16" else jnp.float16


def reduce_dtype():
    """Wire dtype for cross-process gradient reduction (kvstore/dist
    push path): bf16 when amp is on — fp16 grads also reduce in bf16
    (same width, fp32-range exponent, so the sum cannot overflow where
    the addends did not) — else None (keep fp32)."""
    if not _state["enabled"]:
        return None
    from ..base import bfloat16 as _bf16
    return _bf16


def _set_trace_loss_scale(scale):
    """Trace-scoped fp16 loss scale (parallel/dp.py sets it around its
    value_and_grad trace, clears in a finally). While set, the executor
    funnel wraps each legacy loss head's data input in a cotangent
    multiplier — the ONLY way to scale gradients under heads whose
    custom VJP ignores the incoming cotangent (policy.LOSS_HEADS).
    Thread-local: concurrent trainers on other threads are unaffected."""
    _tls.loss_scale = scale


def _trace_loss_scale():
    return getattr(_tls, "loss_scale", None)


def _inject_grad_scale(x, scale):
    """Identity on the forward value; multiplies the backward cotangent
    by `scale` (in fp32, then back to the cotangent's dtype so fp16
    overflow stays detectable as inf downstream)."""
    if _inject_vjp[0] is None:
        import jax
        import jax.numpy as jnp

        @jax.custom_vjp
        def _inject(v, s):
            return v

        def _fwd(v, s):
            return v, s

        def _bwd(s, g):
            scaled = (g.astype(jnp.float32) * s).astype(g.dtype)
            return scaled, jnp.zeros_like(s)

        _inject.defvjp(_fwd, _bwd)
        _inject_vjp[0] = _inject
    return _inject_vjp[0](x, scale)


def cast_op_inputs(op_name, ins):
    """The executor hook: given an op's registry name and its input
    values (jax arrays at trace time), return the policy-cast inputs.
    Identity when amp is off, for NEUTRAL ops, and for every non-float
    input (ids/masks/aux ints are never cast). Independently of the
    policy, while a trace loss scale is set (fp16 training), loss-head
    data inputs get the gradient-scale injection — applied AFTER the
    policy casts so the cotangent multiply runs in the widened dtype."""
    scale = getattr(_tls, "loss_scale", None)
    if not _state["enabled"] and scale is None:
        return ins
    import jax.numpy as jnp
    out = list(ins)
    tgt = None
    if _state["enabled"]:
        if op_name in ALLOW:
            tgt = jnp.bfloat16 if _state["dtype"] == "bfloat16" \
                else jnp.float16
        elif op_name in WIDEN:
            tgt = jnp.float32
    if tgt is not None:
        tgt_np = _np.dtype(tgt)
        for i, x in enumerate(out):
            dt = getattr(x, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating) \
                    and dt != tgt_np:
                saved = (_np.dtype(dt).itemsize - tgt_np.itemsize) \
                    * int(getattr(x, "size", 0))
                if saved > 0:
                    # counted once per TRACE (each compiled program), not
                    # per step: it measures bytes the cast removes from
                    # the program's activation traffic, via counters()
                    with _lock:
                        _cast_bytes_saved[0] += saved
                out[i] = x.astype(tgt)
    if scale is not None and op_name in LOSS_HEADS and out:
        out[0] = _inject_grad_scale(out[0], scale)
    return out


# -- counters ---------------------------------------------------------------

def _register_scale_source(obj):
    """Trainers with a live loss scale register themselves (weakly);
    counters() polls whoever is still alive. `obj` must expose
    `_amp_counters() -> {"amp_scale": float, "amp_skipped_steps": int}`.
    """
    with _lock:
        _scale_sources.append(weakref.ref(obj))


def counters():
    """Snapshot for profiler.export_counters()/dump(): the three ISSUE
    counters plus the active policy."""
    out = {"enabled": _state["enabled"], "dtype": _state["dtype"],
           "amp_cast_bytes_saved": int(_cast_bytes_saved[0]),
           "amp_scale": None, "amp_skipped_steps": 0}
    with _lock:
        refs = list(_scale_sources)
    live = []
    for r in refs:
        src = r()
        if src is None:
            continue
        live.append(r)
        try:
            c = src._amp_counters()
        except Exception:
            continue
        if c.get("amp_scale") is not None:
            out["amp_scale"] = float(c["amp_scale"])
        out["amp_skipped_steps"] += int(c.get("amp_skipped_steps", 0))
    with _lock:
        _scale_sources[:] = live
    return out


def _ensure_counter_export():
    if _export_registered[0]:
        return
    from .. import profiler
    profiler.register_counter_export("amp", counters)
    _export_registered[0] = True


def _reset_for_tests():
    """Test hook: restore pristine module state (policy off, counters
    zeroed) so amp tests cannot leak into dtype-sensitive suites."""
    with _lock:
        _state["enabled"] = False
        _state["dtype"] = "float32"
        _cast_bytes_saved[0] = 0
        _scale_sources[:] = []
    _tls.loss_scale = None

# register the export hook at import, not just amp.init(): the telemetry
# registry absorbs every profiler hook at /metrics scrape time, and amp's
# enabled/dtype/cast-savings counters should be visible (zeroed) even on
# runs that never turn amp on
_ensure_counter_export()
