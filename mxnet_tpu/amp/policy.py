"""Autocast policy: which registry ops run in half precision.

Role of the reference's AMP op lists (python/mxnet/contrib/amp/lists/
symbol_fp16.py: FP16_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS), keyed on
OUR op registry names (ops/registry.py). Three buckets:

  ALLOW  — matmul/conv-class ops whose FLOPs dominate step time and whose
           MXU rate doubles in bf16/fp16: float inputs are cast DOWN to
           the amp dtype at the use site. Accumulation stays fp32 inside
           the kernels (dot_general preferred_element_type, the flash-
           attention VMEM accumulators, conv1x1's fp32 psum), so only
           storage/bandwidth and the MXU input width narrow.
  WIDEN  — numerically fragile reductions: softmax family, loss heads,
           and every normalization whose statistics must accumulate in
           fp32 (the Micikevicius et al. 2018 recipe). Float inputs are
           cast UP to fp32, so a bf16 activation entering softmax is
           widened and the exp/sum runs full width.
  (rest) — NEUTRAL: elementwise/shape ops run in whatever dtype arrives;
           casting them would only add convert traffic. Integer inputs
           (embedding ids, argmax indices) are never touched by any
           bucket — bf16's 8-bit mantissa corrupts ids (parallel/dp.py
           learned this the hard way).

The lists are module-level frozensets so tests and docs/AMP.md can
introspect them; `amp.init` does not mutate them.
"""
from __future__ import annotations

# compute-bound ops: cast float inputs down to the amp dtype
ALLOW = frozenset({
    "FullyConnected",
    "Convolution",
    "Deconvolution",
    "dot",
    "batch_dot",
    "_linalg_gemm",
    "_linalg_gemm2",
    "_contrib_flash_attention",
})

# legacy loss-head ops whose custom VJP supplies its own gradient and
# IGNORES the incoming cotangent (the MXNet out_grad=False contract:
# ops/nn.py returns e.g. (softmax - onehot) * grad_scale regardless of
# what flows in from above). Multiplying the loss by the fp16 loss scale
# therefore does NOT scale gradients under these heads — the scale must
# be injected into the cotangent directly BELOW the head instead
# (amp.cast_op_inputs wraps the head's data input in a custom_vjp that
# multiplies the outgoing cotangent by the live scale). Graphs whose
# loss is an ordinary differentiable value keep the textbook
# `loss * scale` route in parallel/dp.py; the two mechanisms are
# mutually exclusive by construction (scaling the loss above a
# cotangent-ignoring head is a no-op, and injection only fires on the
# ops listed here).
LOSS_HEADS = frozenset({
    "SoftmaxOutput",
    "LinearRegressionOutput",
    "LogisticRegressionOutput",
    "MAERegressionOutput",
    "MakeLoss",
    "SVMOutput",
})

# reduction/loss/norm ops: cast float inputs up to fp32
WIDEN = frozenset({
    "softmax",
    "log_softmax",
    "SoftmaxActivation",
    "SoftmaxOutput",
    "softmax_cross_entropy",
    "BatchNorm",
    "LayerNorm",
    "InstanceNorm",
    "L2Normalization",
    "LRN",
    "norm",
    "MakeLoss",
    "make_loss",
    "SVMOutput",
    "smooth_l1",
    "IdentityAttachKLSparseReg",
})
