"""AMP selftest CLI.

    python -m mxnet_tpu.amp --selftest

Runs three CPU-mesh checks and prints ONE JSON line:

  1. no-op policy: amp.init("float32") leaves a compiled forward
     bit-identical to the amp-off program (the MXNET_AMP=0 contract);
  2. bf16 lane: a DataParallelTrainer(dtype="bfloat16") MLP step loses
     loss over 30 steps while params/optimizer states stay fp32;
  3. fp16 lane: an injected inf batch is skipped (params unchanged),
     the DynamicLossScaler halves, and training continues after it.

Exit code 0 iff all three hold — wired into tools/ci.sh quick.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_cpu(n=2):
    """Force the cpu backend BEFORE jax initializes — the axon site hook
    sets jax_platforms at interpreter start and overrides JAX_PLATFORMS
    env, so the jax.config override is the one that sticks
    (__graft_entry__/conftest idiom)."""
    os.environ.setdefault("JAX_NUM_CPU_DEVICES", str(n))
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_force_host_platform_device"
                                     f"_count={n}")
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        pass
    jax.config.update("jax_platforms", "cpu")


def _mlp_sym():
    import mxnet_tpu as mx
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _trainer(dtype, mesh, **kw):
    from mxnet_tpu.parallel import DataParallelTrainer
    return DataParallelTrainer(_mlp_sym(), mesh, optimizer="sgd",
                               learning_rate=0.1, momentum=0.9,
                               dtype=dtype, rescale_grad=1.0 / 16, **kw)


def selftest():
    _pin_cpu(2)
    import numpy as np
    import jax
    from mxnet_tpu import amp
    from mxnet_tpu.parallel import data_parallel_mesh

    results = {"metric": "amp_selftest"}
    rng = np.random.RandomState(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.float32)

    # 1) amp.init("float32") is a no-op policy: bit-identical forward
    import mxnet_tpu as mx
    sym = _mlp_sym()
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier(rnd_type="gaussian"))
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)],
                                label=[mx.nd.array(y)]), is_train=False)
    base = mod.get_outputs()[0].asnumpy()
    amp.init("float32")
    try:
        mod2 = mx.mod.Module(sym, context=mx.cpu(0))
        mod2.bind(data_shapes=[("data", (16, 8))],
                  label_shapes=[("softmax_label", (16,))])
        arg_p, aux_p = mod.get_params()
        mod2.set_params(arg_p, aux_p)
        mod2.forward(mx.io.DataBatch(data=[mx.nd.array(x)],
                                     label=[mx.nd.array(y)]),
                     is_train=False)
        noop = mod2.get_outputs()[0].asnumpy()
    finally:
        amp._reset_for_tests()
    results["noop_bit_identical"] = bool((base == noop).all())

    # 2) bf16: cross-entropy decreases, masters stay fp32. The step's
    # "loss" output is the SoftmaxOutput head's probabilities sum (its
    # custom vjp supplies the gradient), so measure the actual CE from
    # the output probabilities on the host.
    mesh = data_parallel_mesh(2, jax.devices()[:2])
    tr = _trainer("bfloat16", mesh)
    params, states, aux = tr.init_state({"data": (16, 8),
                                         "softmax_label": (16,)})
    inputs = tr.shard_inputs([x, y])

    def _ce(outs):
        p = np.asarray(outs[0], np.float32)
        return float(-np.log(p[np.arange(16), y.astype(int)]
                             + 1e-8).mean())

    ces = []
    for _ in range(30):
        params, states, aux, loss, outs = tr.step(params, states, aux,
                                                  inputs)
        ces.append(_ce(outs))
    results["bf16_ce_first"] = ces[0]
    results["bf16_ce_last"] = ces[-1]
    results["bf16_converges"] = ces[-1] < ces[0]
    results["bf16_master_f32"] = all(
        str(p.dtype) == "float32" for p in params) and all(
        str(s.dtype) == "float32" for st in states for s in st)

    # 3) fp16: injected inf -> step skipped, scale halved, then training
    # RESUMES AND CONVERGES (the convergence assertion is load-bearing:
    # a finite-only check cannot tell scaled gradients from zeroed ones).
    # init_scale pinned to 1024: the default 2^15 overflows this tiny
    # MLP's batch-summed fp16 grads on step one — a correct backoff,
    # but it would offset the exact skip count asserted below.
    from mxnet_tpu.amp import DynamicLossScaler
    tr16 = _trainer("float16", mesh,
                    loss_scaler=DynamicLossScaler(init_scale=1024.0))
    params, states, aux = tr16.init_state({"data": (16, 8),
                                           "softmax_label": (16,)})
    params, states, aux, _, _ = tr16.step(params, states, aux, inputs)
    before = [np.asarray(p).copy() for p in params]
    scale0 = tr16.loss_scale
    bad = x.copy()
    bad[0, 0] = np.inf
    params, states, aux, _, _ = tr16.step(params, states, aux,
                                          tr16.shard_inputs([bad, y]))
    unchanged = all((np.asarray(p) == b).all()
                    for p, b in zip(params, before))
    results["fp16_skip_params_unchanged"] = bool(unchanged)
    results["fp16_scale_halved"] = tr16.loss_scale == scale0 * 0.5
    results["fp16_skipped_steps"] = int(tr16.skipped_steps)
    ces16 = []
    for _ in range(20):
        params, states, aux, loss, outs = tr16.step(params, states, aux,
                                                    inputs)
        ces16.append(_ce(outs))
    results["fp16_ce_first"] = ces16[0]
    results["fp16_ce_last"] = ces16[-1]
    results["fp16_resumes_and_converges"] = bool(
        np.isfinite(ces16).all() and ces16[-1] < ces16[0])

    ok = (results["noop_bit_identical"] and results["bf16_converges"]
          and results["bf16_master_f32"]
          and results["fp16_skip_params_unchanged"]
          and results["fp16_scale_halved"]
          and results["fp16_skipped_steps"] == 1
          and results["fp16_resumes_and_converges"])
    results["ok"] = bool(ok)
    print(json.dumps(results), flush=True)
    return 0 if ok else 1


def hlo_check(dtype="bfloat16"):
    """Compile the data-parallel half-precision train step on a 2-device
    mesh and report the gradient all-reduce element types from the
    POST-SPMD-PARTITIONING HLO (the pass that inserts the collectives).

    Why not the final optimized HLO: on the cpu backend the later
    float-normalization pass promotes bf16 collectives to f32 (cpu has
    no native bf16 compute) — a backend legalization, not a property of
    the program. TPU keeps them half-width; the post-SPMD dump shows the
    wire dtype the partitioner chose on every backend. Must run in a
    fresh process: --xla_dump_to is read once at backend init.
    """
    import tempfile
    dump = tempfile.mkdtemp(prefix="amp_hlo_")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        + " --xla_dump_hlo_pass_re=.*spmd.*")
    _pin_cpu(2)
    import numpy as np
    import jax
    from mxnet_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh(2, jax.devices()[:2])
    tr = _trainer(dtype, mesh)
    params, states, aux = tr.init_state({"data": (16, 8),
                                         "softmax_label": (16,)})
    x = np.zeros((16, 8), np.float32)
    y = np.zeros((16,), np.float32)
    inputs = tr.shard_inputs([x, y])
    params, states, aux, _, _ = tr.step(params, states, aux, inputs)

    # HLO matching lives in ONE place: the analysis auditor's helpers
    from mxnet_tpu.analysis.hloaudit import spmd_allreduces, wire_bytes
    ars = spmd_allreduces(dump, "jit_step")
    grad_ars = [a for a in ars if a[1]]    # non-scalar = gradient tensors
    ar_bytes = wire_bytes(grad_ars)
    want = {"bfloat16": "bf16", "float16": "f16",
            "float32": "f32"}[dtype]
    master_f32 = all(str(p.dtype) == "float32" for p in params) and all(
        str(s.dtype) == "float32" for st in states for s in st)
    ok = (bool(grad_ars) and all(dt == want for dt, _ in grad_ars)
          and master_f32)
    print(json.dumps({"metric": "amp_hlo_check", "dtype": dtype,
                      "grad_allreduce": grad_ars,
                      "grad_allreduce_bytes_per_step": int(ar_bytes),
                      "master_f32": bool(master_f32),
                      "ok": bool(ok)}), flush=True)
    import shutil
    shutil.rmtree(dump, ignore_errors=True)
    # all work is done and the verdict is flushed; skip interpreter
    # finalization — XLA's --xla_dump_to machinery races CPython teardown
    # on the cpu backend and intermittently SIGSEGVs the otherwise-
    # successful process (observed as rc -11 under the full test suite)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0 if ok else 1)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m mxnet_tpu.amp")
    ap.add_argument("--selftest", action="store_true",
                    help="run the AMP smoke checks (ci.sh quick)")
    ap.add_argument("--hlo-check", action="store_true",
                    help="report gradient all-reduce dtypes from the "
                         "post-SPMD HLO (2-device cpu mesh)")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16", "float16"],
                    help="compute dtype for --hlo-check")
    args = ap.parse_args(argv)
    if args.hlo_check:
        return hlo_check(args.dtype)
    if not args.selftest:
        ap.print_help()
        return 2
    return selftest()


if __name__ == "__main__":
    sys.exit(main())
