"""DynamicLossScaler — fp16 gradient-underflow protection.

fp16's 5-bit exponent bottoms out at ~6e-8: small-magnitude gradients
silently flush to zero, so fp16 training multiplies the loss by a large
scale before backprop (shifting every gradient up into representable
range), unscales before the update, and *skips* any step whose scaled
gradients overflowed to inf/nan (Micikevicius et al. 2018 §3.2; the
reference ships this as contrib/amp's LossScaler). bfloat16 keeps
fp32's 8-bit exponent and needs none of this — see docs/AMP.md.

Two usage shapes:

  - Host-driven (gluon / custom loops): the class below — scale the
    loss, check the grads, call `update(overflow)` each step.
  - Trace-driven (the fused DataParallelTrainer step): the scaler state
    is a 3-vector ``[scale, good_steps, skipped_total]`` carried on
    device through the jitted step (and through the lax.scan carry for
    step_k), updated by `update_state` inside the trace so k fused steps
    grow/backoff exactly like k python-dispatched steps.
"""
from __future__ import annotations

import numpy as _np


class DynamicLossScaler:
    """Grow-on-success / backoff-on-overflow loss scale.

    scale starts at `init_scale`; every `growth_interval` consecutive
    finite steps it multiplies by `growth_factor` (capped at
    `max_scale`); any non-finite gradient halves it by `backoff_factor`
    (floored at `min_scale`) and the step is skipped.
    """

    def __init__(self, init_scale=2.0 ** 15, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000,
                 min_scale=1.0, max_scale=2.0 ** 24):
        if init_scale <= 0:
            raise ValueError("DynamicLossScaler: init_scale must be > 0")
        self.init_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)
        self.scale = self.init_scale
        self.good_steps = 0
        self.skipped_steps = 0

    # -- host-driven API ----------------------------------------------------

    def scale_loss(self, loss):
        return loss * self.scale

    def unscale(self, grads):
        inv = 1.0 / self.scale
        return [g * inv for g in grads]

    def has_overflow(self, grads):
        for g in grads:
            a = _np.asarray(getattr(g, "_data", g), dtype=_np.float32)
            if not _np.all(_np.isfinite(a)):
                return True
        return False

    def update(self, overflow):
        """Advance the schedule after one step; returns True when the
        step should be APPLIED (i.e. no overflow)."""
        if overflow:
            self.scale = max(self.scale * self.backoff_factor,
                             self.min_scale)
            self.good_steps = 0
            self.skipped_steps += 1
            return False
        self.good_steps += 1
        if self.good_steps >= self.growth_interval:
            self.scale = min(self.scale * self.growth_factor,
                             self.max_scale)
            self.good_steps = 0
        return True

    # -- checkpoint round-trip ----------------------------------------------

    def get_state(self):
        """JSON-safe snapshot of the dynamic schedule position. The
        hyperparameters (growth/backoff/interval) are construction-time
        config; only the live [scale, good, skipped] position needs to
        survive a restore for bit-identical continuation."""
        return {"scale": float(self.scale),
                "good_steps": int(self.good_steps),
                "skipped_steps": int(self.skipped_steps)}

    def set_state(self, state):
        self.scale = float(state["scale"])
        self.good_steps = int(state["good_steps"])
        self.skipped_steps = int(state["skipped_steps"])

    # -- trace-driven API (fused step / scan carry) -------------------------

    def state0(self):
        """Initial on-device state vector [scale, good, skipped] (f32)."""
        return _np.array([self.scale, float(self.good_steps),
                          float(self.skipped_steps)], _np.float32)

    def update_state(self, state, finite):
        """Pure jax-traceable schedule update: `state` is the 3-vector,
        `finite` a boolean scalar (all grads finite). Returns the new
        state vector; constants fold into the trace."""
        import jax.numpy as jnp
        scale, good, skipped = state[0], state[1], state[2]
        good = jnp.where(finite, good + 1.0, 0.0)
        grow = good >= float(self.growth_interval)
        new_scale = jnp.where(
            finite,
            jnp.where(grow,
                      jnp.minimum(scale * self.growth_factor,
                                  self.max_scale),
                      scale),
            jnp.maximum(scale * self.backoff_factor, self.min_scale))
        good = jnp.where(grow, 0.0, good)
        skipped = skipped + jnp.where(finite, 0.0, 1.0)
        return jnp.stack([new_scale, good, skipped])
