"""mx.parallel — device-mesh parallelism.

TPU-native replacement for the reference's multi-executor data parallelism
(DataParallelExecutorGroup, python/mxnet/module/executor_group.py:129 +
kvstore device/NCCL reduce, SURVEY.md §2.3): instead of one executor per
device with explicit gradient push/pull, the WHOLE training step — forward,
backward, gradient all-reduce, optimizer update — is one jitted XLA program
over a `jax.sharding.Mesh`. Batch inputs are sharded along the mesh's data
axis; parameters are replicated; XLA inserts the psum over ICI where the
scalar loss sums across the sharded batch. Multi-host: the same program runs
under jax.distributed with a global mesh (DCN between slices).

The compositions — dp×tp GSPMD layouts, ZeRO over any mesh's joint axes —
are unified by `planner` (MXNET_PLAN): one `Plan` names the mesh shape,
layout and knob settings, and `planner.make_trainer` builds (or cost-model
auto-selects) the trainer it describes (docs/PLANNER.md).
"""
from .mesh import (build_mesh, data_parallel_mesh, single_axis_mesh,
                   mesh_for_contexts, mesh_for_devices, axis_size,
                   data_axis, mesh_descriptor, mesh_from_descriptor,
                   replicated_sharding, batch_sharding,
                   put_replicated, put_batch_sharded)
from .dp import DataParallelTrainer
from . import zero
from .zero import ZeroTrainer
from . import embedding
from .embedding import EmbeddingTrainer
from . import planner
from .planner import Plan, make_trainer
from . import sp
from . import tp
from . import pp
from .sp import ring_attention, ulysses_attention
from .tp import megatron_mlp, moe_ffn
from .pp import pipeline_mlp

__all__ = ["build_mesh", "data_parallel_mesh", "single_axis_mesh",
           "DataParallelTrainer", "ZeroTrainer", "zero",
           "EmbeddingTrainer", "embedding", "planner", "Plan",
           "make_trainer", "mesh_for_contexts", "mesh_for_devices",
           "axis_size", "data_axis", "mesh_descriptor",
           "mesh_from_descriptor", "replicated_sharding",
           "batch_sharding", "put_replicated", "put_batch_sharded",
           "sp", "tp", "pp", "ring_attention", "ulysses_attention",
           "megatron_mlp", "moe_ffn", "pipeline_mlp"]
