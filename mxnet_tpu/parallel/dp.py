"""Data-parallel training: one compiled step over a device mesh.

Role of the reference stack {DataParallelExecutorGroup → kvstore device/NCCL
reduce → optimizer update ops} (SURVEY.md §2.3, §3.1-3.5), collapsed into a
single pjit-sharded XLA program: fwd + bwd + grad-psum + SGD/momentum update.
Gradient reduction is implicit — the loss sums over the batch axis that is
sharded across the mesh, so XLA emits the psum over ICI; no push/pull, no
per-device executor replicas, no host round-trips inside the step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..executor import _build_runner


# optimizer name -> fused update op (ops/optimizer_ops.py). All state
# tensors are zeros-initialized; Adam gets the python-optimizer bias
# correction folded into a traced lr (optimizer.py Adam parity).
_OPT_OPS = {
    "sgd": lambda kw: ("sgd_mom_update" if kw.get("momentum")
                       else "sgd_update"),
    "adam": "adam_update",
    "rmsprop": "rmsprop_update",
    "rmspropalex": "rmspropalex_update",
    "ftrl": "ftrl_update",
    "signsgd": "signsgd_update",
    "signum": "signum_update",
    "ftml": "ftml_update",
}


class DataParallelTrainer:
    """Compile a full training step for a Symbol over a 1-D data mesh.

    Parameters are replicated; `data_names`/`label_names` inputs are sharded
    on axis 0 over the mesh's `data` axis. The optimizer update (any op in
    _OPT_OPS) is fused into the step; the learning rate and step count ride
    as traced scalars so schedules never retrace. This is the fully-fused
    engine behind bench.py and the dryrun_multichip driver hook.
    """

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.0, wd=0.0, rescale_grad=None,
                 clip_gradient=None, loss_index=0, dtype="float32",
                 input_preproc=None, **opt_kwargs):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ops.registry import get_op, AttrDict, OpCtx

        self._symbol = symbol
        self._mesh = mesh
        self._data_axis = mesh.axis_names[0]
        arg_names = symbol.list_arguments()
        self._arg_names = arg_names
        self._aux_names = symbol.list_auxiliary_states()
        input_names = list(data_names) + list(label_names)
        self._input_names = [n for n in arg_names if n in input_names]
        self._param_names = [n for n in arg_names if n not in input_names]
        self._param_pos = [arg_names.index(n) for n in self._param_names]
        self._input_pos = [arg_names.index(n) for n in self._input_names]
        self._lr = float(learning_rate)
        self._loss_index = loss_index
        self._t = 0
        # device-carried step state (see step()): rng key, lr, step count
        self._rng_dev = None
        self._lr_dev = None
        self._t_dev = None
        if dtype not in ("float32", "bfloat16"):
            raise MXNetError("DataParallelTrainer dtype must be float32 or "
                             "bfloat16")
        # bf16 = multi-precision training (reference optimizer
        # multi_precision, SURVEY §7 hard-part 5): fp32 master params/aux,
        # compute + activations in bfloat16, grads upcast before the fused
        # fp32 update. ~1.7x step throughput on v5e for ResNet-50.
        self._compute_bf16 = dtype == "bfloat16"

        hp = dict(opt_kwargs)
        if momentum:
            hp["momentum"] = momentum
        opt_op = _OPT_OPS.get(optimizer)
        if opt_op is None:
            raise MXNetError(
                f"DataParallelTrainer: fused optimizer {optimizer!r} not "
                f"supported ({sorted(_OPT_OPS)}); use Module+kvstore for "
                "host-updated optimizers")
        opname = opt_op(hp) if callable(opt_op) else opt_op
        schema = get_op(opname)
        self._opt_schema = schema
        # states = the op's aux inputs beyond (weight, grad)
        self._n_states = len(schema.input_names) - 2
        # built-in knobs are filtered to what the op takes; user opt_kwargs
        # go through UNfiltered so parse_attrs fails fast on typos
        attr_kwargs = {k: v for k, v in
                       {"lr": self._lr, "wd": wd,
                        "rescale_grad": 1.0 if rescale_grad is None
                        else rescale_grad,
                        "clip_gradient": clip_gradient,
                        "t": 1 if "t" in schema.params else None}.items()
                       if k in schema.params and v is not None}
        attr_kwargs.update(hp)
        attrs = schema.parse_attrs(attr_kwargs)

        run = _build_runner(symbol, is_train=True,
                            platform=mesh.devices.flat[0].platform)
        n_args = len(arg_names)
        param_pos = list(self._param_pos)
        input_pos = list(self._input_pos)
        loss_index = self._loss_index
        fcompute = schema.fcompute
        has_t = "t" in schema.params
        is_adam = optimizer == "adam"
        compute_bf16 = self._compute_bf16
        data_name_set = frozenset(data_names)
        cast_input = [arg_names[p] in data_name_set for p in input_pos]
        # input_preproc(name, value) -> value runs INSIDE the compiled
        # step, before any bf16 cast — the device-side half of the
        # ship-uint8/normalize-on-chip input regime (pair with
        # ImageRecordIter(output_dtype="uint8")); XLA fuses it into the
        # first conv's input chain
        preproc_names = [arg_names[p] for p in input_pos]

        def step(params, states, aux, inputs, rng, lr, t):
            # rng and t are device-carried: split/increment INSIDE the
            # compiled step so the host never dispatches a per-step key
            # split or scalar transfer (through a remote PJRT tunnel each
            # of those is a serializing round-trip)
            rng, next_rng = jax.random.split(rng)
            t = t + 1.0

            def loss_fn(params):
                args = [None] * n_args
                for p, v in zip(param_pos, params):
                    args[p] = jnp.asarray(v, jnp.bfloat16) \
                        if compute_bf16 else v
                for p, v, cast, nm in zip(input_pos, inputs, cast_input,
                                          preproc_names):
                    if input_preproc is not None:
                        v = input_preproc(nm, v)
                    # only FLOAT inputs cast: integer data (embedding token
                    # ids) would be corrupted by bf16's 8-bit mantissa
                    args[p] = jnp.asarray(v, jnp.bfloat16) \
                        if compute_bf16 and cast and \
                        jnp.issubdtype(v.dtype, jnp.floating) else v
                # aux (BN running stats) stays fp32: _batch_norm casts at
                # use sites, and the EMA update must accumulate in fp32 —
                # a bf16 round-trip would quantize the running stats
                outputs, new_aux = run(tuple(args), aux, rng)
                # summing the (custom-vjp) head over the sharded batch is
                # what makes XLA insert the gradient psum over ICI
                loss = outputs[loss_index].sum()
                return loss.astype(jnp.float32), (new_aux, outputs)

            # grads are already fp32: the bf16 input casts transpose back
            # to the fp32 primal dtype
            (loss, (new_aux, outputs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            eff_lr = lr
            if is_adam:  # python Adam's bias correction (optimizer.py)
                b1, b2 = attrs["beta1"], attrs["beta2"]
                eff_lr = lr * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
            a2 = AttrDict(attrs)
            a2["lr"] = eff_lr
            if has_t:
                a2["t"] = t
            octx = OpCtx(is_train=True)
            new_params, new_states = [], []
            for w, g, st in zip(params, grads, states):
                res = fcompute(a2, octx, w, g, *st)
                new_params.append(res[0])
                new_states.append(tuple(res[1:]))
            return (tuple(new_params), tuple(new_states), new_aux, loss,
                    outputs, next_rng, t)

        repl = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P(self._data_axis))
        # stacked (K, batch, ...) blocks for step_k: scan axis replicated,
        # batch axis (axis 1) sharded over the mesh
        self._block_shard = NamedSharding(mesh, P(None, self._data_axis))
        self._repl, self._shard = repl, shard
        self._step_py = step
        self._multi = {}   # (k, outputs_mode) -> jitted K-step scan
        self._step = jax.jit(
            step,
            in_shardings=(repl, repl, repl, shard, repl, repl, repl),
            out_shardings=(repl, repl, repl, repl, shard, repl, repl),
            donate_argnums=(0, 1))

    def _multi_step_fn(self, k, outputs_mode, unroll=False):
        """K training steps fused into ONE compiled dispatch (a lax.scan
        over the single-step body). This is the op-bulking concern of the
        reference engine (graph_executor.cc:1343-1369) applied at step
        granularity: through a remote PJRT tunnel each python dispatch
        costs ~1-8 ms, so amortizing it over K steps is worth up to 4x on
        small-step models (measured on the LSTM LM lane, docs/ROUND4.md).
        rng and the step counter are carried on-device across the scan, so
        K fused steps are bit-identical to K python-dispatched steps."""
        # True==1 as a dict key but lax.scan treats them differently
        # (True = full unroll, 1 = rolled): normalize True to "full"
        key = (int(k), outputs_mode,
               "full" if unroll is True else max(1, int(unroll)))
        fn = self._multi.get(key)
        if fn is not None:
            return fn
        step = self._step_py

        def multi(params, states, aux, inputs, rng, lr, t):
            def body(carry, xs):
                params, states, aux, rng, t = carry
                params, states, aux, loss, outputs, rng, t = step(
                    params, states, aux, xs, rng, lr, t)
                ys = (loss, outputs) if outputs_mode == "all" else loss
                return (params, states, aux, rng, t), ys

            (params, states, aux, rng, t), ys = jax.lax.scan(
                body, (params, states, aux, rng, t), inputs, length=key[0],
                unroll=True if key[2] == "full" else key[2])
            if outputs_mode == "all":
                losses, outputs = ys
            else:
                losses, outputs = ys, ()
            return params, states, aux, losses, outputs, rng, t

        repl, block = self._repl, self._block_shard
        fn = jax.jit(
            multi,
            in_shardings=(repl, repl, repl, block, repl, repl, repl),
            out_shardings=(repl, repl, repl, repl,
                           block if outputs_mode == "all" else repl,
                           repl, repl),
            donate_argnums=(0, 1))
        self._multi[key] = fn
        return fn

    @property
    def param_names(self):
        return list(self._param_names)

    @property
    def input_names(self):
        return list(self._input_names)

    @property
    def aux_names(self):
        return list(self._aux_names)

    def init_state(self, shape_kwargs, initializer=None, seed=0,
                   arg_params=None, aux_params=None):
        """Infer shapes from input shapes; return (params, states, aux)
        tuples of replicated jax arrays. `states` holds one tuple of
        optimizer-state arrays per parameter (momenta for sgd, mean/var for
        adam, ...). `arg_params`/`aux_params` (name -> NDArray/array)
        seed values directly — Module's fused fit hands over the params it
        already initialized so both fit paths start from the same draw."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_kwargs)
        shapes = dict(zip(self._arg_names, arg_shapes))
        rng = _np.random.RandomState(seed)
        params = []
        for n in self._param_names:
            s = shapes[n]
            if arg_params is not None and n in arg_params:
                a = arg_params[n]
                v = _np.asarray(getattr(a, "_data", a), _np.float32)
            elif initializer is not None:
                from ..ndarray.ndarray import zeros as nd_zeros
                arr = nd_zeros(s)
                from ..initializer import InitDesc
                initializer(InitDesc(n), arr)
                v = _np.asarray(arr._data)
            else:
                v = rng.normal(0, 0.01, size=s).astype(_np.float32)
            # host numpy straight onto the mesh (see shard_inputs)
            params.append(jax.device_put(v, self._repl))
        states = tuple(
            tuple(jax.device_put(_np.zeros(p.shape, p.dtype), self._repl)
                  for _ in range(self._n_states))
            for p in params)
        aux = tuple(jax.device_put(
            _np.asarray(getattr(aux_params[n], "_data", aux_params[n]),
                        _np.float32)
            if aux_params is not None and n in aux_params
            # moving/running variances start at 1 (MXNet BatchNorm parity)
            else _np.ones(s, _np.float32)
            if n.endswith(("moving_var", "running_var"))
            else _np.zeros(s, _np.float32), self._repl)
            for n, s in zip(self._aux_names, aux_shapes))
        return tuple(params), states, aux

    def shard_inputs(self, arrays, stacked=False):
        """Commit host batch arrays to the mesh, sharded on the batch axis.

        `stacked=False`: per-step (batch, ...) arrays, sharded on axis 0.
        `stacked=True`: (K, batch, ...) blocks for step_k — the scan axis
        stays replicated and axis 1 (batch) is sharded.

        Host numpy goes straight to the mesh sharding — never through
        `jnp.asarray`, which would commit to the *default* device first
        (wrong platform when the mesh is not on the default backend).
        """
        sharding = self._block_shard if stacked else self._shard
        out = []
        for a in arrays:
            a = getattr(a, "_data", a)
            if not isinstance(a, jax.Array):
                a = _np.asarray(a)
            out.append(jax.device_put(a, sharding))
        return tuple(out)

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        """Schedules never retrace: lr is a traced input to the step."""
        self._lr = float(lr)
        self._lr_dev = None  # re-commit on next step

    def replicate_inputs(self, arrays):
        """Commit host arrays to the mesh, replicated (e.g. eval inputs)."""
        out = []
        for a in arrays:
            a = getattr(a, "_data", a)
            if not isinstance(a, jax.Array):
                a = _np.asarray(a)
            out.append(jax.device_put(a, self._repl))
        return tuple(out)

    def step(self, params, states, aux, inputs, rng=None):
        if rng is not None:
            # explicit key (tests/reproducibility): commit it to the mesh —
            # it may have been minted on the default backend
            self._rng_dev = jax.device_put(rng, self._repl)
        elif self._rng_dev is None:
            from .. import random as _random
            self._rng_dev = jax.device_put(_random.next_key(), self._repl)
        if self._lr_dev is None:
            self._lr_dev = jax.device_put(_np.float32(self._lr), self._repl)
        if self._t_dev is None:
            self._t_dev = jax.device_put(_np.float32(self._t), self._repl)
        out = self._step(params, states, aux, inputs, self._rng_dev,
                         self._lr_dev, self._t_dev)
        # rng/t are device-carried (split/incremented inside the step): the
        # host never dispatches per-step key splits or scalar transfers
        self._rng_dev, self._t_dev = out[5], out[6]
        return out[:5]

    def step_k(self, params, states, aux, inputs, rng=None,
               outputs_mode="none", unroll=False):
        """Run K fused training steps in ONE dispatch (steps_per_dispatch).

        `inputs` are (K, batch, ...) stacked blocks (shard_inputs with
        stacked=True); K is read off the leading axis and each distinct K
        compiles once (cached). Returns (params, states, aux, losses,
        outputs) where `losses` has shape (K,). `outputs_mode`:
          - "none" (default): outputs is () — nothing beyond the losses
            leaves the scan (an LSTM LM's stacked logits would be GBs).
          - "all": outputs are the symbol outputs of EVERY step, stacked
            on a leading K axis (Module's fused fit uses this to feed the
            training metric).
        Bit-identical to K step() calls from the same rng key: the scan
        body IS the single-step body and the key chain is the same splits.

        `unroll=True` unrolls the K-step scan into straight-line code:
        K x compile time, but programs whose step itself contains
        lax.while/scan loops (RNNs) avoid the nested-loop overhead XLA
        adds around inner loops (measured on v5e: the LSTM LM step's
        inner whiles run 3x slower under an outer rolled scan; unrolled
        they run at single-step device speed).
        """
        if rng is not None:
            self._rng_dev = jax.device_put(rng, self._repl)
        elif self._rng_dev is None:
            from .. import random as _random
            self._rng_dev = jax.device_put(_random.next_key(), self._repl)
        if self._lr_dev is None:
            self._lr_dev = jax.device_put(_np.float32(self._lr), self._repl)
        if self._t_dev is None:
            self._t_dev = jax.device_put(_np.float32(self._t), self._repl)
        k = int(inputs[0].shape[0])
        fn = self._multi_step_fn(k, outputs_mode, unroll)
        out = fn(params, states, aux, inputs, self._rng_dev, self._lr_dev,
                 self._t_dev)
        self._rng_dev, self._t_dev = out[5], out[6]
        return out[:5]
