"""Data-parallel training: one compiled step over a device mesh.

Role of the reference stack {DataParallelExecutorGroup → kvstore device/NCCL
reduce → optimizer update ops} (SURVEY.md §2.3, §3.1-3.5), collapsed into a
single pjit-sharded XLA program: fwd + bwd + grad-psum + SGD/momentum update.
Gradient reduction is implicit — the loss sums over the batch axis that is
sharded across the mesh, so XLA emits the psum over ICI; no push/pull, no
per-device executor replicas, no host round-trips inside the step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..executor import _build_runner


class DataParallelTrainer:
    """Compile a full training step for a Symbol over a 1-D data mesh.

    Parameters are replicated; `data_names`/`label_names` inputs are sharded
    on axis 0 over the mesh's `data` axis. The optimizer (sgd / sgd_mom) is
    fused into the step. This is the engine under Module's multi-context
    path and the dryrun_multichip driver hook.
    """

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.0, wd=0.0, rescale_grad=None,
                 loss_index=0):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._symbol = symbol
        self._mesh = mesh
        self._data_axis = mesh.axis_names[0]
        arg_names = symbol.list_arguments()
        self._arg_names = arg_names
        self._aux_names = symbol.list_auxiliary_states()
        input_names = list(data_names) + list(label_names)
        self._input_names = [n for n in arg_names if n in input_names]
        self._param_names = [n for n in arg_names if n not in input_names]
        self._param_pos = [arg_names.index(n) for n in self._param_names]
        self._input_pos = [arg_names.index(n) for n in self._input_names]
        self._lr = float(learning_rate)
        self._momentum = float(momentum)
        self._wd = float(wd)
        self._rescale = rescale_grad
        self._loss_index = loss_index
        if optimizer not in ("sgd",):
            raise MXNetError(
                f"DataParallelTrainer: fused optimizer {optimizer!r} not "
                "supported (sgd/sgd-momentum); use Module+kvstore instead")

        run = _build_runner(symbol, is_train=True)
        n_args = len(arg_names)
        param_pos = list(self._param_pos)
        input_pos = list(self._input_pos)
        lr, mom, wd = self._lr, self._momentum, self._wd
        rescale = self._rescale
        loss_index = self._loss_index

        def step(params, momenta, aux, inputs, rng):
            def loss_fn(params):
                args = [None] * n_args
                for p, v in zip(param_pos, params):
                    args[p] = v
                for p, v in zip(input_pos, inputs):
                    args[p] = v
                outputs, new_aux = run(tuple(args), aux, rng)
                # summing the (custom-vjp) head over the sharded batch is
                # what makes XLA insert the gradient psum over ICI
                loss = outputs[loss_index].sum()
                return loss, (new_aux, outputs)

            (loss, (new_aux, outputs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            scale = rescale if rescale is not None else 1.0
            new_params, new_momenta = [], []
            for w, g, m in zip(params, grads, momenta):
                g = g * jnp.asarray(scale, g.dtype) + \
                    jnp.asarray(wd, w.dtype) * w
                if mom != 0.0:
                    m = jnp.asarray(mom, m.dtype) * m - \
                        jnp.asarray(lr, w.dtype) * g
                    w = w + m
                else:
                    w = w - jnp.asarray(lr, w.dtype) * g
                new_params.append(w)
                new_momenta.append(m)
            return (tuple(new_params), tuple(new_momenta), new_aux, loss,
                    outputs)

        repl = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P(self._data_axis))
        self._repl, self._shard = repl, shard
        self._step = jax.jit(
            step,
            in_shardings=(repl, repl, repl, shard, repl),
            out_shardings=(repl, repl, repl, repl, shard),
            donate_argnums=(0, 1))

    @property
    def param_names(self):
        return list(self._param_names)

    @property
    def input_names(self):
        return list(self._input_names)

    def init_state(self, shape_kwargs, initializer=None, seed=0):
        """Infer shapes from input shapes; return (params, momenta, aux)
        tuples of replicated jax arrays."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_kwargs)
        shapes = dict(zip(self._arg_names, arg_shapes))
        rng = _np.random.RandomState(seed)
        params = []
        for n in self._param_names:
            s = shapes[n]
            if initializer is not None:
                from ..ndarray.ndarray import zeros as nd_zeros
                arr = nd_zeros(s)
                from ..initializer import InitDesc
                initializer(InitDesc(n), arr)
                v = _np.asarray(arr._data)
            else:
                v = rng.normal(0, 0.01, size=s).astype(_np.float32)
            # host numpy straight onto the mesh (see shard_inputs)
            params.append(jax.device_put(v, self._repl))
        momenta = tuple(jax.device_put(_np.zeros(p.shape, p.dtype),
                                       self._repl)
                        for p in params)
        aux = tuple(jax.device_put(
            # moving variances start at 1 (MXNet BatchNorm aux parity)
            _np.ones(s, _np.float32) if n.endswith("moving_var")
            else _np.zeros(s, _np.float32), self._repl)
            for n, s in zip(self._aux_names, aux_shapes))
        return tuple(params), momenta, aux

    def shard_inputs(self, arrays):
        """Commit host batch arrays to the mesh, sharded on axis 0.

        Host numpy goes straight to the mesh sharding — never through
        `jnp.asarray`, which would commit to the *default* device first
        (wrong platform when the mesh is not on the default backend).
        """
        out = []
        for a in arrays:
            a = getattr(a, "_data", a)
            if not isinstance(a, jax.Array):
                a = _np.asarray(a)
            out.append(jax.device_put(a, self._shard))
        return tuple(out)

    def step(self, params, momenta, aux, inputs, rng=None):
        if rng is None:
            from .. import random as _random
            rng = _random.next_key()
        # the key may have been minted on the default backend; commit it to
        # the mesh so the step never mixes platforms
        rng = jax.device_put(rng, self._repl)
        return self._step(params, momenta, aux, inputs, rng)
