"""Data-parallel training: one compiled step over a device mesh.

Role of the reference stack {DataParallelExecutorGroup → kvstore device/NCCL
reduce → optimizer update ops} (SURVEY.md §2.3, §3.1-3.5), collapsed into a
single pjit-sharded XLA program: fwd + bwd + grad-psum + SGD/momentum update.
Gradient reduction is implicit — the loss sums over the batch axis that is
sharded across the mesh, so XLA emits the psum over ICI; no push/pull, no
per-device executor replicas, no host round-trips inside the step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..executor import _build_runner
from .mesh import data_axis as _mesh_data_axis


# optimizer name -> fused update op (ops/optimizer_ops.py). All state
# tensors are zeros-initialized; Adam gets the python-optimizer bias
# correction folded into a traced lr (optimizer.py Adam parity).
_OPT_OPS = {
    "sgd": lambda kw: ("sgd_mom_update" if kw.get("momentum")
                       else "sgd_update"),
    "adam": "adam_update",
    "rmsprop": "rmsprop_update",
    "rmspropalex": "rmspropalex_update",
    "ftrl": "ftrl_update",
    "signsgd": "signsgd_update",
    "signum": "signum_update",
    "ftml": "ftml_update",
}


class DataParallelTrainer:
    """Compile a full training step for a Symbol over a 1-D data mesh.

    Parameters are replicated; `data_names`/`label_names` inputs are sharded
    on axis 0 over the mesh's `data` axis. The optimizer update (any op in
    _OPT_OPS) is fused into the step; the learning rate and step count ride
    as traced scalars so schedules never retrace. This is the fully-fused
    engine behind bench.py and the dryrun_multichip driver hook.
    """

    def __new__(cls, *args, **kwargs):
        # MXNET_ZERO_STAGE (or an explicit zero_stage kwarg) reroutes
        # plain DataParallelTrainer construction to the ZeRO-sharded
        # engine (parallel/zero.py) — same constructor surface, same
        # step contract, sharded masters/optimizer state. Subclasses
        # dispatch themselves, so only direct construction reroutes.
        if cls is DataParallelTrainer:
            from .zero import resolve_stage, ZeroTrainer
            if resolve_stage(kwargs.get("zero_stage")) > 0:
                return object.__new__(ZeroTrainer)
        return object.__new__(cls)

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.0, wd=0.0, rescale_grad=None,
                 clip_gradient=None, loss_index=0, dtype="float32",
                 input_preproc=None, loss_scaler=None, param_specs=None,
                 zero_stage=None, zero_bucket_mb=None, grad_compress=None,
                 **opt_kwargs):
        # zero_stage/zero_bucket_mb/grad_compress belong to the ZeRO
        # subclass; accepted (and ignored) here so a stage-0 run can keep
        # them in its construction kwargs
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ops.registry import get_op, AttrDict, OpCtx

        self._symbol = symbol
        self._mesh = mesh
        self._data_axis = _mesh_data_axis(mesh)
        arg_names = symbol.list_arguments()
        self._arg_names = arg_names
        self._aux_names = symbol.list_auxiliary_states()
        input_names = list(data_names) + list(label_names)
        self._input_names = [n for n in arg_names if n in input_names]
        self._param_names = [n for n in arg_names if n not in input_names]
        self._param_pos = [arg_names.index(n) for n in self._param_names]
        self._input_pos = [arg_names.index(n) for n in self._input_names]
        self._lr = float(learning_rate)
        self._loss_index = loss_index
        self._t = 0
        # device-carried step state (see step()): rng key, lr, step count
        self._rng_dev = None
        self._lr_dev = None
        self._t_dev = None
        if dtype not in ("float32", "bfloat16", "float16"):
            raise MXNetError("DataParallelTrainer dtype must be float32, "
                             "bfloat16 or float16")
        # half precision = multi-precision training (reference optimizer
        # multi_precision, SURVEY §7 hard-part 5): fp32 master params/aux,
        # compute + activations + the gradient all-reduce in the half
        # dtype, grads upcast into the fused fp32 update. ~1.7x step
        # throughput on v5e for ResNet-50, and the half-width all-reduce
        # halves the wire bytes of the collective-bound dp step
        # (MULTICHIP_r05: 5.9ms -> 28.3ms from 1 -> 8 devices was one
        # sync fp32 all-reduce).
        self._compute_bf16 = dtype == "bfloat16"
        self._dtype = dtype
        compute_dtype = {"float32": None, "bfloat16": jnp.bfloat16,
                         "float16": jnp.float16}[dtype]
        self._compute_dtype = compute_dtype
        # fp16's 5-bit exponent flushes small grads to zero and overflows
        # large activations: wire in dynamic loss scaling (amp/scaler.py)
        # with non-finite step skip. bf16 keeps fp32's exponent range and
        # needs none of this (docs/AMP.md).
        self._has_ls = dtype == "float16"
        if self._has_ls and loss_scaler is None:
            from ..amp.scaler import DynamicLossScaler
            loss_scaler = DynamicLossScaler()
        self._scaler = loss_scaler if self._has_ls else None
        self._ls_dev = None
        if self._has_ls:
            from .. import amp as _amp
            _amp._register_scale_source(self)

        hp = dict(opt_kwargs)
        if momentum:
            hp["momentum"] = momentum
        opt_op = _OPT_OPS.get(optimizer)
        if opt_op is None:
            raise MXNetError(
                f"DataParallelTrainer: fused optimizer {optimizer!r} not "
                f"supported ({sorted(_OPT_OPS)}); use Module+kvstore for "
                "host-updated optimizers")
        opname = opt_op(hp) if callable(opt_op) else opt_op
        schema = get_op(opname)
        self._opt_schema = schema
        # states = the op's aux inputs beyond (weight, grad)
        self._n_states = len(schema.input_names) - 2
        # built-in knobs are filtered to what the op takes; user opt_kwargs
        # go through UNfiltered so parse_attrs fails fast on typos
        attr_kwargs = {k: v for k, v in
                       {"lr": self._lr, "wd": wd,
                        "rescale_grad": 1.0 if rescale_grad is None
                        else rescale_grad,
                        "clip_gradient": clip_gradient,
                        "t": 1 if "t" in schema.params else None}.items()
                       if k in schema.params and v is not None}
        attr_kwargs.update(hp)
        attrs = schema.parse_attrs(attr_kwargs)

        run = _build_runner(symbol, is_train=True,
                            platform=mesh.devices.flat[0].platform)
        n_args = len(arg_names)
        param_pos = list(self._param_pos)
        input_pos = list(self._input_pos)
        loss_index = self._loss_index
        fcompute = schema.fcompute
        has_t = "t" in schema.params
        is_adam = optimizer == "adam"
        compute_dtype = self._compute_dtype
        has_ls = self._has_ls
        scaler = self._scaler
        data_name_set = frozenset(data_names)
        cast_input = [arg_names[p] in data_name_set for p in input_pos]
        # input_preproc(name, value) -> value runs INSIDE the compiled
        # step, before any bf16 cast — the device-side half of the
        # ship-uint8/normalize-on-chip input regime (pair with
        # ImageRecordIter(output_dtype="uint8")); XLA fuses it into the
        # first conv's input chain
        preproc_names = [arg_names[p] for p in input_pos]
        # the step-building surface, kept on self so subclasses
        # (parallel/zero.py) can assemble their own step program from the
        # same runner/optimizer-op plumbing
        self._run = run
        self._fcompute = fcompute
        self._attrs = attrs
        self._has_t = has_t
        self._is_adam = is_adam
        self._cast_input = cast_input
        self._preproc_names = preproc_names
        self._input_preproc = input_preproc

        def _step_impl(params, states, aux, inputs, rng, lr, t, ls):
            # rng and t are device-carried: split/increment INSIDE the
            # compiled step so the host never dispatches a per-step key
            # split or scalar transfer (through a remote PJRT tunnel each
            # of those is a serializing round-trip)
            rng, next_rng = jax.random.split(rng)
            scale = ls[0] if has_ls else None

            # params are cast to the compute dtype OUTSIDE loss_fn and
            # differentiated AT the cast values: grad dtype == primal
            # dtype, so the batch-axis psum XLA inserts reduces
            # HALF-WIDTH words over ICI (the bf16 all-reduce). The fp32
            # upcast in the update below is the exact transpose of the
            # cast, so the update sees the same values as differentiating
            # the fp32 masters directly — only the all-reduce narrows.
            cparams = params if compute_dtype is None else tuple(
                jnp.asarray(v, compute_dtype) for v in params)

            def loss_fn(cparams):
                args = [None] * n_args
                for p, v in zip(param_pos, cparams):
                    args[p] = v
                for p, v, cast, nm in zip(input_pos, inputs, cast_input,
                                          preproc_names):
                    if input_preproc is not None:
                        v = input_preproc(nm, v)
                    # only FLOAT inputs cast: integer data (embedding token
                    # ids) would be corrupted by the half dtype's mantissa
                    args[p] = jnp.asarray(v, compute_dtype) \
                        if compute_dtype is not None and cast and \
                        jnp.issubdtype(v.dtype, jnp.floating) else v
                # aux (BN running stats) stays fp32: _batch_norm casts at
                # use sites, and the EMA update must accumulate in fp32 —
                # a half round-trip would quantize the running stats
                outputs, new_aux = run(tuple(args), aux, rng)
                # summing the (custom-vjp) head over the sharded batch is
                # what makes XLA insert the gradient psum over ICI
                loss = outputs[loss_index].sum().astype(jnp.float32)
                # fp16: backprop the SCALED loss so small-magnitude grads
                # stay representable; the unscaled loss rides has_aux.
                # NOTE this only reaches the gradients when the loss is an
                # ordinary differentiable value — the legacy loss heads
                # (SoftmaxOutput & co) IGNORE the incoming cotangent, so
                # for them the scale is injected below the head instead
                # (amp.LOSS_HEADS + the trace scale set around this trace)
                obj = loss * scale if has_ls else loss
                return obj, (new_aux, outputs, loss)

            if has_ls:
                from .. import amp as _amp
                _amp._set_trace_loss_scale(scale)
            try:
                (_, (new_aux, outputs, loss)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(cparams)
            finally:
                if has_ls:
                    from .. import amp as _amp
                    _amp._set_trace_loss_scale(None)
            if has_ls:
                # overflow check on the SCALED half grads (post-psum):
                # any inf/nan skips the whole update and backs the scale
                # off (Micikevicius et al. 2018 §3.2)
                finite = jnp.asarray(True)
                for g in grads:
                    finite = jnp.logical_and(finite,
                                             jnp.all(jnp.isfinite(g)))
                # a skipped step is not an update: t (Adam bias
                # correction) advances only on applied steps
                t = t + jnp.where(finite, 1.0, 0.0)
                inv_scale = 1.0 / scale
            else:
                t = t + 1.0
            eff_lr = lr
            if is_adam:  # python Adam's bias correction (optimizer.py)
                b1, b2 = attrs["beta1"], attrs["beta2"]
                eff_lr = lr * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
            a2 = AttrDict(attrs)
            a2["lr"] = eff_lr
            if has_t:
                a2["t"] = t
            octx = OpCtx(is_train=True)
            new_params, new_states = [], []
            for w, g, st in zip(params, grads, states):
                # upcast into the fused fp32 master update; fp16 also
                # unscales — in fp32, so an overflowed grad stays inf
                # (detectable above) instead of wrapping
                if g.dtype != jnp.float32:
                    g = g.astype(jnp.float32)
                if has_ls:
                    g = g * inv_scale
                res = fcompute(a2, octx, w, g, *st)
                if has_ls:
                    # skipped step: params/states stay bit-identical
                    new_params.append(jnp.where(finite, res[0], w))
                    new_states.append(tuple(
                        jnp.where(finite, s, s0)
                        for s, s0 in zip(res[1:], st)))
                else:
                    new_params.append(res[0])
                    new_states.append(tuple(res[1:]))
            if has_ls:
                # an overflowed forward would poison BN running stats too
                new_aux = tuple(jnp.where(finite, a, a0)
                                for a, a0 in zip(new_aux, aux))
                new_ls = scaler.update_state(ls, finite)
                return (tuple(new_params), tuple(new_states), new_aux,
                        loss, outputs, next_rng, t, new_ls)
            return (tuple(new_params), tuple(new_states), new_aux, loss,
                    outputs, next_rng, t)

        # the loss-scaler state rides the step signature ONLY for fp16:
        # fp32/bf16 keep the 7-arg step so existing lower()/cost-analysis
        # call sites (bench.py, __graft_entry__) stay valid
        if has_ls:
            def step(params, states, aux, inputs, rng, lr, t, ls):
                return _step_impl(params, states, aux, inputs, rng, lr,
                                  t, ls)
        else:
            def step(params, states, aux, inputs, rng, lr, t):
                return _step_impl(params, states, aux, inputs, rng, lr,
                                  t, None)

        repl = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P(self._data_axis))
        # stacked (K, batch, ...) blocks for step_k: scan axis replicated,
        # batch axis (axis 1) sharded over the mesh
        self._block_shard = NamedSharding(mesh, P(None, self._data_axis))
        self._repl, self._shard = repl, shard
        # param_specs (name -> PartitionSpec) turns on GSPMD tensor
        # parallelism: the listed params (and their optimizer state) live
        # sharded over the named mesh axes and XLA's partitioner inserts
        # the megatron-style collectives around the matmuls. None keeps
        # today's replicated-params program BIT-identical (same jit, same
        # sharding tuple); unlisted params stay replicated.
        self._param_specs = None
        self._pshard = None
        if param_specs:
            self._param_specs = {str(k): v
                                 for k, v in dict(param_specs).items()}
            self._pshard = tuple(
                NamedSharding(mesh, self._param_specs.get(n, P()))
                for n in self._param_names)
        p_io = self._pshard if self._pshard is not None else repl
        self._step_py = step
        self._multi = {}   # (k, outputs_mode) -> jitted K-step scan
        ls_extra = (repl,) if has_ls else ()
        self._step = jax.jit(
            step,
            in_shardings=(p_io, p_io, repl, shard, repl, repl, repl)
            + ls_extra,
            out_shardings=(p_io, p_io, repl, repl, shard, repl, repl)
            + ls_extra,
            donate_argnums=(0, 1))

    def _multi_step_fn(self, k, outputs_mode, unroll=False):
        """K training steps fused into ONE compiled dispatch (a lax.scan
        over the single-step body). This is the op-bulking concern of the
        reference engine (graph_executor.cc:1343-1369) applied at step
        granularity: through a remote PJRT tunnel each python dispatch
        costs ~1-8 ms, so amortizing it over K steps is worth up to 4x on
        small-step models (measured on the LSTM LM lane, docs/ROUND4.md).
        rng, the step counter and (fp16) the loss-scaler state are carried
        on-device across the scan, so K fused steps are bit-identical to K
        python-dispatched steps — including grow/backoff/skip decisions."""
        # True==1 as a dict key but lax.scan treats them differently
        # (True = full unroll, 1 = rolled): normalize True to "full"
        key = (int(k), outputs_mode,
               "full" if unroll is True else max(1, int(unroll)))
        fn = self._multi.get(key)
        if fn is not None:
            return fn
        step = self._step_py
        unroll_arg = True if key[2] == "full" else key[2]

        if self._has_ls:
            def multi(params, states, aux, inputs, rng, lr, t, ls):
                def body(carry, xs):
                    params, states, aux, rng, t, ls = carry
                    (params, states, aux, loss, outputs, rng, t,
                     ls) = step(params, states, aux, xs, rng, lr, t, ls)
                    ys = (loss, outputs) if outputs_mode == "all" else loss
                    return (params, states, aux, rng, t, ls), ys

                (params, states, aux, rng, t, ls), ys = jax.lax.scan(
                    body, (params, states, aux, rng, t, ls), inputs,
                    length=key[0], unroll=unroll_arg)
                if outputs_mode == "all":
                    losses, outputs = ys
                else:
                    losses, outputs = ys, ()
                return params, states, aux, losses, outputs, rng, t, ls
        else:
            def multi(params, states, aux, inputs, rng, lr, t):
                def body(carry, xs):
                    params, states, aux, rng, t = carry
                    params, states, aux, loss, outputs, rng, t = step(
                        params, states, aux, xs, rng, lr, t)
                    ys = (loss, outputs) if outputs_mode == "all" else loss
                    return (params, states, aux, rng, t), ys

                (params, states, aux, rng, t), ys = jax.lax.scan(
                    body, (params, states, aux, rng, t), inputs,
                    length=key[0], unroll=unroll_arg)
                if outputs_mode == "all":
                    losses, outputs = ys
                else:
                    losses, outputs = ys, ()
                return params, states, aux, losses, outputs, rng, t

        repl, block = self._repl, self._block_shard
        p_io = self._pshard if self._pshard is not None else repl
        ls_extra = (repl,) if self._has_ls else ()
        fn = jax.jit(
            multi,
            in_shardings=(p_io, p_io, repl, block, repl, repl, repl)
            + ls_extra,
            out_shardings=(p_io, p_io, repl, repl,
                           block if outputs_mode == "all" else repl,
                           repl, repl) + ls_extra,
            donate_argnums=(0, 1))
        self._multi[key] = fn
        return fn

    def _param_sharding(self, i):
        """Placement of parameter i (and its optimizer state): its
        param_specs sharding under tensor parallelism, replicated
        otherwise."""
        return self._repl if self._pshard is None else self._pshard[i]

    @property
    def param_names(self):
        return list(self._param_names)

    @property
    def input_names(self):
        return list(self._input_names)

    @property
    def aux_names(self):
        return list(self._aux_names)

    def init_state(self, shape_kwargs, initializer=None, seed=0,
                   arg_params=None, aux_params=None):
        """Infer shapes from input shapes; return (params, states, aux)
        tuples of replicated jax arrays. `states` holds one tuple of
        optimizer-state arrays per parameter (momenta for sgd, mean/var for
        adam, ...). `arg_params`/`aux_params` (name -> NDArray/array)
        seed values directly — Module's fused fit hands over the params it
        already initialized so both fit paths start from the same draw."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**shape_kwargs)
        shapes = dict(zip(self._arg_names, arg_shapes))
        rng = _np.random.RandomState(seed)
        params = []
        for i, n in enumerate(self._param_names):
            s = shapes[n]
            if arg_params is not None and n in arg_params:
                a = arg_params[n]
                v = _np.asarray(getattr(a, "_data", a), _np.float32)
            elif initializer is not None:
                from ..ndarray.ndarray import zeros as nd_zeros
                arr = nd_zeros(s)
                from ..initializer import InitDesc
                initializer(InitDesc(n), arr)
                v = _np.asarray(arr._data)
            else:
                v = rng.normal(0, 0.01, size=s).astype(_np.float32)
            # host numpy straight onto the mesh (see shard_inputs)
            params.append(jax.device_put(v, self._param_sharding(i)))
        states = tuple(
            tuple(jax.device_put(_np.zeros(p.shape, p.dtype),
                                 self._param_sharding(i))
                  for _ in range(self._n_states))
            for i, p in enumerate(params))
        aux = tuple(jax.device_put(
            _np.asarray(getattr(aux_params[n], "_data", aux_params[n]),
                        _np.float32)
            if aux_params is not None and n in aux_params
            # moving/running variances start at 1 (MXNet BatchNorm parity)
            else _np.ones(s, _np.float32)
            if n.endswith(("moving_var", "running_var"))
            else _np.zeros(s, _np.float32), self._repl)
            for n, s in zip(self._aux_names, aux_shapes))
        return tuple(params), states, aux

    def shard_inputs(self, arrays, stacked=False):
        """Commit host batch arrays to the mesh, sharded on the batch axis.

        `stacked=False`: per-step (batch, ...) arrays, sharded on axis 0.
        `stacked=True`: (K, batch, ...) blocks for step_k — the scan axis
        stays replicated and axis 1 (batch) is sharded.

        Host numpy goes straight to the mesh sharding — never through
        `jnp.asarray`, which would commit to the *default* device first
        (wrong platform when the mesh is not on the default backend).
        """
        sharding = self._block_shard if stacked else self._shard
        out = []
        for a in arrays:
            a = getattr(a, "_data", a)
            if not isinstance(a, jax.Array):
                a = _np.asarray(a)
            out.append(jax.device_put(a, sharding))
        return tuple(out)

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        """Schedules never retrace: lr is a traced input to the step."""
        self._lr = float(lr)
        self._lr_dev = None  # re-commit on next step

    def replicate_inputs(self, arrays):
        """Commit host arrays to the mesh, replicated (e.g. eval inputs)."""
        out = []
        for a in arrays:
            a = getattr(a, "_data", a)
            if not isinstance(a, jax.Array):
                a = _np.asarray(a)
            out.append(jax.device_put(a, self._repl))
        return tuple(out)

    def _ensure_dev_state(self, rng):
        if rng is not None:
            # explicit key (tests/reproducibility): commit it to the mesh —
            # it may have been minted on the default backend
            self._rng_dev = jax.device_put(rng, self._repl)
        elif self._rng_dev is None:
            from .. import random as _random
            self._rng_dev = jax.device_put(_random.next_key(), self._repl)
        if self._lr_dev is None:
            self._lr_dev = jax.device_put(_np.float32(self._lr), self._repl)
        if self._t_dev is None:
            self._t_dev = jax.device_put(_np.float32(self._t), self._repl)
        if self._has_ls and self._ls_dev is None:
            self._ls_dev = jax.device_put(self._scaler.state0(), self._repl)

    @property
    def loss_scale(self):
        """Live fp16 loss scale (None when loss scaling is inactive).
        Reads the device-carried scaler state, so it synchronizes."""
        if not self._has_ls:
            return None
        if self._ls_dev is None:
            return float(self._scaler.scale)
        return float(_np.asarray(self._ls_dev)[0])

    @property
    def skipped_steps(self):
        """Steps skipped on non-finite fp16 gradients so far."""
        if not self._has_ls:
            return 0
        if self._ls_dev is None:
            return int(self._scaler.skipped_steps)
        return int(_np.asarray(self._ls_dev)[2])

    def _amp_counters(self):
        """amp counter-export hook (amp.counters aggregates these)."""
        return {"amp_scale": self.loss_scale,
                "amp_skipped_steps": self.skipped_steps}

    # -- host views ---------------------------------------------------------

    def host_params(self, params):
        """name -> host numpy array for the trainer's params tuple. The
        generic spelling fused-fit loops must use for writeback: ZeRO
        subclasses carry flat sharded buckets instead of per-parameter
        replicas, and override this to unflatten them."""
        return {n: _np.asarray(p)
                for n, p in zip(self._param_names, params)}

    def host_aux(self, aux):
        """name -> host numpy array for the aux tuple (replicated on
        every trainer variant)."""
        return {n: _np.asarray(a) for n, a in zip(self._aux_names, aux)}

    # -- checkpoint round-trip ----------------------------------------------

    def _export_meta(self):
        """Scalar device-carried step state (t, rng chain position, fp16
        loss-scaler vector, exporting mesh) — shared by every trainer
        variant's export_training_state."""
        from .. import random as _random
        from .mesh import mesh_descriptor
        return {
            "t": float(self._t if self._t_dev is None
                       else _np.asarray(self._t_dev)),
            "rng": None if self._rng_dev is None
            else _random.key_data(self._rng_dev).ravel().tolist(),
            "loss_scaler": None if not (self._has_ls
                                        and self._ls_dev is not None)
            else [float(x) for x in _np.asarray(self._ls_dev)],
            # the exporting mesh, for the checkpoint TOPOLOGY record —
            # import_training_state ignores it (device_put onto the
            # CURRENT mesh is what reshards an elastic restore)
            "mesh": mesh_descriptor(self._mesh),
        }

    def _import_scalar_state(self, meta):
        """Inverse of _export_meta: restore t/rng/loss-scaler carries."""
        from .. import random as _random
        put = lambda v: jax.device_put(_np.asarray(v), self._repl)
        self._t = float(meta.get("t", 0.0))
        self._t_dev = put(_np.float32(self._t))
        if meta.get("rng") is not None:
            self._rng_dev = jax.device_put(_random.wrap_key(meta["rng"]),
                                           self._repl)
        ls = meta.get("loss_scaler")
        if ls is not None and self._has_ls:
            self._ls_dev = put(_np.asarray(ls, _np.float32))

    def export_training_state(self, params, states, aux):
        """Host snapshot of the full fused-loop training state: the
        (donated, device-carried) params/opt-states/aux tuples as numpy,
        plus the device-carried step counter, PRNG key chain position and
        fp16 loss-scaler vector. Everything mxnet_tpu.checkpoint needs for
        a bit-identical step_k continuation after restore. Must be called
        between dispatches (the tuples are invalidated by the next step's
        donation — copy now, serialize later)."""
        arrays = {}
        for n, p in zip(self._param_names, params):
            arrays[f"param:{n}"] = _np.asarray(p)
        for n, st in zip(self._param_names, states):
            for i, s in enumerate(st):
                arrays[f"opt:{n}:{i}"] = _np.asarray(s)
        for n, a in zip(self._aux_names, aux):
            arrays[f"aux:{n}"] = _np.asarray(a)
        return arrays, self._export_meta()

    def import_training_state(self, arrays, meta):
        """Inverse of export_training_state: re-commit a snapshot to the
        mesh. Returns (params, states, aux) replicated tuples ready for
        step/step_k; the internal t/rng/loss-scaler carries are restored
        so the continuation is bit-identical to the uninterrupted run."""
        put = lambda v: jax.device_put(_np.asarray(v), self._repl)
        pput = lambda v, i: jax.device_put(_np.asarray(v),
                                           self._param_sharding(i))
        params = tuple(pput(arrays[f"param:{n}"], i)
                       for i, n in enumerate(self._param_names))
        states = tuple(
            tuple(pput(arrays[f"opt:{n}:{j}"], i)
                  for j in range(self._n_states))
            for i, n in enumerate(self._param_names))
        aux = tuple(put(arrays[f"aux:{n}"]) for n in self._aux_names)
        self._import_scalar_state(meta)
        return params, states, aux

    def step(self, params, states, aux, inputs, rng=None):
        self._ensure_dev_state(rng)
        from ..telemetry import devstats
        if self._has_ls:
            args = (params, states, aux, inputs, self._rng_dev,
                    self._lr_dev, self._t_dev, self._ls_dev)
            devstats.on_dispatch("dp.step", self._step, args, steps=1)
            out = self._step(*args)
            self._ls_dev = out[7]
        else:
            args = (params, states, aux, inputs, self._rng_dev,
                    self._lr_dev, self._t_dev)
            devstats.on_dispatch("dp.step", self._step, args, steps=1)
            out = self._step(*args)
        # rng/t are device-carried (split/incremented inside the step): the
        # host never dispatches per-step key splits or scalar transfers
        self._rng_dev, self._t_dev = out[5], out[6]
        return out[:5]

    def step_k(self, params, states, aux, inputs, rng=None,
               outputs_mode="none", unroll=False):
        """Run K fused training steps in ONE dispatch (steps_per_dispatch).

        `inputs` are (K, batch, ...) stacked blocks (shard_inputs with
        stacked=True); K is read off the leading axis and each distinct K
        compiles once (cached). Returns (params, states, aux, losses,
        outputs) where `losses` has shape (K,). `outputs_mode`:
          - "none" (default): outputs is () — nothing beyond the losses
            leaves the scan (an LSTM LM's stacked logits would be GBs).
          - "all": outputs are the symbol outputs of EVERY step, stacked
            on a leading K axis (Module's fused fit uses this to feed the
            training metric).
        Bit-identical to K step() calls from the same rng key: the scan
        body IS the single-step body and the key chain is the same splits.

        `unroll=True` unrolls the K-step scan into straight-line code:
        K x compile time, but programs whose step itself contains
        lax.while/scan loops (RNNs) avoid the nested-loop overhead XLA
        adds around inner loops (measured on v5e: the LSTM LM step's
        inner whiles run 3x slower under an outer rolled scan; unrolled
        they run at single-step device speed).
        """
        self._ensure_dev_state(rng)
        k = int(inputs[0].shape[0])
        fn = self._multi_step_fn(k, outputs_mode, unroll)
        from ..telemetry import devstats
        if self._has_ls:
            args = (params, states, aux, inputs, self._rng_dev,
                    self._lr_dev, self._t_dev, self._ls_dev)
            devstats.on_dispatch("dp.step_k%d" % k, fn, args, steps=k)
            out = fn(*args)
            self._ls_dev = out[7]
        else:
            args = (params, states, aux, inputs, self._rng_dev,
                    self._lr_dev, self._t_dev)
            devstats.on_dispatch("dp.step_k%d" % k, fn, args, steps=k)
            out = fn(*args)
        self._rng_dev, self._t_dev = out[5], out[6]
        return out[:5]
