"""jax API-drift shims shared by the parallel modules.

`shard_map` graduated from jax.experimental to the jax namespace; this
image's jax still ships only the experimental home. Import it from here
so sp/tp/pp run on both spellings.
"""
from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:                      # older jax
    import functools
    import inspect
    from jax.experimental.shard_map import shard_map as _experimental

    shard_map = _experimental
    if "check_rep" in inspect.signature(_experimental).parameters:
        # the old replication checker has no rule for pallas_call (new
        # jax replaced it with vma typing, which the kernels satisfy) —
        # default it off; numerics are asserted by the tests either way
        @functools.wraps(_experimental)
        def shard_map(*args, **kwargs):     # noqa: F811
            kwargs.pop("check_vma", None)   # new-jax spelling of the same
            kwargs.setdefault("check_rep", False)
            return _experimental(*args, **kwargs)

try:
    pcast = jax.lax.pcast
except AttributeError:
    # pre-varying-manual-axes jax has no vma typing at all, so there is
    # nothing to cast: identity keeps the carry typecheck happy there
    def pcast(x, axes, to=None):
        return x
