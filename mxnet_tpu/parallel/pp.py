"""Pipeline parallelism — GPipe-style microbatch pipelining over a mesh axis.

Absent from the reference (SURVEY §2.3 lists no PP machinery; its closest
artifact is group2ctx layer placement). TPU-native: each device on the
`pp` axis owns ONE stage's weights; activations flow stage-to-stage with
`jax.lax.ppermute` while microbatches stream in, so after the (n_stages-1)
-tick fill the pipe computes every stage in parallel. Forward-only
schedule (GPipe fill/drain); gradients come from autodiff through the
loop, which replays the same communication pattern in reverse.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from .mesh import axis_size
from .mesh import pcast as _pcast
from .mesh import shard_map as _shard_map

__all__ = ["pipeline_mlp", "pipeline_reference"]


def pipeline_reference(x_micro, w_stack, b_stack):
    """Oracle: run every microbatch through all stages sequentially.
    x_micro (M, B, D); w_stack (S, D, D); b_stack (S, D)."""
    def run_one(x):
        for s in range(w_stack.shape[0]):
            x = jax.nn.relu(x @ w_stack[s] + b_stack[s])
        return x
    return jax.vmap(run_one)(x_micro)


def _pipe_shard(x_micro, w, b, axis_name, n_micro):
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    w = w[0]        # this device's stage weights (leading shard dim of 1)
    b = b[0]
    bsz, d = x_micro.shape[1], x_micro.shape[2]
    ticks = n_micro + n - 1
    fwd_perm = [(i, i + 1) for i in range(n - 1)]     # stage s -> s+1

    # pcast-to-varying marks the carries as device-varying so the fori_loop
    # carry typecheck accepts the (rank-dependent) tick outputs
    y0 = _pcast(jnp.zeros((bsz, d), x_micro.dtype), (axis_name,),
                       to="varying")
    outs0 = _pcast(jnp.zeros((n_micro, bsz, d), x_micro.dtype),
                          (axis_name,), to="varying")

    def tick(t, carry):
        y_prev, outs = carry
        # ship the previous tick's activation down the pipe
        shifted = jax.lax.ppermute(y_prev, axis_name, fwd_perm)
        # stage 0 injects microbatch t (zeros once the stream is drained)
        micro_t = x_micro[jnp.clip(t, 0, n_micro - 1)]
        micro_t = jnp.where(t < n_micro, micro_t, jnp.zeros_like(micro_t))
        inj = jnp.where(rank == 0, micro_t, shifted)
        y = jax.nn.relu(inj @ w + b)
        # the last stage retires microbatch t-(n-1)
        out_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
        retire = (t >= n - 1) & (rank == n - 1)
        upd = jnp.where(retire, y, outs[out_idx])
        outs = jax.lax.dynamic_update_index_in_dim(outs, upd, out_idx, 0)
        return y, outs

    _, outs = jax.lax.fori_loop(0, ticks, tick, (y0, outs0))
    # only the last stage holds real outputs: zero elsewhere, psum shares
    outs = jnp.where(rank == n - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def pipeline_mlp(x_micro, w_stack, b_stack, mesh, axis_name="pp"):
    """Pipelined stack of relu-Dense stages.

    x_micro (M, B, D) microbatches (replicated); w_stack (S, D, D) /
    b_stack (S, D) with S == mesh axis size — stage s lives on device s.
    Returns (M, B, D) replicated outputs.
    """
    n = axis_size(mesh, axis_name)
    if w_stack.shape[0] != n:
        raise MXNetError(
            f"pipeline_mlp: {w_stack.shape[0]} stages but {axis_name} axis "
            f"has {n} devices (one stage per device)")
    fn = _shard_map(
        functools.partial(_pipe_shard, axis_name=axis_name,
                          n_micro=x_micro.shape[0]),
        mesh=mesh,
        in_specs=(P(), P(axis_name, None, None), P(axis_name, None)),
        out_specs=P())
    return fn(x_micro, w_stack, b_stack)
