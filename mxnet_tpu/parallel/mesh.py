"""Mesh helpers: the ONE place device meshes and mesh-axis plumbing
come from.

Every parallel module (dp/zero/tp/pp/sp/embedding, the planner) builds
its mesh through these constructors and imports `shard_map`/`pcast`
from here (re-exported from ._compat, the jax API-drift shim) — a mesh
axis name used anywhere in the package is declared in AXIS_NAMES, and
`axis_size`/`data_axis` replace the ad-hoc `mesh.shape[name]` /
`mesh.axis_names[0]` lookups that used to be copied per module.
"""
from __future__ import annotations

import numpy as np

from ._compat import pcast, shard_map  # noqa: F401  (re-exports)

# canonical axis vocabulary (docs/PLANNER.md): data-parallel batch axis,
# megatron/tensor axis, pipeline-stage axis, sequence axis, expert axis.
# Aliases map the short spellings the shard_map modules historically
# used onto the canonical names.
AXIS_NAMES = ("data", "model", "pipe", "sp", "ep")
AXIS_ALIASES = {"dp": "data", "tp": "model", "pp": "pipe"}


def build_mesh(axis_sizes: dict, devices=None):
    """Build a Mesh with named axes, e.g. {'data': 4, 'model': 2}.

    Axis order follows dict order; total size must divide the device count.
    This is the TPU-native analog of choosing ctx=[gpu(0)..gpu(n)] — the mesh
    IS the device list, and shardings replace per-device executor replicas.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(axis_sizes[n]) for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n=None, devices=None):
    """1-D data-parallel mesh over n (default: all) devices."""
    import jax
    if devices is None:
        devices = jax.devices()
    if n is None:
        n = len(devices)
    return build_mesh({"data": n}, devices)


def single_axis_mesh(axis_name, n=None, devices=None):
    """1-D mesh over one named axis — what the shard_map building blocks
    (tp/pp/sp and their tests/examples) construct instead of an inline
    ``Mesh(np.array(devices), (name,))``."""
    import jax
    if devices is None:
        devices = jax.devices()
    if n is None:
        n = len(devices)
    return build_mesh({str(axis_name): n}, list(devices))


def axis_size(mesh, axis_name, default=None):
    """Size of a named mesh axis; `default` (when given) instead of a
    KeyError for an absent axis, so callers can treat a 1-D data mesh as
    {'model': 1, 'pipe': 1} without special-casing."""
    name = AXIS_ALIASES.get(axis_name, axis_name)
    for n, s in zip(mesh.axis_names, mesh.devices.shape):
        if n == name or n == axis_name:
            return int(s)
    if default is not None:
        return int(default)
    raise KeyError(f"mesh {tuple(mesh.axis_names)} has no axis "
                   f"{axis_name!r}")


def data_axis(mesh):
    """The batch-sharding axis of a mesh: 'data' when present, else the
    leading axis (the historical 1-D convention)."""
    return "data" if "data" in mesh.axis_names else mesh.axis_names[0]


# One canonical mesh per device tuple so Parameters, Module executors and
# split_and_load all agree on the mesh object (shardings compare equal).
_MESH_CACHE: dict = {}


def mesh_for_devices(devices):
    key = tuple(devices)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = data_parallel_mesh(len(devices), list(devices))
        _MESH_CACHE[key] = mesh
    return mesh


def mesh_for_contexts(ctx_list):
    """The cached 1-D data mesh over the jax devices of a context list —
    the TPU-native meaning of ctx=[gpu(0)..gpu(n-1)] everywhere a context
    list is accepted (Module, gluon initialize/split_and_load)."""
    return mesh_for_devices([c.jax_device() for c in ctx_list])


def mesh_descriptor(mesh):
    """JSON-safe description of a mesh: {axis_name: size}. Recorded in
    checkpoint TOPOLOGY.json so a restore at a different device count
    can tell (and log) what it is resharding from; also the Plan's
    mesh-shape spelling (parallel/planner.py)."""
    return {str(n): int(s)
            for n, s in zip(mesh.axis_names, mesh.devices.shape)}


def mesh_from_descriptor(desc, devices=None):
    """Inverse of mesh_descriptor: build (and cache) the mesh a
    descriptor names. The cache key includes the axis layout, so a
    dp4×tp2 mesh and a dp8 mesh over the same devices coexist."""
    import jax
    if devices is None:
        devices = jax.devices()
    items = tuple((str(k), int(v)) for k, v in desc.items())
    key = (tuple(devices), items)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = build_mesh(dict(items), list(devices))
        _MESH_CACHE[key] = mesh
    return mesh


def current_topology(mesh=None):
    """JSON-safe snapshot of this process's device topology (checkpoint
    TOPOLOGY.json): device/process counts plus the mesh axes when one is
    given."""
    import jax
    d = {"device_count": int(jax.device_count()),
         "local_device_count": int(jax.local_device_count()),
         "process_count": int(jax.process_count()),
         "process_index": int(jax.process_index())}
    if mesh is not None:
        d["mesh_axes"] = mesh_descriptor(mesh)
    return d


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def batch_sharding(mesh, batch_axis=0):
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = [None] * batch_axis + [data_axis(mesh)]
    return NamedSharding(mesh, P(*spec))


def put_replicated(data, mesh):
    """Commit host/any-device data to the mesh, replicated."""
    import jax
    data = getattr(data, "_data", data)
    if not isinstance(data, jax.Array):
        data = np.asarray(data)
    return jax.device_put(data, replicated_sharding(mesh))


def put_batch_sharded(data, mesh, batch_axis=0):
    """Commit host/any-device data to the mesh, sharded on the batch axis."""
    import jax
    data = getattr(data, "_data", data)
    if not isinstance(data, jax.Array):
        data = np.asarray(data)
    n = axis_size(mesh, data_axis(mesh))
    if data.shape[batch_axis] % n != 0:
        raise ValueError(
            f"batch axis {batch_axis} of shape {tuple(data.shape)} must be "
            f"divisible by the {n}-way data axis")
    return jax.device_put(data, batch_sharding(mesh, batch_axis))
