"""Mesh helpers: build jax device meshes for dp/tp/pp axes."""
from __future__ import annotations

import numpy as np


def build_mesh(axis_sizes: dict, devices=None):
    """Build a Mesh with named axes, e.g. {'data': 4, 'model': 2}.

    Axis order follows dict order; total size must divide the device count.
    This is the TPU-native analog of choosing ctx=[gpu(0)..gpu(n)] — the mesh
    IS the device list, and shardings replace per-device executor replicas.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(axis_sizes[n]) for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n=None, devices=None):
    """1-D data-parallel mesh over n (default: all) devices."""
    import jax
    if devices is None:
        devices = jax.devices()
    if n is None:
        n = len(devices)
    return build_mesh({"data": n}, devices)


# One canonical mesh per device tuple so Parameters, Module executors and
# split_and_load all agree on the mesh object (shardings compare equal).
_MESH_CACHE: dict = {}


def mesh_for_devices(devices):
    key = tuple(devices)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = data_parallel_mesh(len(devices), list(devices))
        _MESH_CACHE[key] = mesh
    return mesh


def mesh_for_contexts(ctx_list):
    """The cached 1-D data mesh over the jax devices of a context list —
    the TPU-native meaning of ctx=[gpu(0)..gpu(n-1)] everywhere a context
    list is accepted (Module, gluon initialize/split_and_load)."""
    return mesh_for_devices([c.jax_device() for c in ctx_list])


def mesh_descriptor(mesh):
    """JSON-safe description of a mesh: {axis_name: size}. Recorded in
    checkpoint TOPOLOGY.json so a restore at a different device count
    can tell (and log) what it is resharding from."""
    return {str(n): int(s)
            for n, s in zip(mesh.axis_names, mesh.devices.shape)}


def current_topology(mesh=None):
    """JSON-safe snapshot of this process's device topology (checkpoint
    TOPOLOGY.json): device/process counts plus the mesh axes when one is
    given."""
    import jax
    d = {"device_count": int(jax.device_count()),
         "local_device_count": int(jax.local_device_count()),
         "process_count": int(jax.process_count()),
         "process_index": int(jax.process_index())}
    if mesh is not None:
        d["mesh_axes"] = mesh_descriptor(mesh)
    return d


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def batch_sharding(mesh, batch_axis=0):
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = [None] * batch_axis + [mesh.axis_names[0]]
    return NamedSharding(mesh, P(*spec))


def put_replicated(data, mesh):
    """Commit host/any-device data to the mesh, replicated."""
    import jax
    data = getattr(data, "_data", data)
    if not isinstance(data, jax.Array):
        data = np.asarray(data)
    return jax.device_put(data, replicated_sharding(mesh))


def put_batch_sharded(data, mesh, batch_axis=0):
    """Commit host/any-device data to the mesh, sharded on the batch axis."""
    import jax
    data = getattr(data, "_data", data)
    if not isinstance(data, jax.Array):
        data = np.asarray(data)
    n = mesh.devices.size
    if data.shape[batch_axis] % n != 0:
        raise ValueError(
            f"batch axis {batch_axis} of shape {tuple(data.shape)} must be "
            f"divisible by the {n}-device mesh")
    return jax.device_put(data, batch_sharding(mesh, batch_axis))
