"""Mesh helpers: build jax device meshes for dp/tp/pp axes."""
from __future__ import annotations

import numpy as np


def build_mesh(axis_sizes: dict, devices=None):
    """Build a Mesh with named axes, e.g. {'data': 4, 'model': 2}.

    Axis order follows dict order; total size must divide the device count.
    This is the TPU-native analog of choosing ctx=[gpu(0)..gpu(n)] — the mesh
    IS the device list, and shardings replace per-device executor replicas.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(axis_sizes[n]) for n in names)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n=None, devices=None):
    """1-D data-parallel mesh over n (default: all) devices."""
    import jax
    if devices is None:
        devices = jax.devices()
    if n is None:
        n = len(devices)
    return build_mesh({"data": n}, devices)
