"""Sequence/context parallelism — ring attention and Ulysses all-to-all.

The reference handles long sequences only by bucketing (SURVEY §5); this
module is the TPU-native long-context machinery the rebuild treats as
first-class: shard the SEQUENCE axis across a mesh axis so context length
scales with chip count.

  - `ring_attention`: each device holds a sequence shard of Q/K/V; K/V
    blocks rotate around the ring with `jax.lax.ppermute` while per-block
    results merge with a numerically-stable logsumexp combine — N steps
    of compute/communication overlap on ICI, never materializing the
    full (S, S) score matrix. On TPU meshes each (Q, K/V-block) pair
    runs the Pallas flash kernels fwd+bwd (impl='flash': O(S_local)
    memory, lse-differentiable merge); CPU meshes use the blockwise
    dense online-softmax body.
  - `ulysses_attention`: `all_to_all` re-shards sequence->heads, runs
    dense local attention per head group, and re-shards back — cheaper
    for many-head models when heads % devices == 0.

Both are pure jax (shard_map + collectives): jit/grad compose, XLA
schedules the collectives on ICI, and the same code runs on the virtual
CPU mesh used by the tests and the driver dryrun.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from .mesh import axis_size
from .mesh import shard_map as _shard_map

__all__ = ["attention_reference", "ring_attention", "ulysses_attention"]


def attention_reference(q, k, v, causal=False, scale=None):
    """Dense scaled-dot-product attention — ONE oracle shared with the
    flash-attention dispatcher (ops/attention.py)."""
    from ..ops.attention import reference_attention
    return reference_attention(q, k, v, causal=causal, scale=scale)


def _block_attend(q, k, v, acc, m, l, mask=None, scale=1.0):
    """One online-softmax accumulation step.

    q (B,H,Sq,D) against a K/V block (B,H,Sk,D); carries
    acc (unnormalized numerator), m (running max), l (running denom).
    """
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows: exp(-inf - -inf) -> treat as 0 contribution
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    correction = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - safe_m)
    correction = jnp.where(jnp.isneginf(m), 0.0, correction)
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + \
        jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return acc_new, m_new, l_new


def _ring_attention_flash_shard(q, k, v, axis_name, causal, scale, force,
                                platform):
    """Ring body where each (Q, K/V-block) pair runs the Pallas flash
    kernel (fwd AND bwd — O(s_loc) memory, no (s_loc, s_loc) scores) and
    per-block (out, lse) pairs merge with the standard logsumexp
    combine. Block causality: the resident diagonal pair is causal; a
    block from a lower rank attends fully; higher ranks contribute
    nothing (lse=-inf)."""
    from ..ops.attention import flash_attention_with_lse
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    def attend(k_blk, v_blk, blk_causal):
        return flash_attention_with_lse(q, k_blk, v_blk, causal=blk_causal,
                                        scale=scale, force=force,
                                        platform=platform)

    # the ring is UNROLLED in python (n is the static mesh-axis size):
    # straight-line per-step kernel calls lower cleanly under shard_map
    # (interpret-mode pallas inside lax loops trips an MLIR lowering-
    # cache bug in this jax), and causal skipping needs no lax.cond —
    # a skipped block is simply merged with lse=-inf (weight zero),
    # the same every-block-computed masking the dense body uses
    o = jnp.zeros_like(q, dtype=jnp.float32)
    lse = jnp.full_like(q[..., 0], -jnp.inf, dtype=jnp.float32)
    k_blk, v_blk = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        if causal and step == 0:
            # resident diagonal pair: causal within the block
            o_i, lse_i = attend(k_blk, v_blk, True)
        else:
            o_i, lse_i = attend(k_blk, v_blk, False)
            if causal:
                src = (rank - step) % n          # owner of this K/V
                lse_i = jnp.where(src < rank, lse_i, -jnp.inf)
        # logsumexp merge of the block's normalized output
        lse_new = jnp.logaddexp(lse, lse_i)
        safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
        o = o * jnp.exp(lse - safe)[..., None] \
            + o_i.astype(jnp.float32) * jnp.exp(lse_i - safe)[..., None]
        lse = lse_new
        if step < n - 1:
            k_blk, v_blk = (jax.lax.ppermute(x, axis_name, perm)
                            for x in (k_blk, v_blk))
    return o.astype(q.dtype)


def _ring_attention_shard(q, k, v, axis_name, causal, scale):
    """Per-device body under shard_map: Q stays, K/V rotate the ring."""
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    q_pos = rank * s_loc + jnp.arange(s_loc)            # global Q rows

    # carries derive from q so shard_map types them as varying over the
    # mesh axis (fresh constants would be unvarying and fail the scan
    # carry typecheck)
    acc0 = jnp.zeros_like(q, dtype=jnp.float32)
    m0 = jnp.full_like(q[..., 0], -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., 0], dtype=jnp.float32)

    def body(step, carry):
        acc, m, l, k_blk, v_blk = carry
        src = (rank - step) % n                          # owner of this K/V
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            mask = jnp.broadcast_to(mask, (b, h, s_loc, s_loc))
        else:
            mask = None
        acc, m, l = _block_attend(q.astype(jnp.float32),
                                  k_blk.astype(jnp.float32),
                                  v_blk.astype(jnp.float32),
                                  acc, m, l, mask, scale)
        # rotate: receive the next lower rank's block (ship while
        # computing); the last step's rotation would be discarded — skip it
        perm = [(i, (i + 1) % n) for i in range(n)]

        def rotate(blocks):
            return tuple(jax.lax.ppermute(x, axis_name, perm)
                         for x in blocks)

        k_blk, v_blk = jax.lax.cond(step < n - 1, rotate,
                                    lambda blocks: blocks, (k_blk, v_blk))
        return acc, m, l, k_blk, v_blk

    acc, m, l, _, _ = jax.lax.fori_loop(0, n, body, (acc0, m0, l0, k, v))
    l = jnp.where(l == 0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None,
                   impl=None):
    """Sequence-sharded attention over `mesh[axis_name]`.

    q/k/v: (B, H, S, D) with S divisible by the axis size; returns the
    attention output with the same sharding. Context length scales
    linearly with devices.

    impl: None (auto) | 'dense' | 'flash'.
      - 'flash' (auto-picked on TPU meshes): each (Q, K/V-block) pair
        runs the Pallas flash kernels fwd+bwd and per-block (out, lse)
        merge with logsumexp — peak per-device memory O(S_local), never
        an (S_local, S_local) score tile in HBM. Ineligible shapes (and
        CPU meshes) fall back to the dense-with-lse oracle per block
        automatically, so 'flash' is safe everywhere; the Pallas kernels
        themselves engage only on TPU devices. (No interpret mode here:
        interpret-Pallas inside shard_map trips jax-internal vma checks
        in this build — kernel-level coverage lives in
        tests/test_attention.py and tests_tpu.)
    """
    nsp = axis_size(mesh, axis_name)
    if q.shape[2] % nsp != 0:
        raise MXNetError(
            f"ring_attention: sequence {q.shape[2]} not divisible by "
            f"{axis_name}={nsp}")
    if impl is None:
        # the flash kernels are TPU-tuned (8-lane lse layout, TPU block
        # tiling): auto-pick them only on a TPU mesh — any other non-CPU
        # platform (gpu) gets the dense body rather than untested kernels
        impl = "flash" if mesh.devices.flat[0].platform == "tpu" \
            else "dense"
    spec = P(None, None, axis_name, None)
    if impl == "dense":
        body = functools.partial(_ring_attention_shard,
                                 axis_name=axis_name, causal=causal,
                                 scale=scale)
    elif impl == "flash":
        body = functools.partial(
            _ring_attention_flash_shard, axis_name=axis_name,
            causal=causal, scale=scale, force=None,
            platform=mesh.devices.flat[0].platform)
    else:
        raise MXNetError(f"ring_attention: unknown impl {impl!r}")
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)


def _ulysses_shard(q, k, v, axis_name, causal, scale, platform):
    # local (B, H, S/n, D) -> all_to_all -> (B, H/n, S, D)
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # local attention rides the flash dispatcher: Pallas fwd+bwd kernels
    # on TPU (O(S) activation memory — the full-sequence local view is
    # exactly where flash matters), dense XLA on CPU meshes. `platform`
    # comes from the MESH's devices, not the process default backend —
    # a CPU mesh on a TPU-default host must not pick the TPU kernel
    from ..ops.attention import flash_attention
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                          platform=platform)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis_name="sp", causal=False,
                      scale=None):
    """DeepSpeed-Ulysses style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, local attention sees the FULL
    sequence for its head group, and a second all-to-all restores
    sequence sharding. Requires heads % axis_size == 0."""
    nsp = axis_size(mesh, axis_name)
    if q.shape[1] % nsp != 0:
        raise MXNetError(
            f"ulysses_attention: heads {q.shape[1]} not divisible by "
            f"{axis_name}={nsp}")
    if q.shape[2] % nsp != 0:
        raise MXNetError(
            f"ulysses_attention: sequence {q.shape[2]} not divisible by "
            f"{axis_name}={nsp}")
    spec = P(None, None, axis_name, None)
    fn = _shard_map(
        functools.partial(_ulysses_shard, axis_name=axis_name,
                          causal=causal, scale=scale,
                          platform=mesh.devices.flat[0].platform),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
