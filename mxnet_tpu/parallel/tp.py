"""Tensor and expert parallelism helpers.

Absent from the reference (SURVEY §2.3: closest artifact is group2ctx
layer placement); TPU-native additions rounding out the tp/ep lanes of
the mesh story:

  - `megatron_mlp`: Megatron-style column-parallel first projection +
    row-parallel second projection under shard_map — weights live sharded
    over the `tp` axis, ONE psum on the block output, activations of the
    hidden layer never materialize unsharded.
  - `moe_ffn`: expert-parallel mixture-of-experts FFN — experts sharded
    over the `ep` axis, top-1 switch routing, outputs combined with a
    psum. Every device runs its local experts over the full token batch
    and masks non-routed tokens (dense dispatch: simple, correct, and
    collective-light; capacity-based all_to_all dispatch is the optimized
    variant this API is shaped for).

Both are pure shard_map programs: jit/grad compose and the same code runs
on the virtual CPU mesh (tests) and real ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from .mesh import axis_size
from .mesh import shard_map as _shard_map

__all__ = ["megatron_mlp", "moe_ffn", "moe_ffn_reference"]


def _mlp_shard(x, w1, b1, w2, b2, axis_name):
    h = jax.nn.relu(x @ w1 + b1)          # local hidden shard (col-parallel)
    partial = h @ w2                      # row-parallel partial sum
    return jax.lax.psum(partial, axis_name) + b2


def megatron_mlp(x, w1, b1, w2, b2, mesh, axis_name="tp"):
    """x (B, D); w1 (D, H) column-sharded; w2 (H, D_out) row-sharded.

    H must divide by the axis size. Returns (B, D_out) replicated.
    """
    n = axis_size(mesh, axis_name)
    if w1.shape[1] != w2.shape[0]:
        raise MXNetError(
            f"megatron_mlp: w1 hidden dim {w1.shape[1]} != w2 input dim "
            f"{w2.shape[0]}")
    if w1.shape[1] % n != 0:
        raise MXNetError(
            f"megatron_mlp: hidden dim {w1.shape[1]} not divisible by "
            f"{axis_name}={n}")
    fn = _shard_map(
        functools.partial(_mlp_shard, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(), P(None, axis_name), P(axis_name),
                  P(axis_name, None), P()),
        out_specs=P())
    return fn(x, w1, b1, w2, b2)


def moe_ffn_reference(x, gate_w, w1, w2):
    """Dense oracle: top-1 switch MoE over all experts."""
    logits = x @ gate_w                              # (B, E)
    choice = jnp.argmax(logits, axis=1)              # (B,)
    gate = jax.nn.softmax(logits, axis=1)
    gate_val = jnp.take_along_axis(gate, choice[:, None], axis=1)
    h = jax.nn.relu(jnp.einsum("bd,edh->beh", x, w1))
    out = jnp.einsum("beh,ehd->bed", h, w2)          # (B, E, D)
    picked = jnp.take_along_axis(
        out, choice[:, None, None].repeat(out.shape[-1], -1), axis=1)[:, 0]
    return picked * gate_val


def _moe_shard(x, gate_w, w1, w2, axis_name, experts_per_dev):
    rank = jax.lax.axis_index(axis_name)
    # routing is replicated math (gate_w replicated)
    logits = x @ gate_w
    choice = jnp.argmax(logits, axis=1)
    gate = jax.nn.softmax(logits, axis=1)
    gate_val = jnp.take_along_axis(gate, choice[:, None], axis=1)
    # local experts: ids [rank*epd, (rank+1)*epd)
    local_ids = rank * experts_per_dev + jnp.arange(experts_per_dev)
    h = jax.nn.relu(jnp.einsum("bd,edh->beh", x, w1))   # local experts only
    out = jnp.einsum("beh,ehd->bed", h, w2)             # (B, epd, D)
    routed = choice[:, None] == local_ids[None, :]      # (B, epd)
    local = jnp.einsum("bed,be->bd", out, routed.astype(out.dtype))
    return jax.lax.psum(local, axis_name) * gate_val


def moe_ffn(x, gate_w, w1, w2, mesh, axis_name="ep"):
    """Expert-parallel top-1 MoE FFN.

    x (B, D); gate_w (D, E) replicated; w1 (E, D, H) / w2 (E, H, D)
    sharded over experts on `axis_name` (E % axis_size == 0).
    """
    n = axis_size(mesh, axis_name)
    n_experts = w1.shape[0]
    if n_experts % n != 0:
        raise MXNetError(f"moe_ffn: {n_experts} experts not divisible by "
                         f"{axis_name}={n}")
    fn = _shard_map(
        functools.partial(_moe_shard, axis_name=axis_name,
                          experts_per_dev=n_experts // n),
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name, None, None),
                  P(axis_name, None, None)),
        out_specs=P())
    return fn(x, gate_w, w1, w2)
