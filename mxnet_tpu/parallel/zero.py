"""ZeRO-sharded data parallelism (mx.parallel.zero).

Role of the reference's reserved ``KVStore.SetGradientCompression`` +
multi-device optimizer sharding (PAPER.md §6), built TPU-native on the
fused dp step: instead of every device holding fp32 master weights and
optimizer state for EVERY parameter and all-reducing full fp32
gradients (parallel/dp.py), each device owns a 1/N slice of flat
per-bucket master/optimizer buffers:

  stage 1  grads are psum'd (same wire as dp), but each device applies
           the optimizer to ITS shard only — optimizer state is
           sharded, the update work drops N-fold, and fp32 stage-1
           training is BIT-IDENTICAL to the unsharded baseline (same
           reduction, same elementwise update per element);
  stage 2  the psum becomes a reduce-scatter: each device receives only
           its gradient shard ((N-1)/N of the all-reduce wire), then
           all-gathers the updated compute-dtype params.

Parameters are packed into flat fp32 buckets of ``MXNET_ZERO_BUCKET_MB``
bytes (padded to a multiple of N, sharded over the dp axis); every fused
optimizer op in dp's ``_OPT_OPS`` is elementwise, so the update applies
directly to the flat 1-D shards. Bucketing bounds peak gather/scatter
buffer size and — because each bucket's reduce-scatter depends only on
that bucket's gradients — lets XLA's latency-hiding scheduler start
bucket k's collective while the backward for bucket k+1 is still
computing (asserted post-SPMD by analysis/hloaudit's ``fit_step_zero``
program; the cpu backend lowers synchronous collective forms, so the
async-interleave assertion binds where async pairs exist, i.e. on TPU).

On-wire gradient compression (``MXNET_GRAD_COMPRESS=fp8|bf16``) casts
the bucketed gradient to the wire dtype before the reduce, with a
per-device error-feedback residual (Lin et al., Deep Gradient
Compression) carried across steps — and through the fused K-step scan —
so the quantization error is re-injected instead of lost. This is WHY
the step is an explicit `shard_map` program rather than dp's implicit
GSPMD sharding: error feedback needs the per-device PARTIAL gradient
before the reduction, which the partitioner-inserted psum never exposes
at trace level.

Semantics deltas vs dp (documented in docs/ZERO.md): under shard_map
the forward runs per-device, so BatchNorm batch statistics are LOCAL to
each device's batch shard (the reference's cross-device BN semantics);
aux running stats are pmean'd back to replicated each step.

Env surface: ``MXNET_ZERO_STAGE=0|1|2`` (0 = plain dp; >0 reroutes
``DataParallelTrainer(...)`` construction here), ``MXNET_ZERO_BUCKET_MB``
(default 4), ``MXNET_GRAD_COMPRESS=none|bf16|fp8``.

CLI: ``python -m mxnet_tpu.parallel.zero --selftest`` (2-device A/B:
bitwise stage-1 parity, fp8 convergence, HLO wire-byte reduction),
``--hlo-check`` (post-SPMD collective report), ``--bench`` (8-device
dp vs ZeRO-1 vs ZeRO-2 vs +fp8 steps/s + wire bytes — bench.py's
``zero`` lane).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .dp import DataParallelTrainer
from .mesh import shard_map

__all__ = ["ZeroTrainer", "ZeroLayout", "counters", "resolve_stage",
           "resolve_compress", "WIRE_DTYPES"]

# wire dtypes for MXNET_GRAD_COMPRESS; fp8 e4m3 keeps the most mantissa
# of the fp8 encodings (gradients after loss rescale sit well inside its
# range; the residual carries what the 3-bit mantissa drops)
WIRE_DTYPES = {
    "bf16": jnp.bfloat16,
    "fp8": getattr(jnp, "float8_e4m3fn", jnp.bfloat16),
}


def resolve_stage(value=None):
    """ZeRO stage: explicit arg wins, else MXNET_ZERO_STAGE, else the
    stage a pure-zero MXNET_PLAN names (so ``MXNET_PLAN=zero2`` reroutes
    plain trainer construction without going through the planner), else
    0."""
    if value is None:
        import os
        from .. import config
        # unset/empty collapses to the declared default 0; an explicit
        # "0" is the truthy string "0" here, so it still wins over plan
        value = os.environ.get("MXNET_ZERO_STAGE") or 0
        if not value:
            plan = str(config.get("MXNET_PLAN", "auto")).strip().lower()
            if plan in ("zero1", "zero2"):
                return int(plan[-1])
    try:
        stage = int(value)
    except (TypeError, ValueError):
        raise MXNetError(f"MXNET_ZERO_STAGE must be 0|1|2, got {value!r}")
    if stage not in (0, 1, 2):
        raise MXNetError(f"MXNET_ZERO_STAGE must be 0|1|2, got {stage}")
    return stage


def resolve_compress(value=None):
    """Wire-compression mode: none|bf16|fp8 (MXNET_GRAD_COMPRESS)."""
    if value is None:
        from .. import config
        value = config.get("MXNET_GRAD_COMPRESS", "none")
    mode = str(value or "none").strip().lower()
    if mode in ("", "0", "none", "off"):
        return "none"
    if mode not in WIRE_DTYPES:
        raise MXNetError(
            f"MXNET_GRAD_COMPRESS must be none|bf16|fp8, got {value!r}")
    return mode


def _resolve_bucket_bytes(mb=None):
    if mb is None:
        from .. import config
        mb = config.get("MXNET_ZERO_BUCKET_MB", 4)
    try:
        b = int(float(mb) * (1 << 20))
    except (TypeError, ValueError):
        raise MXNetError(f"MXNET_ZERO_BUCKET_MB must be a number, got {mb!r}")
    return max(b, 1)


class ZeroLayout:
    """Flat-bucket layout of the parameter set over N devices.

    Parameters are packed in declaration order into buckets of at most
    ``bucket_bytes`` fp32 bytes (a parameter never splits across
    buckets; a single parameter larger than the threshold gets its own
    bucket). Each bucket's flat length is padded to a multiple of
    ``n_dev`` so the P("data") shard is even; padding is zeros and the
    elementwise optimizer update on zero grads leaves it zeros.
    """

    def __init__(self, shapes, n_dev, bucket_bytes):
        self.shapes = [tuple(s) for s in shapes]
        self.n_dev = int(n_dev)
        self.sizes = [max(1, int(_np.prod(s))) if s else 1
                      for s in self.shapes]
        self.buckets = []
        cur, cur_bytes = [], 0
        for i, sz in enumerate(self.sizes):
            if cur and cur_bytes + 4 * sz > bucket_bytes:
                self.buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += 4 * sz
        if cur:
            self.buckets.append(cur)
        self.offsets, self.totals, self.padded, self.shard_len = \
            [], [], [], []
        for idxs in self.buckets:
            offs, o = [], 0
            for i in idxs:
                offs.append(o)
                o += self.sizes[i]
            self.offsets.append(offs)
            self.totals.append(o)
            p = o + (-o % self.n_dev)
            self.padded.append(p)
            self.shard_len.append(p // self.n_dev)

    @property
    def n_buckets(self):
        return len(self.buckets)

    def flatten_host(self, arrays, b):
        """Host numpy (padded,) fp32 flat buffer of bucket b."""
        flat = _np.zeros(self.padded[b], _np.float32)
        for a, i, off in zip(arrays, self.buckets[b], self.offsets[b]):
            flat[off:off + self.sizes[i]] = \
                _np.asarray(a, _np.float32).ravel()
        return flat

    def flatten_traced(self, parts, b):
        """Traced flat (padded,) buffer from bucket b's per-param
        tensors (keeps their dtype; pads with zeros)."""
        flat = jnp.concatenate([p.reshape(-1) for p in parts])
        pad = self.padded[b] - self.totals[b]
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    def unflatten_traced(self, flat, b):
        """[(param_index, tensor)] views of bucket b's flat buffer."""
        out = []
        for i, off in zip(self.buckets[b], self.offsets[b]):
            out.append((i, jax.lax.dynamic_slice_in_dim(
                flat, off, self.sizes[i]).reshape(self.shapes[i])))
        return out

    def unflatten_host(self, flat, b):
        out = []
        for i, off in zip(self.buckets[b], self.offsets[b]):
            out.append((i, _np.asarray(
                flat[off:off + self.sizes[i]]).reshape(self.shapes[i])))
        return out

    def owner(self, i):
        """Device owning parameter i's shard (by its start offset) —
        the checkpoint ownership map, so cooperative sharded commits
        write exactly the optimizer shards a rank owns."""
        b = next(k for k, idxs in enumerate(self.buckets) if i in idxs)
        off = self.offsets[b][self.buckets[b].index(i)]
        return min(off // self.shard_len[b], self.n_dev - 1)

    def wire_bytes_breakdown(self, stage, compute_itemsize, wire_itemsize):
        """(param all-gather bytes, grad-reduce bytes) per device per step
        (ring collective accounting: all-gather/reduce-scatter move
        (N-1)/N of the global buffer per device, all-reduce twice that).
        The per-stage split telemetry.devstats pairs with the step
        program's FLOPs for roofline accounting."""
        n = self.n_dev
        frac = (n - 1) / n
        ag = red = 0.0
        for p in self.padded:
            ag += p * frac * compute_itemsize               # all-gather
            r = p * frac * wire_itemsize                    # grad reduce
            red += r if stage >= 2 else 2 * r               # ar = 2x rs
        return int(ag), int(red)

    def wire_bytes_per_step(self, stage, compute_itemsize, wire_itemsize):
        """Analytic per-device wire bytes of one step — the breakdown's
        sum. The HLO-measured numbers come from hloaudit.spmd_collectives;
        this feeds the live `zero_wire_bytes` telemetry counter without a
        device sync."""
        ag, red = self.wire_bytes_breakdown(stage, compute_itemsize,
                                            wire_itemsize)
        return ag + red

    def overlap_frac(self):
        """Fraction of grad-reduce bytes whose bucket collective can
        start before the full backward finishes: every bucket except
        the one whose gradients complete last (bucket 0 — the
        input-side params, last out of the backward). Structural
        headroom; the HLO interleave assertion is the proof."""
        tot = sum(self.padded)
        if self.n_buckets < 2 or not tot:
            return 0.0
        return round(1.0 - self.padded[0] / tot, 4)

    def ownership(self, param_names, n_states):
        own = {}
        for i, n in enumerate(param_names):
            k = self.owner(i)
            own[f"param:{n}"] = k
            for j in range(n_states):
                own[f"opt:{n}:{j}"] = k
        return own


# -- live counter export (profiler hook "zero", scraped by telemetry) --------

_COUNTERS = {"zero_wire_bytes": 0, "zero_steps": 0,
             "zero_wire_allgather_bytes": 0, "zero_wire_reduce_bytes": 0,
             "zero_flops_per_step": 0.0,
             "zero_overlap_frac": 0.0, "zero_stage": 0,
             "zero_buckets": 0, "zero_compress_bits": 32}
_HOOKED = False


def counters():
    """Host-side ZeRO counters (no device sync): cumulative analytic
    wire bytes, steps, current stage/bucket/overlap configuration."""
    return dict(_COUNTERS)


def _ensure_hook():
    global _HOOKED
    if not _HOOKED:
        from .. import profiler
        profiler.register_counter_export("zero", counters)
        _HOOKED = True


class ZeroTrainer(DataParallelTrainer):
    """DataParallelTrainer with ZeRO-sharded masters/optimizer state.

    Drop-in: same constructor surface plus ``zero_stage`` /
    ``zero_bucket_mb`` / ``grad_compress`` (env-defaulted), same
    step/step_k/init_state/export/import contract. The params/states
    tuples it hands back are per-BUCKET flat fp32 shards instead of
    per-parameter replicas — opaque to every fused-fit loop, which
    round-trips them through the trainer; host access goes through
    ``host_params``/``export_training_state`` (which return the usual
    per-parameter arrays, so checkpoints interchange with plain dp and
    ``MXNET_ZERO_STAGE`` can change across a resume).
    """

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 learning_rate=0.01, momentum=0.0, wd=0.0,
                 rescale_grad=None, clip_gradient=None, loss_index=0,
                 dtype="float32", input_preproc=None, loss_scaler=None,
                 zero_stage=None, zero_bucket_mb=None, grad_compress=None,
                 **opt_kwargs):
        stage = resolve_stage(zero_stage)
        if stage == 0:
            # direct construction is an explicit opt-in: default to
            # stage 1 when neither arg nor env picked one
            stage = 1
        super().__init__(symbol, mesh, data_names=data_names,
                         label_names=label_names, optimizer=optimizer,
                         learning_rate=learning_rate, momentum=momentum,
                         wd=wd, rescale_grad=rescale_grad,
                         clip_gradient=clip_gradient,
                         loss_index=loss_index, dtype=dtype,
                         input_preproc=input_preproc,
                         loss_scaler=loss_scaler, **opt_kwargs)
        self._zero_stage = stage
        self._bucket_bytes = _resolve_bucket_bytes(zero_bucket_mb)
        self._compress = resolve_compress(grad_compress)
        self._wire_dtype = (None if self._compress == "none"
                            else WIRE_DTYPES[self._compress])
        self._n_dev = int(self._mesh.devices.size)
        self._n_outputs = len(symbol.list_outputs())
        # N-D meshes (the planner's dp×tp+ZeRO composition): masters,
        # optimizer state and the gather/scatter collectives shard JOINTLY
        # over every mesh axis — 1/(D·T) per device — while the batch
        # stays sharded over the data axis only, so the T model replicas
        # of a data rank compute identical forwards/grads and the joint
        # reduce needs a 1/T rescale (docs/PLANNER.md "ZeRO over dp×tp").
        # A 1-D mesh keeps the scalar axis spelling so its programs stay
        # bit-identical to the single-mode trainer.
        axis_names = tuple(self._mesh.axis_names)
        self._shard_axes = (self._data_axis if len(axis_names) == 1
                            else axis_names)
        self._axis_sizes = tuple(int(self._mesh.shape[a])
                                 for a in axis_names)
        self._model_factor = (self._n_dev
                              // int(self._mesh.shape[self._data_axis]))
        self._layout = None
        self._resid_dev = ()
        self._zstep = None
        self._zero_multi = {}
        self._compute_itemsize = (
            _np.dtype(self._compute_dtype).itemsize
            if self._compute_dtype is not None else 4)
        self._wire_itemsize = (
            _np.dtype(self._wire_dtype).itemsize
            if self._wire_dtype is not None else self._compute_itemsize)
        # distinct jit names per config: the post-SPMD dump is matched
        # by module substring, and no tag may be a prefix of another
        suffix = {"none": "n", "bf16": "b16", "fp8": "f8"}[self._compress]
        if self._model_factor > 1:
            self._program_tag = \
                f"zstep_t{self._model_factor}s{stage}{suffix}"
        else:
            self._program_tag = f"zstep_s{stage}{suffix}"
        _ensure_hook()

    # -- layout / sharded placement ------------------------------------------

    def _ensure_layout(self, shapes):
        if self._layout is None:
            self._layout = ZeroLayout(shapes, self._n_dev,
                                      self._bucket_bytes)
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._zshard = NamedSharding(self._mesh, P(self._shard_axes))
            self._rshard = NamedSharding(self._mesh,
                                         P(self._shard_axes, None))
        return self._layout

    def _pack_from_host(self, host_params, host_states):
        """Flatten per-parameter host arrays into sharded flat buckets;
        (re)initialize the compression residual to zeros."""
        L = self._ensure_layout([p.shape for p in host_params])
        masters, zstates = [], []
        for b, idxs in enumerate(L.buckets):
            masters.append(jax.device_put(
                L.flatten_host([host_params[i] for i in idxs], b),
                self._zshard))
            zstates.append(tuple(jax.device_put(
                L.flatten_host([host_states[i][j] for i in idxs], b),
                self._zshard) for j in range(self._n_states)))
        self._reset_residual()
        self._build_zero_step()
        return tuple(masters), tuple(zstates)

    def _reset_residual(self):
        if self._wire_dtype is None:
            self._resid_dev = ()
            return
        L = self._layout
        self._resid_dev = tuple(jax.device_put(
            _np.zeros((self._n_dev, L.padded[b]), _np.float32),
            self._rshard) for b in range(L.n_buckets))

    def init_state(self, shape_kwargs, initializer=None, seed=0,
                   arg_params=None, aux_params=None):
        params, states, aux = super().init_state(
            shape_kwargs, initializer=initializer, seed=seed,
            arg_params=arg_params, aux_params=aux_params)
        masters, zstates = self._pack_from_host(
            [_np.asarray(p) for p in params],
            [[_np.asarray(s) for s in st] for st in states])
        return masters, zstates, aux

    # -- the sharded step program --------------------------------------------

    def _zero_impl(self):
        """Per-device step body (runs under shard_map): all-gather
        compute-dtype params from the master shards, local fwd/bwd,
        per-bucket error-feedback compress + reduce(-scatter), update
        the owned master/state shards. Closures mirror dp._step_impl."""
        from ..ops.registry import AttrDict, OpCtx
        L = self._layout
        ax = self._data_axis
        # joint shard axes: scalar data axis on a 1-D mesh (bit-identical
        # legacy program), the full axis tuple on the planner's N-D
        # meshes. model replicas (non-data axes) compute identical grads,
        # so the joint psum over-counts by T — the 1/T rescale below is
        # EXACT for power-of-two T (an fp32 exponent decrement).
        axes = self._shard_axes
        axis_names = tuple(self._mesh.axis_names)
        axis_sizes = self._axis_sizes
        model_scale = (1.0 / self._model_factor
                       if self._model_factor > 1 else None)
        stage = self._zero_stage
        wire_dt = self._wire_dtype
        run, n_args = self._run, len(self._arg_names)
        param_pos, input_pos = list(self._param_pos), list(self._input_pos)
        loss_index = self._loss_index
        fcompute, attrs = self._fcompute, self._attrs
        has_t, is_adam = self._has_t, self._is_adam
        compute_dtype, has_ls = self._compute_dtype, self._has_ls
        scaler = self._scaler
        cast_input, preproc_names = self._cast_input, self._preproc_names
        input_preproc = self._input_preproc
        n_aux = len(self._aux_names)
        B = L.n_buckets

        def impl(masters, states, resid, aux, inputs, rng, lr, t, ls):
            rng, next_rng = jax.random.split(rng)
            scale = ls[0] if has_ls else None
            # [1] masters -> full compute-dtype params. The cast happens
            # on the SHARD, before the gather, so the param all-gather
            # moves half-width words under amp (the gather-side analogue
            # of dp's half-width grad all-reduce); the cast is
            # elementwise, so cast-then-gather == gather-then-cast.
            cparams = [None] * len(param_pos)
            for b in range(B):
                m = masters[b]
                if compute_dtype is not None:
                    m = m.astype(compute_dtype)
                full = jax.lax.all_gather(m, axes, tiled=True)
                for i, arr in L.unflatten_traced(full, b):
                    cparams[i] = arr
            cparams = tuple(cparams)

            def loss_fn(cparams):
                args = [None] * n_args
                for p, v in zip(param_pos, cparams):
                    args[p] = v
                for p, v, cast, nm in zip(input_pos, inputs, cast_input,
                                          preproc_names):
                    if input_preproc is not None:
                        v = input_preproc(nm, v)
                    args[p] = jnp.asarray(v, compute_dtype) \
                        if compute_dtype is not None and cast and \
                        jnp.issubdtype(v.dtype, jnp.floating) else v
                outputs, new_aux = run(tuple(args), aux, rng)
                # LOCAL batch-shard sum; the explicit psum below makes
                # the reported loss match dp's global-batch sum
                loss = outputs[loss_index].sum().astype(jnp.float32)
                obj = loss * scale if has_ls else loss
                return obj, (new_aux, outputs, loss)

            if has_ls:
                from .. import amp as _amp
                _amp._set_trace_loss_scale(scale)
            try:
                (_, (new_aux, outputs, loss)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(cparams)
            finally:
                if has_ls:
                    from .. import amp as _amp
                    _amp._set_trace_loss_scale(None)

            # [2] per bucket: error feedback + wire cast + reduce. Each
            # bucket's collective depends only on that bucket's grads —
            # the dataflow slack the latency-hiding scheduler uses to
            # overlap bucket k's reduce with bucket k+1's backward.
            gshards, new_resid = [], []
            finite = jnp.asarray(True)
            for b in range(B):
                g = L.flatten_traced([grads[i] for i in L.buckets[b]], b)
                if model_scale is not None:
                    g = g * jnp.asarray(model_scale, g.dtype)
                if wire_dt is not None:
                    r = resid[b][0]                 # (padded,) local f32
                    acc = g.astype(jnp.float32) + r
                    c = acc.astype(wire_dt)
                    new_resid.append(acc - c.astype(jnp.float32))
                    g = c
                if stage >= 2:
                    gs = jax.lax.psum_scatter(g, axes, scatter_dimension=0,
                                              tiled=True)
                else:
                    gfull = jax.lax.psum(g, axes)
                    # joint linear rank in P(axes) tiling order (row-major
                    # over the mesh axes; == axis_index(ax) on 1-D)
                    k = jax.lax.axis_index(axis_names[0])
                    for a, s in zip(axis_names[1:], axis_sizes[1:]):
                        k = k * s + jax.lax.axis_index(a)
                    gs = jax.lax.dynamic_slice_in_dim(
                        gfull, k * L.shard_len[b], L.shard_len[b])
                g32 = gs.astype(jnp.float32)
                if has_ls:
                    finite = jnp.logical_and(
                        finite, jnp.all(jnp.isfinite(g32)))
                gshards.append(g32)

            if has_ls:
                # stage-2 shards are distinct per device: the skip
                # decision must be GLOBAL or replicas diverge
                bad = jax.lax.psum(
                    jnp.where(finite, 0, 1).astype(jnp.float32), axes)
                finite = bad == 0
                t = t + jnp.where(finite, 1.0, 0.0)
                inv_scale = 1.0 / scale
            else:
                t = t + 1.0
            eff_lr = lr
            if is_adam:
                b1, b2 = attrs["beta1"], attrs["beta2"]
                eff_lr = lr * jnp.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
            a2 = AttrDict(attrs)
            a2["lr"] = eff_lr
            if has_t:
                a2["t"] = t
            octx = OpCtx(is_train=True)

            # [3] elementwise optimizer update on the OWNED 1/N shard
            new_masters, new_states = [], []
            for b in range(B):
                g32 = gshards[b]
                if has_ls:
                    g32 = g32 * inv_scale
                res = fcompute(a2, octx, masters[b], g32, *states[b])
                if has_ls:
                    new_masters.append(
                        jnp.where(finite, res[0], masters[b]))
                    new_states.append(tuple(
                        jnp.where(finite, s, s0)
                        for s, s0 in zip(res[1:], states[b])))
                else:
                    new_masters.append(res[0])
                    new_states.append(tuple(res[1:]))
            if wire_dt is not None:
                if has_ls:
                    # a skipped step applied nothing: the residual must
                    # not absorb the overflowed gradient either
                    new_resid = [jnp.where(finite, nr, resid[b][0])
                                 for b, nr in enumerate(new_resid)]
                new_resid = tuple(nr[None] for nr in new_resid)
            else:
                new_resid = ()

            if has_ls:
                new_aux = tuple(jnp.where(finite, a, a0)
                                for a, a0 in zip(new_aux, aux))
            if n_aux:
                # local-BN statistics averaged back to replicated (the
                # out_spec asserts replication; exact for means, a
                # shard-average for variances — docs/ZERO.md)
                new_aux = tuple(jax.lax.pmean(a, ax) for a in new_aux)
            loss = jax.lax.psum(loss, ax)
            if has_ls:
                new_ls = scaler.update_state(ls, finite)
                return (tuple(new_masters), tuple(new_states), new_resid,
                        new_aux, loss, outputs, next_rng, t, new_ls)
            return (tuple(new_masters), tuple(new_states), new_resid,
                    new_aux, loss, outputs, next_rng, t)

        return impl

    def _zero_specs(self, stacked=False):
        from jax.sharding import PartitionSpec as P
        ax = self._data_axis
        axes = self._shard_axes      # joint masters/state/resid sharding
        ispec = P(None, ax) if stacked else P(ax)
        in_specs = (P(axes), P(axes), P(axes, None), P(), ispec,
                    P(), P(), P())
        out_core = (P(axes), P(axes), P(axes, None), P())
        return in_specs, out_core

    def _build_zero_step(self):
        if self._zstep is not None:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P
        impl = self._zero_impl()
        self._zimpl = impl
        has_ls = self._has_ls
        ax = self._data_axis
        mesh = self._mesh

        if has_ls:
            def zstep(masters, states, resid, aux, inputs, rng, lr, t,
                      ls):
                return impl(masters, states, resid, aux, inputs, rng,
                            lr, t, ls)
        else:
            def zstep(masters, states, resid, aux, inputs, rng, lr, t):
                return impl(masters, states, resid, aux, inputs, rng,
                            lr, t, None)
        zstep.__name__ = self._program_tag

        in_specs, out_core = self._zero_specs()
        ls_extra = (P(),) if has_ls else ()
        out_specs = out_core + (P(), P(ax), P(), P()) + ls_extra
        sm = shard_map(zstep, mesh=mesh, in_specs=in_specs + ls_extra,
                       out_specs=out_specs)
        try:
            sm.__name__ = self._program_tag
        except AttributeError:      # pragma: no cover
            pass
        ns = lambda spec: NamedSharding(mesh, spec)
        self._zstep = jax.jit(
            sm,
            in_shardings=tuple(ns(s) for s in in_specs)
            + tuple(ns(s) for s in ls_extra),
            out_shardings=tuple(ns(s) for s in out_specs),
            donate_argnums=(0, 1, 2))

    def _zero_multi_fn(self, k, outputs_mode, unroll=False):
        key = (int(k), outputs_mode,
               "full" if unroll is True else max(1, int(unroll)))
        fn = self._zero_multi.get(key)
        if fn is not None:
            return fn
        from jax.sharding import NamedSharding, PartitionSpec as P
        impl = self._zimpl
        has_ls = self._has_ls
        ax = self._data_axis
        mesh = self._mesh
        unroll_arg = True if key[2] == "full" else key[2]

        if has_ls:
            def multi(masters, states, resid, aux, inputs, rng, lr, t,
                      ls):
                def body(carry, xs):
                    masters, states, resid, aux, rng, t, ls = carry
                    (masters, states, resid, aux, loss, outputs, rng, t,
                     ls) = impl(masters, states, resid, aux, xs, rng,
                                lr, t, ls)
                    ys = (loss, outputs) if outputs_mode == "all" \
                        else loss
                    return (masters, states, resid, aux, rng, t, ls), ys

                (masters, states, resid, aux, rng, t, ls), ys = \
                    jax.lax.scan(body,
                                 (masters, states, resid, aux, rng, t,
                                  ls), inputs, length=key[0],
                                 unroll=unroll_arg)
                losses, outputs = ys if outputs_mode == "all" \
                    else (ys, ())
                return (masters, states, resid, aux, losses, outputs,
                        rng, t, ls)
        else:
            def multi(masters, states, resid, aux, inputs, rng, lr, t):
                def body(carry, xs):
                    masters, states, resid, aux, rng, t = carry
                    (masters, states, resid, aux, loss, outputs, rng,
                     t) = impl(masters, states, resid, aux, xs, rng,
                               lr, t, None)
                    ys = (loss, outputs) if outputs_mode == "all" \
                        else loss
                    return (masters, states, resid, aux, rng, t), ys

                (masters, states, resid, aux, rng, t), ys = jax.lax.scan(
                    body, (masters, states, resid, aux, rng, t), inputs,
                    length=key[0], unroll=unroll_arg)
                losses, outputs = ys if outputs_mode == "all" \
                    else (ys, ())
                return (masters, states, resid, aux, losses, outputs,
                        rng, t)
        multi.__name__ = self._program_tag.replace("zstep", "zstepk")

        in_specs, out_core = self._zero_specs(stacked=True)
        ls_extra = (P(),) if has_ls else ()
        out_specs = out_core + (
            P(), P(None, ax) if outputs_mode == "all" else P(),
            P(), P()) + ls_extra
        sm = shard_map(multi, mesh=mesh, in_specs=in_specs + ls_extra,
                       out_specs=out_specs)
        ns = lambda spec: NamedSharding(mesh, spec)
        fn = jax.jit(
            sm,
            in_shardings=tuple(ns(s) for s in in_specs)
            + tuple(ns(s) for s in ls_extra),
            out_shardings=tuple(ns(s) for s in out_specs),
            donate_argnums=(0, 1, 2))
        self._zero_multi[key] = fn
        return fn

    # -- public step surface (dp contract) -----------------------------------

    def _tick_counters(self, k):
        L = self._layout
        ag, red = L.wire_bytes_breakdown(self._zero_stage,
                                         self._compute_itemsize,
                                         self._wire_itemsize)
        _COUNTERS["zero_wire_bytes"] += (ag + red) * int(k)
        _COUNTERS["zero_wire_allgather_bytes"] += ag * int(k)
        _COUNTERS["zero_wire_reduce_bytes"] += red * int(k)
        _COUNTERS["zero_steps"] += int(k)
        _COUNTERS["zero_overlap_frac"] = L.overlap_frac()
        _COUNTERS["zero_stage"] = self._zero_stage
        _COUNTERS["zero_buckets"] = L.n_buckets
        _COUNTERS["zero_compress_bits"] = self._wire_itemsize * 8
        # XLA-reported FLOPs of the active zero step program (devstats
        # async extraction; 0 until the first extraction lands)
        from ..telemetry import devstats
        costs = devstats.step_costs()
        if costs["flops"] > 0 and str(costs["name"]).startswith("zero"):
            _COUNTERS["zero_flops_per_step"] = costs["flops"]

    def step(self, params, states, aux, inputs, rng=None):
        if self._zstep is None:
            raise MXNetError("ZeroTrainer.step before init_state/"
                             "import_training_state")
        self._ensure_dev_state(rng)
        from ..telemetry import devstats
        name = "zero%d.step" % self._zero_stage
        if self._has_ls:
            args = (params, states, self._resid_dev, aux, inputs,
                    self._rng_dev, self._lr_dev, self._t_dev,
                    self._ls_dev)
            devstats.on_dispatch(name, self._zstep, args, steps=1)
            out = self._zstep(*args)
            self._ls_dev = out[8]
        else:
            args = (params, states, self._resid_dev, aux, inputs,
                    self._rng_dev, self._lr_dev, self._t_dev)
            devstats.on_dispatch(name, self._zstep, args, steps=1)
            out = self._zstep(*args)
        self._resid_dev = out[2]
        self._rng_dev, self._t_dev = out[6], out[7]
        self._tick_counters(1)
        return out[0], out[1], out[3], out[4], out[5]

    def step_k(self, params, states, aux, inputs, rng=None,
               outputs_mode="none", unroll=False):
        if self._zstep is None:
            raise MXNetError("ZeroTrainer.step_k before init_state/"
                             "import_training_state")
        self._ensure_dev_state(rng)
        k = int(inputs[0].shape[0])
        fn = self._zero_multi_fn(k, outputs_mode, unroll)
        from ..telemetry import devstats
        name = "zero%d.step_k%d" % (self._zero_stage, k)
        if self._has_ls:
            args = (params, states, self._resid_dev, aux, inputs,
                    self._rng_dev, self._lr_dev, self._t_dev,
                    self._ls_dev)
            devstats.on_dispatch(name, fn, args, steps=k)
            out = fn(*args)
            self._ls_dev = out[8]
        else:
            args = (params, states, self._resid_dev, aux, inputs,
                    self._rng_dev, self._lr_dev, self._t_dev)
            devstats.on_dispatch(name, fn, args, steps=k)
            out = fn(*args)
        self._resid_dev = out[2]
        self._rng_dev, self._t_dev = out[6], out[7]
        self._tick_counters(k)
        return out[0], out[1], out[3], out[4], out[5]

    # -- host views / checkpoint round-trip ----------------------------------

    def host_params(self, params):
        """name -> full per-parameter fp32 host arrays (np.asarray of a
        sharded global array materializes the gather)."""
        L = self._layout
        out = {}
        for b, m in enumerate(params):
            flat = _np.asarray(m)
            for i, arr in L.unflatten_host(flat, b):
                out[self._param_names[i]] = arr
        return out

    def export_training_state(self, params, states, aux):
        """Same per-parameter array names as dp (param:/opt:/aux:), so
        ZeRO checkpoints restore into plain dp and vice versa — an
        MXNET_ZERO_STAGE change across a resume is just a repack. Adds
        the zero meta block (stage/compress/ownership) and, under
        compression, the per-device error-feedback residuals."""
        L = self._layout
        arrays = {}
        for n, a in self.host_params(params).items():
            arrays[f"param:{n}"] = a
        for b in range(L.n_buckets):
            for j in range(self._n_states):
                flat = _np.asarray(states[b][j])
                for i, arr in L.unflatten_host(flat, b):
                    arrays[f"opt:{self._param_names[i]}:{j}"] = arr
        for n, a in zip(self._aux_names, aux):
            arrays[f"aux:{n}"] = _np.asarray(a)
        meta = self._export_meta()
        meta["zero"] = {
            "stage": self._zero_stage,
            "compress": self._compress,
            "bucket_bytes": self._bucket_bytes,
            "ownership": L.ownership(self._param_names, self._n_states),
        }
        if self._wire_dtype is not None:
            for b, r in enumerate(self._resid_dev):
                arrays[f"zero_resid:{b}"] = _np.asarray(r)
        return arrays, meta

    def import_training_state(self, arrays, meta):
        hp = [_np.asarray(arrays[f"param:{n}"], _np.float32)
              for n in self._param_names]
        hs = [[_np.asarray(arrays[f"opt:{n}:{j}"], _np.float32)
               for j in range(self._n_states)]
              for n in self._param_names]
        masters, zstates = self._pack_from_host(hp, hs)
        put = lambda v: jax.device_put(_np.asarray(v), self._repl)
        aux = tuple(put(arrays[f"aux:{n}"]) for n in self._aux_names)
        self._import_scalar_state(meta)
        if self._wire_dtype is not None:
            L = self._layout
            resid = []
            compat = True
            for b in range(L.n_buckets):
                r = arrays.get(f"zero_resid:{b}")
                if r is None or tuple(_np.asarray(r).shape) != \
                        (self._n_dev, L.padded[b]):
                    compat = False
                    break
                resid.append(jax.device_put(
                    _np.asarray(r, _np.float32), self._rshard))
            if compat and resid:
                self._resid_dev = tuple(resid)
            # else: _pack_from_host already zeroed them — an elastic
            # restore at a different device count or from a plain-dp
            # checkpoint drops the residual (a bounded one-step
            # compression-error loss, not a correctness loss)
        return masters, zstates, aux


# ============================================================================
# CLI: --selftest / --hlo-check / --bench  (tools/ci.sh quick + bench.py)
# ============================================================================

def _wide_sym(dim=64, hidden=256, nclass=16):
    import mxnet_tpu as mx
    net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                num_hidden=hidden, name="zfc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="zfc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=nclass, name="zfc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _make_trainer(sym, mesh, stage, compress="none", dtype="float32",
                  batch=16, optimizer="sgd", bucket_mb=0.002, **kw):
    """stage 0 -> plain dp baseline; >0 -> ZeroTrainer. The tiny default
    bucket threshold forces multi-bucket layouts on the selftest MLPs."""
    from mxnet_tpu.parallel import DataParallelTrainer as DP
    common = dict(optimizer=optimizer, learning_rate=0.1,
                  rescale_grad=1.0 / batch, dtype=dtype, **kw)
    if optimizer == "sgd":
        common["momentum"] = 0.9
    if stage == 0:
        return DP(sym, mesh, zero_stage=0, **common)
    return ZeroTrainer(sym, mesh, zero_stage=stage,
                       grad_compress=compress, zero_bucket_mb=bucket_mb,
                       **common)


def _ce_of(outs, y, n):
    p = _np.asarray(outs[0], _np.float32)
    return float(-_np.log(p[_np.arange(n), y.astype(int)] + 1e-8).mean())


def selftest(argv_devices=2):
    """2-device A/B vs the unsharded baseline, printed as ONE
    zero_selftest JSON line (tools/ci.sh quick):

      1. stage-1 fp32: BIT-identical trained params after 20 steps;
      2. stage-1 bf16: fp32 masters within a few bf16 ULP of dp's and
         bit-identical across two ZeRO runs (XLA elides one bf16
         rounding point inside dp's weight-grad dot+all-reduce chain
         that an explicit shard_map psum cannot reproduce — docs/ZERO.md
         "bf16 parity"; the wire stays half-width either way);
      3. stage-2 fp32: numerically equal (reduce-scatter may reassociate
         the sum) and loss trace close;
      4. stage-2 + fp8 error feedback: CE decreases over 60 steps and
         the carried residual is non-zero;
      5. wire bytes: two --hlo-check subprocesses prove the stage-2
         reduce-scatter exists and the fp8 grad-reduce moves less than
         1/4 of the fp32 all-reduce's bytes (post-SPMD HLO).
    """
    import json
    import subprocess
    import sys
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(argv_devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh

    n_dev = min(argv_devices, len(_jax.devices()))
    mesh = data_parallel_mesh(n_dev, _jax.devices()[:n_dev])
    batch, dim, nclass = 16, 64, 16
    rng = _np.random.RandomState(0)
    x = rng.normal(size=(batch, dim)).astype(_np.float32)
    y = rng.randint(0, nclass, size=(batch,)).astype(_np.float32)
    sym = _wide_sym(dim=dim, nclass=nclass)
    results = {"metric": "zero_selftest", "devices": n_dev}

    def _train(stage, compress="none", dtype="float32", steps=20,
               optimizer="sgd"):
        tr = _make_trainer(sym, mesh, stage, compress=compress,
                           dtype=dtype, batch=batch, optimizer=optimizer)
        params, states, aux = tr.init_state(
            {"data": (batch, dim), "softmax_label": (batch,)})
        inputs = tr.shard_inputs([x, y])
        ces = []
        for _ in range(steps):
            params, states, aux, loss, outs = tr.step(params, states,
                                                      aux, inputs)
            ces.append(_ce_of(outs, y, batch))
        return tr, params, ces

    # 1) stage-1 fp32 bitwise parity
    tr0, p0, ce0 = _train(0)
    tr1, p1, ce1 = _train(1)
    h0 = {n: _np.asarray(p) for n, p in zip(tr0.param_names, p0)}
    h1 = tr1.host_params(p1)
    results["stage1_fp32_bitwise"] = bool(
        all((h0[n] == h1[n]).all() for n in h0))

    # 2) stage-1 bf16: masters track dp at bf16-ULP scale, and ZeRO
    # itself is run-to-run deterministic (bitwise)
    tr0b, p0b, _ = _train(0, dtype="bfloat16")
    tr1b, p1b, _ = _train(1, dtype="bfloat16")
    tr1c, p1c, _ = _train(1, dtype="bfloat16")
    h0b = {n: _np.asarray(p) for n, p in zip(tr0b.param_names, p0b)}
    h1b = tr1b.host_params(p1b)
    h1c = tr1c.host_params(p1c)
    # Closeness is measured in units of the bf16 mantissa step at each
    # tensor's own scale: XLA elides one bf16 rounding point in dp's
    # fused weight-grad chain that shard_map cannot reproduce (see
    # docs/ZERO.md "bf16 parity"), so the two programs drift by O(ULP)
    # per step.  Measured worst case at 2 devices / 20 steps: 2.1 ULP.
    ulp = 2.0 ** -8        # bf16 mantissa step
    results["stage1_bf16_close"] = bool(all(
        float(_np.abs(h0b[n] - h1b[n]).max())
        <= 8 * ulp * max(float(_np.abs(h0b[n]).max()), 1e-6)
        for n in h0b))
    results["stage1_bf16_deterministic"] = bool(
        all((h1b[n] == h1c[n]).all() for n in h1b))

    # 3) stage-2 fp32: allclose (reduce-scatter reassociates)
    tr2, p2, ce2 = _train(2)
    h2 = tr2.host_params(p2)
    results["stage2_fp32_allclose"] = bool(
        all(_np.allclose(h0[n], h2[n], rtol=1e-5, atol=1e-6)
            for n in h0))
    results["stage2_ce_last"] = ce2[-1]

    # 4) fp8 + error feedback converges; residual is live
    tr8, p8, ce8 = _train(2, compress="fp8", steps=60)
    first, last = ce8[0], ce8[-1]
    resid_norm = float(sum(
        _np.abs(_np.asarray(r)).sum() for r in tr8._resid_dev))
    results["fp8_ce_first"] = first
    results["fp8_ce_last"] = last
    results["fp8_converges"] = bool(_np.isfinite(last) and last < first)
    results["fp8_residual_nonzero"] = bool(resid_norm > 0)

    # 5) wire bytes from the post-SPMD HLO (fresh subprocesses: the
    # dump flags are consumed once at backend init)
    def _hlo(stage, compress):
        proc = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.parallel.zero",
             "--hlo-check", "--stage", str(stage),
             "--compress", compress],
            capture_output=True, text=True, timeout=300)
        from mxnet_tpu.analysis.hloaudit import parse_last_metric
        rec = parse_last_metric(proc.stdout, "zero_hlo_check")
        rec.setdefault("_stderr", (proc.stderr or "")[-300:])
        return rec

    h_base = _hlo(0, "none")
    h_z2 = _hlo(2, "none")
    h_f8 = _hlo(2, "fp8")
    base_bytes = h_base.get("grad_reduce_bytes_per_step") or 0
    z2_bytes = h_z2.get("grad_reduce_bytes_per_step") or 0
    f8_bytes = h_f8.get("grad_reduce_bytes_per_step") or 0
    results["hlo_base_grad_reduce_bytes"] = base_bytes
    results["hlo_zero2_grad_reduce_bytes"] = z2_bytes
    results["hlo_zero2_fp8_grad_reduce_bytes"] = f8_bytes
    results["hlo_zero2_has_reduce_scatter"] = bool(
        h_z2.get("has_reduce_scatter"))
    # stage-2 halves the grad-reduce wire (rs = half an all-reduce);
    # fp8 cuts the remaining bytes 4x vs f32
    results["hlo_wire_reduced"] = bool(
        base_bytes and z2_bytes and f8_bytes
        and z2_bytes < base_bytes and f8_bytes * 4 <= base_bytes)

    ok = (results["stage1_fp32_bitwise"]
          and results["stage1_bf16_close"]
          and results["stage1_bf16_deterministic"]
          and results["stage2_fp32_allclose"]
          and results["fp8_converges"]
          and results["fp8_residual_nonzero"]
          and results["hlo_zero2_has_reduce_scatter"]
          and results["hlo_wire_reduced"])
    results["ok"] = bool(ok)
    print(json.dumps(results), flush=True)
    return 0 if ok else 1


def hlo_check(stage, compress="none", dtype="float32", devices=2):
    """Compile one (multi-bucket) step on a fresh pinned backend and
    report its post-SPMD collectives + ring wire bytes. stage 0 audits
    the plain dp baseline for the A/B."""
    import json
    import tempfile
    import os as _os
    dump = tempfile.mkdtemp(prefix="zero_hlo_")
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        + " --xla_dump_hlo_pass_re=.*spmd.*")
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh(devices, _jax.devices()[:devices])
    batch, dim, nclass = 16, 64, 16
    sym = _wide_sym(dim=dim, nclass=nclass)
    tr = _make_trainer(sym, mesh, stage, compress=compress, dtype=dtype,
                       batch=batch)
    params, states, aux = tr.init_state(
        {"data": (batch, dim), "softmax_label": (batch,)})
    x = _np.zeros((batch, dim), _np.float32)
    y = _np.zeros((batch,), _np.float32)
    params, states, aux, _, _ = tr.step(
        params, states, aux, tr.shard_inputs([x, y]))

    from mxnet_tpu.analysis.hloaudit import (spmd_collectives,
                                             collective_wire_bytes)
    tag = "jit_step" if stage == 0 else f"jit_{tr._program_tag}"
    colls = spmd_collectives(dump, tag)
    wires = collective_wire_bytes(colls, devices)
    # non-scalar all-reduces = gradient (or compressed-gradient) tensors;
    # scalar ones are the loss/finite reductions
    grad_ars = [c for c in colls["all-reduce"] if c[1]]
    rec = {"metric": "zero_hlo_check", "stage": stage,
           "compress": compress, "dtype": dtype, "devices": devices,
           "buckets": getattr(tr, "_layout", None).n_buckets
           if getattr(tr, "_layout", None) else 1,
           "collectives": {k: len(v) for k, v in colls.items()},
           "has_reduce_scatter": bool(colls["reduce-scatter"]),
           "grad_allreduce_nonscalar": len(grad_ars),
           "grad_reduce_bytes_per_step":
               wires["reduce-scatter"] + collective_wire_bytes(
                   {"all-reduce": grad_ars,
                    "reduce-scatter": [], "all-gather": []},
                   devices)["all-reduce"],
           "gather_bytes_per_step": wires["all-gather"],
           "wire_bytes_per_step": sum(wires.values())}
    rec["ok"] = bool(colls["all-reduce"] or colls["reduce-scatter"]) \
        and (stage == 0 or (rec["has_reduce_scatter"]
                            and not grad_ars) or stage == 1)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


def bench(devices=8, steps=12, hidden=1024, batch=16):
    """bench.py's `zero` lane body: dp fp32 vs ZeRO-1 vs ZeRO-2 vs
    ZeRO-2+fp8 on an N-virtual-device cpu mesh, one big-parameter Adam
    MLP (optimizer-update work dominates, which is exactly the work
    ZeRO de-replicates: dp updates ALL params on EVERY device; ZeRO
    updates 1/N per device). Wire bytes per step come from the
    post-SPMD dump of each arm's distinctly-named module. Prints one
    zero_bench JSON line."""
    import json
    import tempfile
    import time
    import os as _os
    dump = tempfile.mkdtemp(prefix="zero_bench_hlo_")
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        + " --xla_dump_hlo_pass_re=.*spmd.*")
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh
    from mxnet_tpu.analysis.hloaudit import (spmd_collectives,
                                             collective_wire_bytes)

    n_dev = min(devices, len(_jax.devices()))
    mesh = data_parallel_mesh(n_dev, _jax.devices()[:n_dev])
    dim, nclass = 256, 16
    sym = _wide_sym(dim=dim, hidden=hidden, nclass=nclass)
    rng = _np.random.RandomState(0)
    x = rng.normal(size=(batch, dim)).astype(_np.float32)
    y = rng.randint(0, nclass, size=(batch,)).astype(_np.float32)

    def _arm(stage, compress):
        tr = _make_trainer(sym, mesh, stage, compress=compress,
                           batch=batch, optimizer="adam",
                           bucket_mb=1.0)
        params, states, aux = tr.init_state(
            {"data": (batch, dim), "softmax_label": (batch,)})
        inputs = tr.shard_inputs([x, y])
        for _ in range(2):
            params, states, aux, loss, _ = tr.step(params, states, aux,
                                                   inputs)
        float(loss)
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                params, states, aux, loss, _ = tr.step(params, states,
                                                       aux, inputs)
            float(loss)
            rates.append(steps / (time.perf_counter() - t0))
        tag = "jit_step" if stage == 0 else f"jit_{tr._program_tag}"
        wires = collective_wire_bytes(spmd_collectives(dump, tag),
                                      n_dev)
        return sorted(rates)[1], sum(wires.values()), wires

    n_params = sum(
        max(1, int(_np.prod(s))) for n, s in zip(
            sym.list_arguments(),
            sym.infer_shape(data=(batch, dim),
                            softmax_label=(batch,))[0])
        if n not in ("data", "softmax_label"))
    dp_sps, dp_wire, _ = _arm(0, "none")
    z1_sps, z1_wire, _ = _arm(1, "none")
    z2_sps, z2_wire, _ = _arm(2, "none")
    z8_sps, z8_wire, _ = _arm(2, "fp8")
    rec = {"metric": "zero_bench", "devices": n_dev,
           "params": int(n_params), "optimizer": "adam",
           "batch": batch, "steps_per_window": steps,
           "dp_steps_per_s": round(dp_sps, 2),
           "zero1_steps_per_s": round(z1_sps, 2),
           "zero2_steps_per_s": round(z2_sps, 2),
           "zero2_fp8_steps_per_s": round(z8_sps, 2),
           "speedup_zero1": round(z1_sps / dp_sps, 3),
           "speedup_zero2": round(z2_sps / dp_sps, 3),
           "speedup_zero2_fp8": round(z8_sps / dp_sps, 3),
           "wire_bytes_per_step_dp": int(dp_wire),
           "wire_bytes_per_step_zero1": int(z1_wire),
           "wire_bytes_per_step_zero2": int(z2_wire),
           "wire_bytes_per_step_zero2_fp8": int(z8_wire),
           "wire_source": "post_spmd_hlo"}
    print(json.dumps(rec), flush=True)
    return 0


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.parallel.zero")
    ap.add_argument("--selftest", action="store_true",
                    help="2-device A/B vs unsharded dp (ci.sh quick)")
    ap.add_argument("--hlo-check", action="store_true",
                    help="post-SPMD collective/wire-byte report")
    ap.add_argument("--bench", action="store_true",
                    help="dp vs ZeRO-1/2/fp8 steps/s + wire bytes")
    ap.add_argument("--stage", type=int, default=2)
    ap.add_argument("--compress", default="none",
                    choices=["none", "bf16", "fp8"])
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "float16"])
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--steps", type=int, default=12)
    args = ap.parse_args(argv)
    if args.hlo_check:
        return hlo_check(args.stage, args.compress, args.dtype,
                         args.devices)
    if args.bench:
        return bench(devices=args.devices, steps=args.steps)
    if args.selftest:
        return selftest(args.devices)
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
