"""Row-sharded embedding training with row-sparse gradient exchange
(mx.parallel.embedding).

Role of the reference's row_sparse recommender stack — `Embedding` over a
row_sparse weight, `KVStore.PullRowSparse`, and the sparse optimizer
kernels (PAPER.md §3/§6) — composed TPU-native into one shard_map step.
A vocab-size table cannot replicate per device ("millions of users" is
the ROADMAP's recommender scenario), and a dense gradient exchange moves
the WHOLE table every step even though a batch touches a sliver of it.
Here:

  placement   the (V, D) table is row-sharded 1/N per device over the dp
              mesh axis (padded so the shard is even); optimizer state
              for the table is sharded identically, so memory AND update
              cost drop N-fold.
  lookup      each device dedups its local batch's flat ids
              (ops/sparse_ops.unique_rows — static-shape jnp.unique),
              all-gathers the per-device unique id lists, serves the rows
              it owns (non-owned slots contribute zeros), and a
              psum-scatter returns exactly each device's unique rows —
              a gather whose wire scales with TOUCHED rows, not vocab.
  backward    the loss is differentiated wrt the gathered unique ROWS
              (never the table — autodiff would materialize a dense
              (V/N, D) cotangent), and the (rows, vals) pairs are
              exchanged as-is: one all-gather of the per-row gradients,
              a second dedup + segment-sum on the receiver, then the
              lazy `rows_*` scatter kernels update only owned touched
              rows. Out-of-shard slots map one-past-the-shard and the
              kernels' mode="drop" scatters discard them.
  dense MLP   the non-embedding parameters keep the normal dp path:
              replicated, gradient psum, same fused update formulas.

``MXNET_EMBED_EXCHANGE=dense`` keeps the table replicated and all-reduces
the dense (V, D) gradient — the paper-baseline A/B the bench lane and
`hloaudit.fit_step_embedding` measure against. With every row touched
(fp32) the two exchanges are BIT-identical: same forward values, same
per-row scatter-add sums, same `rows_*` update kernels.

``MXNET_EMBED_COMPRESS=bf16|fp8`` casts the backward (rows, vals)
exchange to a narrow wire dtype (fp8 adds a per-row max-abs scale
exchanged alongside). Unlike parallel/zero.py's bucket compression there
is NO error-feedback residual: a residual needs stable coordinates
across steps, and a row's slot in the per-step unique list is not one —
the honest alternative would be a per-device table-sized residual,
defeating the sharding. Per-row scaling bounds the relative error at the
wire dtype's mantissa step instead; convergence is asserted by the
selftest (docs/SPARSE.md "wire compression").

Env surface: ``MXNET_EMBED_EXCHANGE=sparse|dense``,
``MXNET_EMBED_UNIQUE_CAP`` (per-device unique-row slots, 0 = auto =
local ids per step, always lossless), ``MXNET_EMBED_COMPRESS``.

CLI: ``python -m mxnet_tpu.parallel.embedding --selftest`` (tiny-DLRM
convergence, dense-vs-sparse bit-identity when every row is touched,
checkpoint resume across sharding changes, wire proof), ``--hlo-check``
(post-SPMD collective/wire report at a given vocab), ``--bench``
(bench.py's `dlrm` lane: sparse vs dense steps/s + wire bytes at ≤5%
touched rows).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .mesh import shard_map

__all__ = ["EmbeddingTrainer", "EmbeddingLayout", "counters",
           "resolve_exchange", "resolve_compress", "resolve_unique_cap"]

# wire dtypes for MXNET_EMBED_COMPRESS (same encodings as
# zero.WIRE_DTYPES; fp8 e4m3 keeps the most mantissa)
WIRE_DTYPES = {
    "bf16": jnp.bfloat16,
    "fp8": getattr(jnp, "float8_e4m3fn", jnp.bfloat16),
}
# fp8 per-row scale target: e4m3 tops out at 448; scaling row maxima to
# 240 leaves headroom for the decode multiply to stay finite
_FP8_AMAX = 240.0


def resolve_exchange(value=None):
    """Exchange mode: explicit arg wins, else MXNET_EMBED_EXCHANGE,
    else sparse."""
    if value is None:
        from .. import config
        value = config.get("MXNET_EMBED_EXCHANGE", "sparse")
    mode = str(value or "sparse").strip().lower()
    if mode not in ("sparse", "dense"):
        raise MXNetError(
            f"MXNET_EMBED_EXCHANGE must be sparse|dense, got {value!r}")
    return mode


def resolve_compress(value=None):
    """Wire-compression mode: none|bf16|fp8 (MXNET_EMBED_COMPRESS)."""
    if value is None:
        from .. import config
        value = config.get("MXNET_EMBED_COMPRESS", "none")
    mode = str(value or "none").strip().lower()
    if mode in ("", "0", "none", "off"):
        return "none"
    if mode not in WIRE_DTYPES:
        raise MXNetError(
            f"MXNET_EMBED_COMPRESS must be none|bf16|fp8, got {value!r}")
    return mode


def resolve_unique_cap(value=None):
    """Per-device unique-row slots per step (0 = auto = the local id
    count, which can never drop a row). A positive cap bounds the
    exchange size; it must cover the worst-case per-device unique count
    or over-cap rows lose their gradient (jnp.unique keeps the smallest
    ids) — docs/SPARSE.md "unique cap"."""
    if value is None:
        from .. import config
        value = config.get("MXNET_EMBED_UNIQUE_CAP", 0)
    try:
        cap = int(value)
    except (TypeError, ValueError):
        raise MXNetError(
            f"MXNET_EMBED_UNIQUE_CAP must be an int, got {value!r}")
    if cap < 0:
        raise MXNetError(
            f"MXNET_EMBED_UNIQUE_CAP must be >= 0, got {cap}")
    return cap


class EmbeddingLayout:
    """Row-shard layout of a (vocab, dim) table over N devices plus the
    analytic wire accounting of one training step.

    The vocab is padded to a multiple of N so the P("data") row shard is
    even; pad rows can never be looked up (ids are validated < vocab)
    and the one-past-the-pad sentinel marks unique-list slack. Ring
    collective accounting matches ZeroLayout: all-gather/reduce-scatter
    move (N-1)/N of the global buffer per device, all-reduce twice that.
    """

    def __init__(self, vocab, dim, n_dev, unique, n_states):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.n_dev = int(n_dev)
        self.unique = int(unique)           # per-device unique slots U
        self.n_states = int(n_states)
        self.padded_vocab = self.vocab + (-self.vocab % self.n_dev)
        self.rows_per_dev = self.padded_vocab // self.n_dev
        self.sentinel = self.padded_vocab   # fill id: owned by no shard

    def wire_bytes_per_step(self, exchange, wire_itemsize, mlp_bytes):
        """Analytic per-device wire bytes of one step (feeds the live
        `embed_wire_bytes` counter without a device sync; the measured
        numbers come from hloaudit.spmd_collectives). Sparse exchange:
        id all-gather + row psum-scatter forward, value all-gather (+
        fp8 scales) backward — every term scales with N*U, none with
        vocab. Dense exchange: one table-sized fp32 all-reduce."""
        n = self.n_dev
        frac = (n - 1) / n
        mlp = 2.0 * frac * mlp_bytes                    # grad all-reduce
        if exchange == "dense":
            return int(mlp + 2.0 * frac
                       * self.padded_vocab * self.dim * 4)
        nu = n * self.unique
        table = (nu * 4                                 # fwd id gather
                 + nu * self.dim * 4                    # fwd row scatter
                 + nu * self.dim * wire_itemsize)       # bwd val gather
        if wire_itemsize == 1:
            table += nu * 4                             # fp8 row scales
        return int(mlp + frac * table)

    def ownership(self, mlp_names):
        """{array name: owning dp rank} for checkpoint shard placement
        (checkpoint/state.to_shard_files ownership=): the table and its
        optimizer rows live row-sharded on every rank — rank 0 seals
        them (it already owns the leading rows); replicated MLP arrays
        round-robin so no single shard carries the whole dense tail."""
        own = {"param:embed": 0}
        for j in range(self.n_states):
            own[f"opt:embed:{j}"] = 0
        for i, n in enumerate(mlp_names):
            k = i % self.n_dev
            own[f"param:{n}"] = k
            for j in range(self.n_states):
                own[f"opt:{n}:{j}"] = k
        return own


# -- live counter export (profiler hook "embed", scraped by telemetry) -------

_COUNTERS = {"embed_wire_bytes": 0, "embed_steps": 0,
             "embed_unique_rows": 0, "embed_touched_frac": 0.0,
             "embed_vocab_rows": 0, "embed_sparse": 1,
             "embed_compress_bits": 32}
# last step's device-resident global-unique-row count: materialized at
# scrape time (counters()), never on the step path — the dispatch loop
# must not sync on a scalar
_LAST_NNZ = {"dev": None, "vocab": 0}
_HOOKED = False


def counters():
    """Host-side embedding-exchange counters: cumulative analytic wire
    bytes, steps, and the last step's touched-row stats. Reading the
    touched-row count materializes one device scalar (scrape-time only;
    by then the step that produced it has long retired)."""
    dev, vocab = _LAST_NNZ["dev"], _LAST_NNZ["vocab"]
    if dev is not None and vocab:
        try:
            nnz = int(dev)
        except Exception:           # pragma: no cover - mid-teardown
            nnz = 0
        _COUNTERS["embed_unique_rows"] = nnz
        _COUNTERS["embed_touched_frac"] = round(nnz / vocab, 6)
    return dict(_COUNTERS)


def _ensure_hook():
    global _HOOKED
    if not _HOOKED:
        from .. import profiler
        profiler.register_counter_export("embed", counters)
        _HOOKED = True


def _bce_logits(logit, y):
    """Numerically stable sum of binary cross-entropy with logits."""
    z = logit.astype(jnp.float32)
    return jnp.sum(jnp.maximum(z, 0.0) - z * y
                   + jnp.log1p(jnp.exp(-jnp.abs(z))))


class EmbeddingTrainer:
    """One-table DLRM-style trainer: a row-sharded embedding over S
    categorical slots + an optional dense-feature input, concatenated
    into a replicated MLP ending in one click logit (sum-BCE loss).

    The whole step — sparse lookup exchange, fwd/bwd, row-sparse
    gradient exchange, lazy table update, MLP psum + update — is ONE
    shard_map program per config (distinctly named for the post-SPMD
    HLO audit). State is an opaque tuple the step round-trips (dp
    contract); host access goes through ``host_params`` /
    ``export_training_state``, which return full topology-independent
    per-parameter arrays so checkpoints interchange across device
    counts, unique caps, and MXNET_EMBED_EXCHANGE changes.
    """

    def __init__(self, mesh, vocab, embed_dim, n_slots, dense_dim=0,
                 mlp_hidden=(32,), optimizer="sgd", learning_rate=0.05,
                 momentum=0.0, wd=0.0, rescale_grad=1.0,
                 clip_gradient=None, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, exchange=None, compress=None,
                 unique_cap=None, batch_size=None, program_tag=None):
        if optimizer not in ("sgd", "adam"):
            raise MXNetError(
                f"EmbeddingTrainer supports sgd|adam, got {optimizer!r}")
        self._mesh = mesh
        self._ax = mesh.axis_names[0]
        self._n_dev = int(mesh.devices.size)
        self.vocab = int(vocab)
        self.dim = int(embed_dim)
        self.n_slots = int(n_slots)
        self.dense_dim = int(dense_dim)
        self.mlp_hidden = tuple(int(h) for h in mlp_hidden)
        self.optimizer = optimizer
        self._lr = float(learning_rate)
        self._momentum = float(momentum)
        self._wd = float(wd)
        self._rescale = float(rescale_grad)
        self._clip = -1.0 if clip_gradient is None else float(clip_gradient)
        self._beta1, self._beta2, self._eps = \
            float(beta1), float(beta2), float(epsilon)
        self.exchange = resolve_exchange(exchange)
        self.compress = resolve_compress(compress)
        self._wire_dtype = (None if self.compress == "none"
                            else WIRE_DTYPES[self.compress])
        self._wire_itemsize = (4 if self._wire_dtype is None else
                               _np.dtype(self._wire_dtype).itemsize)
        cap = resolve_unique_cap(unique_cap)
        if batch_size is not None and int(batch_size) % self._n_dev:
            raise MXNetError(
                f"global batch {batch_size} must divide over "
                f"{self._n_dev} devices")
        self._batch = None if batch_size is None else int(batch_size)
        self._cap = cap
        n_states = {"sgd": (1 if self._momentum else 0), "adam": 2}[
            optimizer]
        self._n_states = n_states
        # U is only known once the per-device id count is (first step)
        self._layout = None
        self._step_fn = None
        # distinct jit names per config; no tag a prefix of another
        # (hloaudit matches the dump by module substring)
        suffix = {"none": "n", "bf16": "b", "fp8": "f"}[self.compress]
        mode = {"sparse": "sp", "dense": "dn"}[self.exchange]
        self._program_tag = (program_tag or f"estep_{mode}{suffix}")
        self._t = 0.0
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._P = P
        self._repl = NamedSharding(mesh, P())
        self._bshard = NamedSharding(mesh, P(self._ax))
        self._tshard = (NamedSharding(mesh, P(self._ax, None))
                        if self.exchange == "sparse" else self._repl)
        _ensure_hook()

    # -- parameter surface ---------------------------------------------------

    @property
    def mlp_names(self):
        names = []
        for i in range(len(self.mlp_hidden) + 1):
            names += [f"mlp_w{i}", f"mlp_b{i}"]
        return names

    @property
    def param_names(self):
        return ["embed"] + self.mlp_names

    def _mlp_shapes(self):
        dims = ([self.n_slots * self.dim + self.dense_dim]
                + list(self.mlp_hidden) + [1])
        shapes = []
        for i in range(len(dims) - 1):
            shapes += [(dims[i], dims[i + 1]), (dims[i + 1],)]
        return shapes

    def _ensure_layout(self, n_local_ids):
        if self._layout is None:
            u = self._cap or int(n_local_ids)
            self._layout = EmbeddingLayout(self.vocab, self.dim,
                                           self._n_dev, u,
                                           self._n_states)
        return self._layout

    # -- state init / placement ----------------------------------------------

    def init_state(self, batch_size=None, seed=0):
        """(table, tstates, mlp, mstates, t) device state. The table is
        placed row-sharded (sparse exchange) or replicated (dense); the
        MLP replicates; `t` is the device-carried update count (adam
        bias correction), restored by import_training_state."""
        b = self._batch if batch_size is None else int(batch_size)
        if b is None:
            raise MXNetError("init_state needs batch_size")
        if b % self._n_dev:
            raise MXNetError(f"global batch {b} must divide over "
                             f"{self._n_dev} devices")
        self._batch = b
        L = self._ensure_layout(b // self._n_dev * self.n_slots)
        rng = _np.random.RandomState(seed)
        table = rng.normal(0.0, 0.01, size=(
            L.padded_vocab, self.dim)).astype(_np.float32)
        table[self.vocab:] = 0.0
        mlp = []
        for s in self._mlp_shapes():
            if len(s) == 2:
                mlp.append(rng.normal(
                    0.0, _np.sqrt(2.0 / s[0]), size=s)
                    .astype(_np.float32))
            else:
                mlp.append(_np.zeros(s, _np.float32))
        return self._place(table, [_np.zeros_like(table)
                                   for _ in range(self._n_states)],
                           mlp, [[_np.zeros_like(p)
                                  for _ in range(self._n_states)]
                                 for p in mlp], 0.0)

    def _place(self, table, tstates, mlp, mstates, t):
        self._t = float(t)
        put_t = lambda a: jax.device_put(
            _np.asarray(a, _np.float32), self._tshard)
        put_r = lambda a: jax.device_put(
            _np.asarray(a, _np.float32), self._repl)
        return (put_t(table), tuple(put_t(s) for s in tstates),
                tuple(put_r(p) for p in mlp),
                tuple(tuple(put_r(s) for s in st) for st in mstates),
                put_r(_np.float32(t)))

    def shard_inputs(self, arrays):
        """[ids (B,S) int, dense (B,F) f32, labels (B,) f32] -> device
        arrays sharded along the batch axis."""
        out = []
        for a in arrays:
            a = _np.asarray(a)
            a = a.astype(_np.int32 if _np.issubdtype(a.dtype, _np.integer)
                         else _np.float32)
            out.append(jax.device_put(a, self._bshard))
        return tuple(out)

    # -- the step program ----------------------------------------------------

    def _optimizer_rows(self, weight, states, rows, grad_rows, lr_t):
        """One lazy row-update: the SAME ops/sparse_ops kernels in every
        mode — sparse exchange hands them the deduped owned rows, the
        dense baseline and the MLP hand them an iota over all rows —
        so cross-mode parity is a data question, never a formula one."""
        from ..ops import sparse_ops as sp
        lr, t = lr_t
        if self.optimizer == "sgd":
            if self._n_states:
                w, m = sp.rows_sgd_mom_update(
                    weight, states[0], rows, grad_rows, lr,
                    self._momentum, wd=self._wd,
                    rescale_grad=self._rescale, clip_gradient=self._clip)
                return w, (m,)
            w = sp.rows_sgd_update(
                weight, rows, grad_rows, lr, wd=self._wd,
                rescale_grad=self._rescale, clip_gradient=self._clip)
            return w, ()
        eff_lr = lr * jnp.sqrt(1.0 - self._beta2 ** t) \
            / (1.0 - self._beta1 ** t)
        w, m, v = sp.rows_adam_update(
            weight, states[0], states[1], rows, grad_rows, eff_lr,
            self._beta1, self._beta2, self._eps, wd=self._wd,
            rescale_grad=self._rescale, clip_gradient=self._clip)
        return w, (m, v)

    def _mlp_forward(self, mlp, feat):
        h = feat
        n_layers = len(self.mlp_hidden) + 1
        for i in range(n_layers):
            w, b = mlp[2 * i], mlp[2 * i + 1]
            h = h @ w + b
            if i < n_layers - 1:
                h = jax.nn.relu(h)
        return h.reshape(-1)

    def _encode_wire(self, g):
        """Backward wire cast: bf16 is a straight cast (fp32 exponent
        range); fp8 e4m3 rides a per-row max-abs scale exchanged
        alongside (no residual — see module docstring)."""
        if self.compress == "bf16":
            return g.astype(jnp.bfloat16), None
        amax = jnp.max(jnp.abs(g), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / _FP8_AMAX, 1.0)
        return (g / scale).astype(self._wire_dtype), \
            scale[:, 0].astype(jnp.float32)

    def _impl(self):
        L = self._layout
        ax = self._ax
        n_dev, U = self._n_dev, L.unique
        R, Vp, sent = L.rows_per_dev, L.padded_vocab, L.sentinel
        dim, slots, ddim = self.dim, self.n_slots, self.dense_dim
        sparse = self.exchange == "sparse"
        wire_dt = self._wire_dtype
        lr = self._lr
        from ..ops import sparse_ops as sp

        def impl(table, tstates, mlp, mstates, t, ids, dense, labels):
            t = t + 1.0
            flat = ids.reshape(-1).astype(jnp.int32)

            if sparse:
                # [1] dedup local ids, gather every device's unique
                # list, serve owned rows, scatter the sums back: each
                # device ends with ITS unique rows (U, D). Non-owned
                # slots contribute exact zeros to the psum.
                uniq, inv, _ = sp.unique_rows(flat, U, sent)
                all_ids = jax.lax.all_gather(uniq, ax, tiled=True)
                k = jax.lax.axis_index(ax)
                lo = (k * R).astype(jnp.int32)
                owned = (all_ids >= lo) & (all_ids < lo + R)
                loc = jnp.where(owned, all_ids - lo, R)
                contrib = jnp.take(table, loc, axis=0, mode="fill",
                                   fill_value=0.0)
                rows = jax.lax.psum_scatter(
                    contrib, ax, scatter_dimension=0, tiled=True)
            else:
                rows, inv = table, flat

            def loss_fn(rows, mlp):
                emb = jnp.take(rows, inv, axis=0)
                feat = emb.reshape(-1, slots * dim)
                if ddim:
                    feat = jnp.concatenate([feat, dense], axis=1)
                return _bce_logits(self._mlp_forward(mlp, feat), labels)

            loss, (g_rows, g_mlp) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(rows, mlp)

            if sparse:
                # [2] row-sparse gradient exchange: (rows, vals) pairs
                # on the wire, never a table-sized buffer. The id list
                # was already gathered in [1]; only values (+ fp8
                # scales) move here.
                if wire_dt is not None:
                    wire, scales = self._encode_wire(g_rows)
                    vals = jax.lax.all_gather(
                        wire, ax, tiled=True).astype(jnp.float32)
                    if scales is not None:
                        s_all = jax.lax.all_gather(scales, ax,
                                                   tiled=True)
                        vals = vals * s_all[:, None]
                else:
                    vals = jax.lax.all_gather(g_rows, ax, tiled=True)
                # [3] receiver-side dedup: devices sharing a row each
                # contributed a partial sum — segment-sum them, then
                # map to local shard coordinates (one-past-the-shard
                # for non-owned/pad slots; the rows_* kernels drop
                # those writes)
                uniq2, inv2, nnz = sp.unique_rows(all_ids, n_dev * U,
                                                  sent)
                gsum = sp.segment_sum_rows(vals, inv2, n_dev * U)
                owned2 = (uniq2 >= lo) & (uniq2 < lo + R)
                rows2 = jnp.where(owned2, uniq2 - lo, R)
                new_table, new_tstates = self._optimizer_rows(
                    table, tstates, rows2, gsum, (lr, t))
            else:
                g_table = jax.lax.psum(g_rows, ax)
                all_rows = jnp.arange(Vp, dtype=jnp.int32)
                new_table, new_tstates = self._optimizer_rows(
                    table, tstates, all_rows, g_table, (lr, t))
                nnz = jnp.int32(Vp)

            # [4] dense MLP params: the normal dp path — psum'd grads,
            # replicated update (iota rows, same kernels)
            new_mlp, new_mstates = [], []
            for p, st, g in zip(mlp, mstates, g_mlp):
                g = jax.lax.psum(g, ax)
                p2 = p.reshape(p.shape[0], -1)
                w, s2 = self._optimizer_rows(
                    p2, tuple(s.reshape(p2.shape) for s in st),
                    jnp.arange(p2.shape[0], dtype=jnp.int32),
                    g.reshape(p2.shape), (lr, t))
                new_mlp.append(w.reshape(p.shape))
                new_mstates.append(tuple(s.reshape(p.shape)
                                         for s in s2))
            loss = jax.lax.psum(loss, ax)
            return (new_table, tuple(new_tstates), tuple(new_mlp),
                    tuple(new_mstates), t, loss, nnz)

        return impl

    def _build_step(self):
        if self._step_fn is not None:
            return
        from jax.sharding import NamedSharding
        P = self._P
        ax = self._ax
        tspec = P(ax, None) if self.exchange == "sparse" else P()
        impl = self._impl()

        def estep(table, tstates, mlp, mstates, t, ids, dense, labels):
            return impl(table, tstates, mlp, mstates, t, ids, dense,
                        labels)
        estep.__name__ = self._program_tag

        in_specs = (tspec, tspec, P(), P(), P(), P(ax), P(ax), P(ax))
        out_specs = (tspec, tspec, P(), P(), P(), P(), P())
        sm = shard_map(estep, mesh=self._mesh, in_specs=in_specs,
                       out_specs=out_specs)
        try:
            sm.__name__ = self._program_tag
        except AttributeError:          # pragma: no cover
            pass
        ns = lambda spec: NamedSharding(self._mesh, spec)
        self._step_fn = jax.jit(
            sm, in_shardings=tuple(ns(s) for s in in_specs),
            out_shardings=tuple(ns(s) for s in out_specs),
            donate_argnums=(0, 1, 2, 3, 4))

    def _mlp_bytes(self):
        return sum(4 * max(1, int(_np.prod(s)))
                   for s in self._mlp_shapes())

    def _tick_counters(self, nnz_dev):
        L = self._layout
        _COUNTERS["embed_wire_bytes"] += L.wire_bytes_per_step(
            self.exchange, self._wire_itemsize, self._mlp_bytes())
        _COUNTERS["embed_steps"] += 1
        _COUNTERS["embed_vocab_rows"] = self.vocab
        _COUNTERS["embed_sparse"] = int(self.exchange == "sparse")
        _COUNTERS["embed_compress_bits"] = self._wire_itemsize * 8
        _LAST_NNZ["dev"] = nnz_dev
        _LAST_NNZ["vocab"] = self.vocab

    def step(self, state, inputs):
        """One fused train step: (state, inputs) -> (state, loss, nnz)
        where nnz is the global touched-row count (device scalar — only
        telemetry scrape materializes it)."""
        table, tstates, mlp, mstates, t = state
        self._ensure_layout(
            inputs[0].shape[0] // self._n_dev * self.n_slots)
        self._build_step()
        ids, dense, labels = inputs
        from ..telemetry import devstats
        name = f"embed_{self.exchange}.step"
        args = (table, tstates, mlp, mstates, t, ids, dense, labels)
        devstats.on_dispatch(name, self._step_fn, args, steps=1)
        out = self._step_fn(*args)
        self._tick_counters(out[6])
        return out[:5], out[5], out[6]

    # -- host views / checkpoint round-trip ----------------------------------

    def host_params(self, state):
        """name -> full fp32 host arrays; the table is trimmed back to
        (vocab, dim) so the export is topology-independent (pad rows
        are a device-count artifact)."""
        table = _np.asarray(state[0])[:self.vocab]
        out = {"embed": table}
        for n, p in zip(self.mlp_names, state[2]):
            out[n] = _np.asarray(p)
        return out

    def export_training_state(self, state):
        """checkpoint.TrainingState-ready (arrays, meta): the usual
        param:/opt: names with FULL per-parameter arrays, so a resume
        can change device count, MXNET_EMBED_EXCHANGE, or the unique
        cap and restore state_sha256-identical state. meta["embed"]
        carries the layout + the ownership map for sharded commits."""
        # scratch layout, NOT _ensure_layout: only the cap-independent
        # fields (padded_vocab, ownership) are read here, and caching a
        # layout before the first step would freeze the unique cap at a
        # value unrelated to the batch (a fresh trainer that imports a
        # checkpoint before ever stepping would silently truncate its
        # dedup list to n_slots rows)
        L = self._layout or EmbeddingLayout(
            self.vocab, self.dim, self._n_dev,
            self._cap or self.n_slots, self._n_states)
        arrays = {}
        for n, a in self.host_params(state).items():
            arrays[f"param:{n}"] = a
        for j in range(self._n_states):
            arrays[f"opt:embed:{j}"] = \
                _np.asarray(state[1][j])[:self.vocab]
            for n, st in zip(self.mlp_names, state[3]):
                arrays[f"opt:{n}:{j}"] = _np.asarray(st[j])
        meta = {
            "t": float(_np.asarray(state[4])),
            "optimizer": self.optimizer,
            "embed": {
                "exchange": self.exchange,
                "compress": self.compress,
                "vocab": self.vocab, "dim": self.dim,
                "unique_cap": self._cap,
                "ownership": L.ownership(self.mlp_names),
            },
        }
        return arrays, meta

    def import_training_state(self, arrays, meta):
        """Inverse of export: re-pad the table for THIS topology and
        re-place every array under the current exchange mode's
        shardings. The checkpoint's own exchange/unique-cap settings are
        irrelevant — full arrays carry no layout."""
        t = float((meta or {}).get("t", 0.0))
        table = _np.asarray(arrays["param:embed"], _np.float32)
        if table.shape != (self.vocab, self.dim):
            raise MXNetError(
                f"embed table shape {table.shape} != "
                f"{(self.vocab, self.dim)}")
        # scratch layout, NOT _ensure_layout: only the cap-independent
        # fields (padded_vocab, ownership) are read here, and caching a
        # layout before the first step would freeze the unique cap at a
        # value unrelated to the batch (a fresh trainer that imports a
        # checkpoint before ever stepping would silently truncate its
        # dedup list to n_slots rows)
        L = self._layout or EmbeddingLayout(
            self.vocab, self.dim, self._n_dev,
            self._cap or self.n_slots, self._n_states)
        pad = L.padded_vocab - self.vocab

        def _padded(a):
            a = _np.asarray(a, _np.float32)
            return _np.concatenate(
                [a, _np.zeros((pad,) + a.shape[1:], _np.float32)]) \
                if pad else a

        tstates = [_padded(arrays[f"opt:embed:{j}"])
                   for j in range(self._n_states)]
        mlp = [_np.asarray(arrays[f"param:{n}"], _np.float32)
               for n in self.mlp_names]
        mstates = [[_np.asarray(arrays[f"opt:{n}:{j}"], _np.float32)
                    for j in range(self._n_states)]
                   for n in self.mlp_names]
        return self._place(_padded(table), tstates, mlp, mstates, t)


# ============================================================================
# CLI: --selftest / --hlo-check / --bench  (tools/ci.sh quick + bench.py)
# ============================================================================

def _click_data(vocab, batch, slots, dense_dim, seed=0, structured=True):
    """Synthetic click data with learnable structure: the label is a
    parity-style function of two slots' ids plus a dense margin, so a
    table+MLP that memorizes per-row embeddings can drive the BCE
    down (the convergence assertion has something to converge TO)."""
    rng = _np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(batch, slots)).astype(_np.int32)
    dense = rng.normal(size=(batch, dense_dim)).astype(_np.float32) \
        if dense_dim else _np.zeros((batch, 0), _np.float32)
    if structured:
        y = (((ids[:, 0] + ids[:, 1 % slots]) % 2)
             ^ (dense[:, 0] > 0 if dense_dim else 0)).astype(_np.float32)
    else:
        y = rng.randint(0, 2, size=(batch,)).astype(_np.float32)
    return ids, dense, y


def _permutation_data(vocab, batch, slots, dense_dim, seed=0):
    """Every table row touched EXACTLY once globally (ids are a
    permutation of arange(vocab) reshaped to (batch, slots)): each row's
    gradient has a single contribution, so no exchange can reassociate
    a sum and dense-vs-sparse bit-identity is well-posed."""
    assert batch * slots == vocab
    rng = _np.random.RandomState(seed)
    ids = rng.permutation(vocab).astype(_np.int32).reshape(batch, slots)
    dense = rng.normal(size=(batch, dense_dim)).astype(_np.float32) \
        if dense_dim else _np.zeros((batch, 0), _np.float32)
    y = rng.randint(0, 2, size=(batch,)).astype(_np.float32)
    return ids, dense, y


def _mk(mesh, vocab, batch, exchange, compress="none", optimizer="adam",
        lr=0.02, slots=4, dense_dim=4, dim=8, tag=None, cap=None,
        momentum=0.9):
    return EmbeddingTrainer(
        mesh, vocab=vocab, embed_dim=dim, n_slots=slots,
        dense_dim=dense_dim, mlp_hidden=(32,), optimizer=optimizer,
        learning_rate=lr, momentum=momentum if optimizer == "sgd" else 0.0,
        rescale_grad=1.0 / batch, exchange=exchange, compress=compress,
        batch_size=batch, program_tag=tag, unique_cap=cap)


def _run(tr, data, steps, state=None, seed=0):
    if state is None:
        state = tr.init_state(seed=seed)
    inputs = tr.shard_inputs(list(data))
    losses = []
    for _ in range(steps):
        state, loss, nnz = tr.step(state, inputs)
        losses.append(float(loss))
    return state, losses, int(nnz)


def selftest(argv_devices=2):
    """A/B the sparse exchange against the dense baseline on a tiny
    DLRM, printed as ONE embed_selftest JSON line (tools/ci.sh quick):

      1. convergence: sum-BCE falls >30% over 60 adam steps (sparse);
      2. bit-identity: with every row touched exactly once globally
         (fp32, sgd+momentum AND adam), trained table+MLP+optimizer
         state match the dense exchange BIT-for-bit;
      3. wire compression: bf16 stays close to fp32; fp8 (per-row
         scales) still converges;
      4. checkpoint: export -> import across an exchange-mode AND
         unique-cap change -> re-export restores state_sha256-equal
         state, and training continues;
      5. wire: --hlo-check subprocesses prove post-SPMD exchange bytes
         are vocab-INdependent under sparse (equal at V and 2V) and
         vocab-proportional under dense.
    """
    import json
    import subprocess
    import sys
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(argv_devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh

    n_dev = min(argv_devices, len(_jax.devices()))
    mesh = data_parallel_mesh(n_dev, _jax.devices()[:n_dev])
    results = {"metric": "embed_selftest", "devices": n_dev}

    # 1) convergence on structured clicks
    vocab, batch, slots = 64, 32, 4
    data = _click_data(vocab, batch, slots, 4, seed=1)
    tr, = [_mk(mesh, vocab, batch, "sparse")]
    state, ces, nnz = _run(tr, data, 60)
    results["ce_first"] = round(ces[0], 4)
    results["ce_last"] = round(ces[-1], 4)
    results["touched_rows"] = nnz
    results["converges"] = bool(
        _np.isfinite(ces[-1]) and ces[-1] < 0.7 * ces[0])

    # 2) dense-vs-sparse bit-identity when every row is touched once
    pvocab = batch * slots
    pdata = _permutation_data(pvocab, batch, slots, 4, seed=2)
    bit = {}
    for optimizer in ("sgd", "adam"):
        tr_sp = _mk(mesh, pvocab, batch, "sparse", optimizer=optimizer)
        tr_dn = _mk(mesh, pvocab, batch, "dense", optimizer=optimizer)
        ssp, _, _ = _run(tr_sp, pdata, 10)
        sdn, _, _ = _run(tr_dn, pdata, 10)
        hs, hd = tr_sp.host_params(ssp), tr_dn.host_params(sdn)
        same = all((hs[n] == hd[n]).all() for n in hs)
        # optimizer state must match too (moments only decay on
        # touched rows — here that is EVERY row)
        same = same and all(
            (_np.asarray(a)[:pvocab] == _np.asarray(b)[:pvocab]).all()
            for a, b in zip(ssp[1], sdn[1]))
        bit[optimizer] = bool(same)
    results["bitwise_sgd"] = bit["sgd"]
    results["bitwise_adam"] = bit["adam"]

    # 3) wire compression
    s16, ce16, _ = _run(_mk(mesh, vocab, batch, "sparse",
                            compress="bf16"), data, 60)
    s8, ce8, _ = _run(_mk(mesh, vocab, batch, "sparse",
                          compress="fp8"), data, 60)
    results["bf16_ce_last"] = round(ce16[-1], 4)
    results["fp8_ce_last"] = round(ce8[-1], 4)
    results["bf16_close"] = bool(
        abs(ce16[-1] - ces[-1]) <= 0.15 * ces[0])
    results["fp8_converges"] = bool(
        _np.isfinite(ce8[-1]) and ce8[-1] < 0.7 * ce8[0])

    # 4) checkpoint resume across exchange-mode + unique-cap change
    from mxnet_tpu.checkpoint.state import state_sha256, TrainingState
    arrays, meta = tr.export_training_state(state)
    sha0 = state_sha256(TrainingState(arrays, meta={"trainer": meta}))
    tr_dn = _mk(mesh, vocab, batch, "dense", cap=2 * batch * slots)
    st2 = tr_dn.import_training_state(arrays, meta)
    arrays2, meta2 = tr_dn.export_training_state(st2)
    sha1 = state_sha256(TrainingState(arrays2, meta={"trainer": meta2}))
    results["resume_sha_equal"] = bool(sha0 == sha1)
    _, cont, _ = _run(tr_dn, data, 3, state=st2)
    results["resume_continues"] = bool(_np.isfinite(cont[-1]))

    # 5) wire proof from the post-SPMD HLO (fresh subprocesses: dump
    # flags are consumed once at backend init)
    def _hlo(exchange, vocab_n):
        proc = subprocess.run(
            [sys.executable, "-m", "mxnet_tpu.parallel.embedding",
             "--hlo-check", "--exchange", exchange,
             "--vocab", str(vocab_n), "--devices", str(n_dev)],
            capture_output=True, text=True, timeout=300)
        from mxnet_tpu.analysis.hloaudit import parse_last_metric
        rec = parse_last_metric(proc.stdout, "embed_hlo_check")
        rec.setdefault("_stderr", (proc.stderr or "")[-300:])
        return rec

    v1, v2 = 2048, 4096
    sp1, sp2 = _hlo("sparse", v1), _hlo("sparse", v2)
    dn1, dn2 = _hlo("dense", v1), _hlo("dense", v2)
    b_sp1 = sp1.get("exchange_bytes_per_step") or 0
    b_sp2 = sp2.get("exchange_bytes_per_step") or 0
    b_dn1 = dn1.get("exchange_bytes_per_step") or 0
    b_dn2 = dn2.get("exchange_bytes_per_step") or 0
    results["hlo_sparse_bytes_v1"] = b_sp1
    results["hlo_sparse_bytes_v2"] = b_sp2
    results["hlo_dense_bytes_v1"] = b_dn1
    results["hlo_dense_bytes_v2"] = b_dn2
    results["hlo_wire_scales_with_rows"] = bool(
        b_sp1 and b_sp1 == b_sp2            # vocab-independent
        and b_dn2 > int(1.5 * b_dn1)        # vocab-proportional
        and b_sp1 < b_dn1)                  # and smaller outright

    ok = (results["converges"] and results["bitwise_sgd"]
          and results["bitwise_adam"] and results["bf16_close"]
          and results["fp8_converges"] and results["resume_sha_equal"]
          and results["resume_continues"]
          and results["hlo_wire_scales_with_rows"])
    results["ok"] = bool(ok)
    print(json.dumps(results), flush=True)
    return 0 if ok else 1


def hlo_check(exchange, compress="none", vocab=2048, devices=2,
              batch=32, slots=4):
    """Compile one step on a fresh pinned backend and report its
    post-SPMD collectives + ring wire bytes, split into the embedding
    exchange vs the (vocab-independent) MLP all-reduce."""
    import json
    import tempfile
    import os as _os
    dump = tempfile.mkdtemp(prefix="embed_hlo_")
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        + " --xla_dump_hlo_pass_re=.*spmd.*")
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh
    from mxnet_tpu.analysis.hloaudit import (spmd_collectives,
                                             collective_wire_bytes)

    mesh = data_parallel_mesh(devices, _jax.devices()[:devices])
    mode = {"sparse": "sp", "dense": "dn"}[exchange]
    suffix = {"none": "n", "bf16": "b", "fp8": "f"}[compress]
    tag = f"estep_{mode}{suffix}_v{vocab}"
    tr = _mk(mesh, vocab, batch, exchange, compress=compress, tag=tag,
             slots=slots)
    data = _click_data(vocab, batch, slots, 4)
    state, _, _ = _run(tr, data, 1)

    colls = spmd_collectives(dump, f"jit_{tag}")
    import shutil
    shutil.rmtree(dump, ignore_errors=True)
    wires = collective_wire_bytes(colls, devices)
    mlp_ar = 2.0 * (devices - 1) / devices * tr._mlp_bytes()
    total = sum(wires.values())
    # scalar all-reduces (loss) round to 0 wire; the MLP all-reduce is
    # the only other vocab-independent term — everything else IS the
    # embedding exchange
    exch = max(0, int(total - wires["all-reduce"])) \
        if exchange == "sparse" else int(wires["all-reduce"] - mlp_ar)
    rec = {"metric": "embed_hlo_check", "exchange": exchange,
           "compress": compress, "vocab": vocab, "devices": devices,
           "unique_per_dev": tr._layout.unique,
           "collectives": {k: len(v) for k, v in colls.items()},
           "has_reduce_scatter": bool(colls["reduce-scatter"]),
           "exchange_bytes_per_step": exch,
           "mlp_allreduce_bytes": int(mlp_ar),
           "analytic_bytes_per_step": tr._layout.wire_bytes_per_step(
               exchange, tr._wire_itemsize, tr._mlp_bytes()),
           "wire_bytes_per_step": int(total)}
    rec["ok"] = bool(total > 0 and (
        exchange == "dense" or rec["has_reduce_scatter"]))
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


def bench(devices=8, steps=10, vocab=65536, dim=48, batch=256, slots=8):
    """bench.py's `dlrm` lane body: sparse vs dense gradient exchange
    on an N-virtual-device cpu mesh at a ≤5% touched-row fraction (the
    regime the row-sparse exchange exists for). Reports steps/s A/B,
    HLO-measured wire bytes per step for both arms, and the touched-row
    fraction. Prints one embed_bench JSON line."""
    import json
    import tempfile
    import time
    import os as _os
    dump = tempfile.mkdtemp(prefix="embed_bench_hlo_")
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        + " --xla_dump_hlo_pass_re=.*spmd.*")
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax as _jax
    from mxnet_tpu.parallel import data_parallel_mesh
    from mxnet_tpu.analysis.hloaudit import (spmd_collectives,
                                             collective_wire_bytes)

    n_dev = min(devices, len(_jax.devices()))
    mesh = data_parallel_mesh(n_dev, _jax.devices()[:n_dev])
    data = _click_data(vocab, batch, slots, 8, seed=0)
    touched = len(_np.unique(data[0]))

    def _arm(exchange, compress="none"):
        tag = ("estep_sp" if exchange == "sparse" else "estep_dn") + \
            {"none": "n", "bf16": "b", "fp8": "f"}[compress] + "_bench"
        tr = _mk(mesh, vocab, batch, exchange, compress=compress,
                 dim=dim, slots=slots, dense_dim=8, tag=tag)
        state, _, nnz = _run(tr, data, 2)
        inputs = tr.shard_inputs(list(data))
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                state, loss, _ = tr.step(state, inputs)
            float(loss)
            rates.append(steps / (time.perf_counter() - t0))
        wires = collective_wire_bytes(
            spmd_collectives(dump, f"jit_{tag}"), n_dev)
        return sorted(rates)[1], int(sum(wires.values())), nnz

    sp_sps, sp_wire, nnz = _arm("sparse")
    f8_sps, f8_wire, _ = _arm("sparse", "fp8")
    dn_sps, dn_wire, _ = _arm("dense")
    import shutil
    shutil.rmtree(dump, ignore_errors=True)
    rec = {"metric": "embed_bench", "devices": n_dev,
           "vocab": vocab, "dim": dim, "batch": batch, "slots": slots,
           "touched_rows": int(touched),
           "touched_frac": round(touched / vocab, 4),
           "steps_per_window": steps,
           "dense_steps_per_s": round(dn_sps, 2),
           "sparse_steps_per_s": round(sp_sps, 2),
           "sparse_fp8_steps_per_s": round(f8_sps, 2),
           "speedup_sparse": round(sp_sps / dn_sps, 3),
           "speedup_sparse_fp8": round(f8_sps / dn_sps, 3),
           "wire_bytes_per_step_dense": dn_wire,
           "wire_bytes_per_step_sparse": sp_wire,
           "wire_bytes_per_step_sparse_fp8": f8_wire,
           "wire_reduction": round(dn_wire / max(1, sp_wire), 1),
           "wire_source": "post_spmd_hlo"}
    rec["ok"] = bool(rec["speedup_sparse"] >= 2.0
                     and rec["touched_frac"] <= 0.05
                     and sp_wire and dn_wire and sp_wire < dn_wire)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.parallel.embedding")
    ap.add_argument("--selftest", action="store_true",
                    help="tiny-DLRM A/B vs dense exchange (ci.sh quick)")
    ap.add_argument("--hlo-check", action="store_true",
                    help="post-SPMD collective/wire-byte report")
    ap.add_argument("--bench", action="store_true",
                    help="sparse vs dense exchange steps/s + wire bytes")
    ap.add_argument("--exchange", default="sparse",
                    choices=["sparse", "dense"])
    ap.add_argument("--compress", default="none",
                    choices=["none", "bf16", "fp8"])
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args(argv)
    if args.hlo_check:
        return hlo_check(args.exchange, args.compress, args.vocab,
                         args.devices)
    if args.bench:
        return bench(devices=args.devices, steps=args.steps)
    if args.selftest:
        return selftest(args.devices)
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
