"""Cost-model-driven sharding planner over dp/zero/tp (mx.parallel.planner).

The reference exposes ONE parallelism (executor-group data parallelism)
and leaves composition to the user; this module is the TPU-native
unification the ROADMAP's N-D story builds toward: a single ``Plan``
names a mesh shape over the shared axis vocabulary (mesh.AXIS_NAMES), a
per-parameter layout, and the runtime knob settings — and a planner
picks one by MEASURED compiled cost instead of folklore:

  candidates   dp, ZeRO-1, ZeRO-2 (1-D data mesh), dpK.tpT (GSPMD
               param shardings on a data×model mesh), dpK.tpT+zero2
               (masters/opt-state sharded 1/(D·T) jointly over BOTH
               axes — the new composition this PR adds). pp appears in
               the explain listing but is never auto-selected: a
               generic Symbol carries no stage partition map
               (docs/PLANNER.md "candidate space").
  prefilter    an analytic per-device HBM lower bound per candidate is
               checked against telemetry.devstats.hbm_budget() BEFORE
               any compilation (devstats.preflight); a plan whose
               lower bound alone overflows is rejected without ever
               building an executable.
  scoring      each survivor's training step is AOT-lowered and
               compiled (never executed); XLA's own cost/memory
               analysis (devstats.extract: per-device flops, bytes,
               peak) lands on the devstats roofline peak table, and
               collective wire bytes are read out of the compiled
               module's HLO (hloaudit.collectives_in_text under ring
               accounting):

                 cost_s = max(flops/peak_flops, bytes/peak_bw)
                        + wire_bytes/wire_bw          (docs/PLANNER.md)

               wire_bw is MXNET_PLAN_WIRE_GBPS (default 25 GB/s — a
               conservative ICI figure; override per fabric). A
               compiled peak over the HBM budget rejects the plan too.
  selection    deterministic argmin over (cost_s, name); ties break
               lexicographically so two runs always agree.

``MXNET_PLAN=auto|dp|zero1|zero2|dpK.tpT[+zero2]|tpT[+zero2]`` selects
the plan (auto = run the planner); the chosen plan auto-tunes the six
runtime knobs — MXNET_ZERO_STAGE, MXNET_ZERO_BUCKET_MB,
MXNET_GRAD_COMPRESS, MXNET_DEVICE_FEED, MXNET_DEVICE_FEED_DEPTH,
MXNET_FUSED_K — each only when the user has not set it ("auto unless
set", docs/env_vars.md).

Degenerate plans (pure dp, pure zero) construct the EXACT legacy
trainers, so fp32 training under the planner is bit-identical to the
single-mode paths (tests/test_planner.py asserts this).

CLI: ``--selftest`` (determinism, pruning-before-compile, degenerate
parity, ZeRO-over-dp×tp trajectory — tools/ci.sh quick), ``--explain``
(the per-candidate score table), ``--bench`` (bench.py's `plan` lane),
``--hlo-audit`` (hloaudit's fit_step_plan subprocess body).
"""
from __future__ import annotations

import os

import numpy as _np

from ..base import MXNetError
from .mesh import build_mesh

__all__ = ["Plan", "PlanReport", "ModelSpec", "parse_plan",
           "resolve_plan", "enumerate_candidates", "tp_param_specs",
           "plan_auto", "make_trainer", "resolve_wire_bw",
           "AUTO_KNOB_VARS"]

# the six runtime knobs a chosen plan auto-tunes ("auto unless set"):
# Plan.apply_env writes each ONLY when the process env leaves it unset,
# so an explicit user setting always wins (docs/PLANNER.md knob table)
AUTO_KNOB_VARS = ("MXNET_ZERO_STAGE", "MXNET_ZERO_BUCKET_MB",
                  "MXNET_GRAD_COMPRESS", "MXNET_DEVICE_FEED",
                  "MXNET_DEVICE_FEED_DEPTH", "MXNET_FUSED_K")


def resolve_plan(value=None):
    """Plan spec string: explicit arg wins, else MXNET_PLAN, else auto."""
    if value is None:
        from .. import config
        value = config.get("MXNET_PLAN", "auto")
    spec = str(value or "auto").strip().lower()
    return spec or "auto"


def resolve_wire_bw(value=None):
    """Cross-device wire bandwidth in bytes/s for the cost model
    (MXNET_PLAN_WIRE_GBPS, default 25 GB/s)."""
    if value is None:
        from .. import config
        value = config.get("MXNET_PLAN_WIRE_GBPS", "25")
    try:
        bw = float(value) * 1e9
    except (TypeError, ValueError):
        raise MXNetError(
            f"MXNET_PLAN_WIRE_GBPS must be a number, got {value!r}")
    if bw <= 0:
        raise MXNetError(
            f"MXNET_PLAN_WIRE_GBPS must be > 0, got {value!r}")
    return bw


class Plan:
    """One point in the planner's composition space: a named mesh shape
    plus the sharding mode and knob settings that make a trainer.

    ``axes`` is an ordered {axis_name: size} over mesh.AXIS_NAMES
    ("data" first, "model" when tensor parallelism is on);
    ``zero_stage`` > 0 shards masters/optimizer state jointly over ALL
    mesh axes (parallel/zero.py); ``param_specs`` (name ->
    PartitionSpec) is the GSPMD tensor-parallel layout for stage-0
    plans. The knob fields feed apply_env().
    """

    def __init__(self, name, axes, zero_stage=0, param_specs=None,
                 compress="none", bucket_mb=None, fused_k=None,
                 feed_depth=2):
        self.name = str(name)
        self.axes = dict(axes)
        self.zero_stage = int(zero_stage)
        self.param_specs = dict(param_specs) if param_specs else None
        self.compress = compress
        self.bucket_mb = bucket_mb
        self.fused_k = fused_k
        self.feed_depth = int(feed_depth)
        if "data" not in self.axes:
            raise MXNetError(f"plan {name!r}: no data axis in {axes}")
        if self.zero_stage and self.param_specs:
            raise MXNetError(
                f"plan {name!r}: ZeRO plans shard masters jointly over "
                "the mesh and keep compute model-replicated; GSPMD "
                "param_specs only apply to stage-0 plans "
                "(docs/PLANNER.md)")

    @property
    def n_devices(self):
        n = 1
        for s in self.axes.values():
            n *= int(s)
        return n

    @property
    def model_factor(self):
        return self.n_devices // int(self.axes["data"])

    def mesh(self, devices=None):
        return build_mesh(self.axes, devices=devices)

    def knobs(self):
        """The auto-tuned knob values (docs/PLANNER.md knob table)."""
        return {
            "MXNET_ZERO_STAGE": str(self.zero_stage),
            "MXNET_ZERO_BUCKET_MB": str(self.bucket_mb
                                        if self.bucket_mb else 4),
            "MXNET_GRAD_COMPRESS": str(self.compress),
            "MXNET_DEVICE_FEED": "1",
            "MXNET_DEVICE_FEED_DEPTH": str(self.feed_depth),
            "MXNET_FUSED_K": str(self.fused_k if self.fused_k else 8),
        }

    def apply_env(self):
        """Write the knob values into os.environ — each only when the
        user has NOT set it ("auto unless set"). Returns the dict of
        vars actually written."""
        applied = {}
        for k, v in self.knobs().items():
            if os.environ.get(k) in (None, ""):
                os.environ[k] = v
                applied[k] = v
        return applied

    def to_dict(self):
        return {"name": self.name, "axes": dict(self.axes),
                "zero_stage": self.zero_stage,
                "tp_params": sorted(self.param_specs)
                if self.param_specs else [],
                "knobs": self.knobs()}

    def __repr__(self):
        return f"Plan({self.name!r}, axes={self.axes}, " \
               f"zero_stage={self.zero_stage})"


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def parse_plan(spec, n_dev, model=None):
    """Parse a non-auto MXNET_PLAN spec into a Plan.

    Grammar: ``dp`` | ``zero1`` | ``zero2`` | ``dpK.tpT`` | ``tpT``,
    optionally ``+zero1``/``+zero2`` after a tp form. K·T must equal
    the device count (K inferred when the dp factor is omitted).
    ``model`` (a ModelSpec) supplies the tp layout; required for tp
    plans.
    """
    spec = str(spec).strip().lower()
    if not spec or spec == "auto":
        raise MXNetError("parse_plan: 'auto' is resolved by plan_auto")
    stage = 0
    base = spec
    if "+" in spec:
        base, suffix = spec.split("+", 1)
        if suffix not in ("zero1", "zero2"):
            raise MXNetError(f"MXNET_PLAN: unknown suffix +{suffix} "
                             f"in {spec!r} (want +zero1|+zero2)")
        stage = int(suffix[-1])
    if base == "dp":
        if stage:
            return Plan(spec, {"data": n_dev}, zero_stage=stage)
        return Plan("dp", {"data": n_dev})
    if base in ("zero1", "zero2"):
        if stage:
            raise MXNetError(f"MXNET_PLAN: {spec!r} names zero twice")
        return Plan(base, {"data": n_dev}, zero_stage=int(base[-1]))
    # dpK.tpT / tpT
    dp_k, tp_t = None, None
    for tok in base.split("."):
        if tok.startswith("dp") and tok[2:].isdigit():
            dp_k = int(tok[2:])
        elif tok.startswith("tp") and tok[2:].isdigit():
            tp_t = int(tok[2:])
        else:
            raise MXNetError(
                f"MXNET_PLAN: cannot parse {tok!r} in {spec!r} (want "
                "auto|dp|zero1|zero2|dpK.tpT[+zero1|+zero2]|tpT[...])")
    if tp_t is None:
        raise MXNetError(f"MXNET_PLAN: no tp factor in {spec!r}")
    if dp_k is None:
        if n_dev % tp_t:
            raise MXNetError(
                f"MXNET_PLAN: tp{tp_t} does not divide {n_dev} devices")
        dp_k = n_dev // tp_t
    if dp_k * tp_t != n_dev:
        raise MXNetError(
            f"MXNET_PLAN: {spec!r} spans {dp_k * tp_t} devices but the "
            f"mesh has {n_dev}")
    name = f"dp{dp_k}.tp{tp_t}" + (f"+zero{stage}" if stage else "")
    axes = {"data": dp_k, "model": tp_t}
    if stage:
        return Plan(name, axes, zero_stage=stage)
    if model is None:
        raise MXNetError(
            f"MXNET_PLAN: {spec!r} needs a model spec for the tp "
            "layout (construct through planner.make_trainer)")
    specs, sharded, total = tp_param_specs(model.param_names,
                                           model.param_shapes, tp_t)
    if not specs:
        raise MXNetError(
            f"MXNET_PLAN: {spec!r} — no parameter dimension divides by "
            f"tp={tp_t}; pick a divisor of the layer widths")
    return Plan(name, axes, param_specs=specs)


def tp_param_specs(param_names, param_shapes, t):
    """Megatron-style layout heuristic over a generic Symbol's params.

    2-D weights alternate column-parallel / row-parallel in declaration
    order — mxnet FullyConnected stores weight as (num_hidden, in_dim)
    and computes x @ W.T, so column-parallel (shard the OUTPUT features)
    is P("model", None) and row-parallel (shard the input features) is
    P(None, "model"); a column-parallel layer's 1-D bias shards with its
    output features. Dims that t does not divide stay replicated (GSPMD
    keeps any mix correct; the alternation only minimizes resharding).
    Returns (specs dict, sharded_bytes, total_bytes).
    """
    from jax.sharding import PartitionSpec as P
    specs, col_next = {}, True
    sharded = total = 0
    bias_of = {}        # "<prefix>_bias" -> col-sharded?
    for n, s in zip(param_names, param_shapes):
        sz = 4 * max(1, int(_np.prod(s)) if s else 1)
        total += sz
        if len(s) == 2:
            if col_next and s[0] % t == 0:
                specs[n] = P("model", None)
                if n.endswith("_weight"):
                    bias_of[n[:-len("_weight")] + "_bias"] = True
                sharded += sz
                col_next = False
            elif not col_next and s[1] % t == 0:
                specs[n] = P(None, "model")
                sharded += sz
                col_next = True
        elif len(s) == 1 and bias_of.get(n) and s[0] % t == 0:
            specs[n] = P("model")
            sharded += sz
    return specs, sharded, total


class ModelSpec:
    """Everything the planner needs to size and build a trainer for one
    Symbol: inferred parameter shapes, optimizer state width, the batch
    geometry, and the trainer kwargs forwarded to construction."""

    def __init__(self, symbol, shape_kwargs, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 dtype="float32", **trainer_kwargs):
        from .dp import _OPT_OPS
        from ..ops.registry import get_op
        self.symbol = symbol
        self.shape_kwargs = dict(shape_kwargs)
        self.data_names = tuple(data_names)
        self.label_names = tuple(label_names)
        self.optimizer = optimizer
        self.dtype = dtype
        self.trainer_kwargs = dict(trainer_kwargs)
        arg_names = symbol.list_arguments()
        input_names = set(self.data_names) | set(self.label_names)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape_kwargs)
        shapes = dict(zip(arg_names, arg_shapes))
        self.param_names = [n for n in arg_names if n not in input_names]
        self.param_shapes = [tuple(shapes[n]) for n in self.param_names]
        self.aux_shapes = [tuple(s) for s in aux_shapes]
        self.input_shapes = [tuple(shapes[n]) for n in arg_names
                             if n in input_names]
        self.batch = int(shape_kwargs[self.data_names[0]][0])
        opt_op = _OPT_OPS.get(optimizer)
        if opt_op is None:
            raise MXNetError(f"planner: no fused op for {optimizer!r}")
        hp = dict(trainer_kwargs)
        opname = opt_op(hp) if callable(opt_op) else opt_op
        self.n_states = len(get_op(opname).input_names) - 2
        self.param_elems = sum(max(1, int(_np.prod(s)) if s else 1)
                               for s in self.param_shapes)
        self.param_bytes = 4 * self.param_elems

    def compute_itemsize(self):
        return 2 if self.dtype in ("bfloat16", "float16") else 4


# -- analytic estimates (prefilter + the audit's wire cross-check) -----------

def estimate_hbm_bytes(model, plan):
    """Analytic per-device HBM LOWER BOUND of one training step under
    `plan` — masters + optimizer state at their sharded residency, one
    compute-dtype param copy + one gradient (the live set at the
    backward/update boundary), and the local batch. Deliberately a
    lower bound (no activation model for a generic Symbol): a plan
    rejected on it alone can never fit, while survivors still face the
    compiled-peak check (docs/PLANNER.md "HBM prefilter")."""
    pb = model.param_bytes
    ci = model.compute_itemsize()
    n = plan.n_devices
    t = plan.model_factor
    if plan.zero_stage > 0:
        master_opt = pb * (1 + model.n_states) / n
    elif plan.param_specs:
        # tp: listed params shard 1/T, the rest replicate
        _, sharded, total = tp_param_specs(model.param_names,
                                           model.param_shapes, t)
        shard_b = sharded / t + (total - sharded)
        master_opt = shard_b * (1 + model.n_states)
    else:
        master_opt = pb * (1 + model.n_states)
    # one gathered/cast compute copy + one gradient, at compute width
    live = 2 * pb * ci / 4
    if plan.param_specs:
        live /= t
    batch_local = 0
    for s in model.input_shapes:
        elems = max(1, int(_np.prod(s)) if s else 1)
        batch_local += 4 * elems / int(plan.axes["data"])
    return int(master_opt + live + batch_local)


def estimate_wire_bytes(model, plan, bucket_bytes=None):
    """Analytic per-device collective wire bytes of one step — the
    number the fit_step_plan audit holds the compiled HLO to within
    10%. ZeRO plans reuse ZeroLayout's ring accounting (gather +
    reduce over the JOINT axis ring); stage-0 dp is one all-reduce of
    the full gradient. Stage-0 tp has no closed form for a generic
    Symbol (activation collectives depend on the layer graph) — None
    means "score from the compiled HLO only"."""
    ci = model.compute_itemsize()
    n = plan.n_devices
    if plan.zero_stage > 0:
        from .zero import ZeroLayout, _resolve_bucket_bytes
        bb = bucket_bytes if bucket_bytes is not None \
            else _resolve_bucket_bytes(plan.bucket_mb)
        lay = ZeroLayout(model.param_shapes, n, bb)
        return lay.wire_bytes_per_step(plan.zero_stage, ci, ci)
    if plan.param_specs:
        return None
    return int(2 * (n - 1) / n * model.param_bytes * ci / 4)


# -- candidate space ---------------------------------------------------------

def enumerate_candidates(model, n_dev, max_tp=8):
    """The planner's candidate compositions for one model at one device
    count: [(plan_or_None, reject_reason_or_None)]. Deterministic
    order. pp rides along as an explained rejection — a generic Symbol
    has no stage partition map, so the planner never selects it."""
    out = [(Plan("dp", {"data": n_dev}), None)]
    if n_dev > 1:
        out.append((Plan("zero1", {"data": n_dev}, zero_stage=1), None))
        out.append((Plan("zero2", {"data": n_dev}, zero_stage=2), None))
    for t in _divisors(n_dev):
        if t == 1 or t == n_dev or t > max_tp:
            continue
        k = n_dev // t
        specs, sharded, total = tp_param_specs(model.param_names,
                                               model.param_shapes, t)
        if not specs:
            out.append((None, (f"dp{k}.tp{t}: no parameter dimension "
                               f"divides by tp={t}")))
            continue
        out.append((Plan(f"dp{k}.tp{t}", {"data": k, "model": t},
                         param_specs=specs), None))
        out.append((Plan(f"dp{k}.tp{t}+zero2", {"data": k, "model": t},
                         zero_stage=2), None))
    if n_dev > 1:
        out.append((None, f"pp{n_dev}: generic Symbol has no stage "
                          "partition map (use parallel.pp directly)"))
    return out


# -- trainer construction ----------------------------------------------------

def _auto_bucket_mb(model):
    """Bucket threshold targeting ~4 gradient buckets, clamped to
    [1, 32] MB (docs/PLANNER.md knob table)."""
    mb = model.param_bytes / (1 << 20)
    return max(1, min(32, int(round(mb / 4)) or 1))


def _auto_fused_k(model):
    """Small-step models amortize dispatch deeper: K=16 under 8 MB of
    params, the dp default K=8 above."""
    return 16 if model.param_bytes < (8 << 20) else 8


def _finalize_knobs(plan, model):
    if plan.bucket_mb is None:
        plan.bucket_mb = _auto_bucket_mb(model)
    if plan.fused_k is None:
        plan.fused_k = _auto_fused_k(model)
    return plan


def build_trainer(model, plan, devices=None):
    """Construct the trainer a Plan describes. Degenerate plans call
    the EXACT legacy constructors (bitwise parity with the single-mode
    paths); tp plans hand dp the GSPMD param_specs; any zero_stage>0
    plan builds a ZeroTrainer over the plan's (possibly N-D) mesh."""
    from .dp import DataParallelTrainer
    from .zero import ZeroTrainer
    _finalize_knobs(plan, model)
    mesh = plan.mesh(devices)
    kw = dict(model.trainer_kwargs, optimizer=model.optimizer,
              dtype=model.dtype, data_names=model.data_names,
              label_names=model.label_names)
    if plan.zero_stage > 0:
        tr = ZeroTrainer(model.symbol, mesh, zero_stage=plan.zero_stage,
                         grad_compress=plan.compress,
                         zero_bucket_mb=plan.bucket_mb, **kw)
    else:
        tr = DataParallelTrainer(model.symbol, mesh, zero_stage=0,
                                 param_specs=plan.param_specs, **kw)
    tr._plan = plan
    return tr


# -- AOT scoring -------------------------------------------------------------

def _abstract_args(model, tr):
    """ShapeDtypeStructs for one single-step dispatch of `tr` — metadata
    only, so scoring never allocates training state."""
    import jax
    import jax.numpy as jnp
    from .. import random as _random
    f32 = jnp.float32
    sds = jax.ShapeDtypeStruct
    key = _random.next_key()
    rng = sds(key.shape, key.dtype)
    scalar = sds((), f32)
    inputs = tuple(sds(s, f32) for s in model.input_shapes)
    aux = tuple(sds(s, f32) for s in model.aux_shapes)
    from .zero import ZeroTrainer
    if isinstance(tr, ZeroTrainer):
        L = tr._ensure_layout(model.param_shapes)
        masters = tuple(sds((L.padded[b],), f32)
                        for b in range(L.n_buckets))
        states = tuple(tuple(sds((L.padded[b],), f32)
                             for _ in range(tr._n_states))
                       for b in range(L.n_buckets))
        resid = () if tr._wire_dtype is None else tuple(
            sds((tr._n_dev, L.padded[b]), f32)
            for b in range(L.n_buckets))
        tr._build_zero_step()
        return tr._zstep, (masters, states, resid, aux, inputs, rng,
                           scalar, scalar)
    params = tuple(sds(s, f32) for s in model.param_shapes)
    states = tuple(tuple(sds(s, f32) for _ in range(tr._n_states))
                   for s in model.param_shapes)
    return tr._step, (params, states, aux, inputs, rng, scalar, scalar)


def score_plan(model, plan, devices=None, wire_bw=None):
    """AOT-compile one candidate's step and price it: returns the
    record dict (never executes the step). The compiled peak is
    re-checked against the HBM budget here — the prefilter is a lower
    bound, this is XLA's own number."""
    from ..telemetry import devstats
    from ..analysis.hloaudit import (collectives_in_text,
                                     collective_wire_bytes)
    wire_bw = wire_bw or resolve_wire_bw()
    tr = build_trainer(model, plan, devices)
    fn, args = _abstract_args(model, tr)
    compiled = fn.lower(*args).compile()
    stats = devstats.extract(compiled)
    colls = collectives_in_text(compiled.as_text())
    wires = collective_wire_bytes(colls, plan.n_devices)
    wire = float(sum(wires.values()))
    pf, pb, _ = devstats.peaks()
    cost = max(stats["flops"] / pf, stats["bytes_accessed"] / pb) \
        + wire / wire_bw
    est = estimate_wire_bytes(model, plan,
                              bucket_bytes=getattr(tr, "_bucket_bytes",
                                                   None))
    return {"plan": plan, "trainer": tr, "compiled": compiled,
            "flops": stats["flops"], "bytes": stats["bytes_accessed"],
            "peak_bytes": stats["peak_bytes"],
            "wire_bytes_hlo": int(wire),
            "wire_bytes_estimate": est,
            "collectives": {k: len(v) for k, v in colls.items()},
            "cost_s": cost}


class PlanReport:
    """The planner's full decision record: the chosen Plan plus one
    entry per candidate — scored (cost_s ...), rejected_hbm (the
    prefilter said it cannot fit; never compiled), rejected_peak (XLA's
    compiled peak overflowed), or unsupported (no layout). `compiled`
    counts executables actually built — the pruning test pins it."""

    def __init__(self, chosen, entries, compiled, budget):
        self.chosen = chosen
        self.entries = entries
        self.compiled = compiled
        self.budget = budget

    def to_dict(self):
        return {"chosen": self.chosen.name if self.chosen else None,
                "budget_bytes": self.budget,
                "compiled": self.compiled,
                "candidates": [
                    {k: v for k, v in e.items()
                     if k not in ("plan", "trainer", "compiled")}
                    | {"name": e["plan"].name if e.get("plan") else
                       e.get("name")}
                    for e in self.entries]}


def plan_auto(model, n_dev=None, devices=None, budget=None,
              wire_bw=None, max_tp=8):
    """Enumerate → prefilter → compile+score → argmin. Returns a
    PlanReport whose `chosen` plan minimizes (cost_s, name); raises
    MXNetError when every candidate is rejected."""
    import jax
    from ..telemetry import devstats
    if devices is None and n_dev is not None:
        devices = jax.devices()[:n_dev]
    if devices is not None:
        n_dev = len(devices)
    if n_dev is None:
        n_dev = len(jax.devices())
    if budget is None:
        budget = devstats.hbm_budget()
    entries, compiled_n = [], 0
    for plan, reason in enumerate_candidates(model, n_dev, max_tp):
        if plan is None:
            entries.append({"name": reason.split(":")[0],
                            "status": "unsupported", "reason": reason})
            continue
        _finalize_knobs(plan, model)
        need = estimate_hbm_bytes(model, plan)
        try:
            devstats.preflight(plan.name, need, budget=budget,
                               what="plan")
        except devstats.HBMPreflightError as e:
            entries.append({"plan": plan, "status": "rejected_hbm",
                            "need_bytes": need, "reason": str(e)})
            continue
        rec = score_plan(model, plan, devices, wire_bw)
        compiled_n += 1
        if budget is not None and rec["peak_bytes"] > budget:
            rec |= {"status": "rejected_peak",
                    "reason": f"compiled peak {rec['peak_bytes']} over "
                              f"budget {budget}"}
        else:
            rec["status"] = "scored"
        entries.append(rec)
    scored = [e for e in entries if e.get("status") == "scored"]
    if not scored:
        # carry the full record out on the error so callers (and the
        # pruning test) can see that nothing was compiled
        err = MXNetError(
            "planner: no feasible plan — every candidate was rejected "
            f"({[e.get('reason') for e in entries]})")
        err.report = PlanReport(None, entries, compiled_n, budget)
        raise err
    best = min(scored, key=lambda e: (e["cost_s"], e["plan"].name))
    best["status"] = "selected"
    return PlanReport(best["plan"], entries, compiled_n, budget)


def make_trainer(symbol, shape_kwargs, plan=None, devices=None,
                 n_dev=None, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 dtype="float32", apply_knobs=True, budget=None,
                 **trainer_kwargs):
    """The MXNET_PLAN front door: build the trainer the (possibly
    auto-)selected plan describes. `plan` overrides the env spec;
    "auto" runs the planner. The chosen plan's knob values land in the
    environment ("auto unless set") unless apply_knobs=False. The
    trainer carries `_plan` (and `_plan_report` under auto)."""
    import jax
    model = ModelSpec(symbol, shape_kwargs, data_names=data_names,
                      label_names=label_names, optimizer=optimizer,
                      dtype=dtype, **trainer_kwargs)
    if devices is None and n_dev is not None:
        devices = jax.devices()[:n_dev]
    n = len(devices) if devices is not None else len(jax.devices())
    spec = resolve_plan(plan)
    report = None
    if spec == "auto":
        report = plan_auto(model, n_dev=n, devices=devices,
                           budget=budget)
        chosen = report.chosen
        # the scoring trainer is the real trainer — reuse it, its jit
        # cache already holds the compiled step
        tr = next(e["trainer"] for e in report.entries
                  if e.get("status") == "selected")
    else:
        chosen = parse_plan(spec, n, model)
        tr = build_trainer(model, chosen, devices)
    if apply_knobs:
        chosen.apply_env()
    tr._plan_report = report
    return tr


# ============================================================================
# CLI: --selftest / --explain / --bench / --hlo-audit
# ============================================================================

def _bench_sym(dim=256, hidden=2048, nclass=16):
    """The transformer-scale bench arm: wide FC stack whose parameter
    gather/reduce wire dwarfs the tiny per-device batch compute."""
    from .zero import _wide_sym
    return _wide_sym(dim=dim, hidden=hidden, nclass=nclass)


def _small_model(batch=16, dim=32, hidden=64, nclass=8,
                 optimizer="sgd"):
    from .zero import _wide_sym
    sym = _wide_sym(dim=dim, hidden=hidden, nclass=nclass)
    kw = {"learning_rate": 0.1, "rescale_grad": 1.0 / batch}
    if optimizer == "sgd":
        kw["momentum"] = 0.9
    return ModelSpec(sym, {"data": (batch, dim),
                           "softmax_label": (batch,)},
                     optimizer=optimizer, **kw), batch, dim, nclass


def selftest(devices=8):
    """tools/ci.sh quick body — one planner_selftest JSON line:

      1. determinism: two plan_auto runs agree on the choice AND the
         full (name, cost) candidate ordering;
      2. pruning: a 1 MB budget rejects every candidate BEFORE any
         executable is built (report.compiled == 0 via the raised
         no-feasible-plan error's report-free path — asserted with a
         probe run at a budget only dp fits);
      3. degenerate construction: plan="dp" is a plain
         DataParallelTrainer, plan="zero2" a stage-2 ZeroTrainer;
      4. ZeRO over dp×tp: dpK.tp2+zero2 trains the selftest model with
         an fp32 loss trajectory within 8 ULP of pure dp after 10
         steps, and its masters shard 1/(D·T).
    """
    import json
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax
    n_dev = min(devices, len(jax.devices()))
    model, batch, dim, nclass = _small_model()
    results = {"metric": "planner_selftest", "devices": n_dev}

    # 1) determinism
    r1 = plan_auto(model, n_dev=n_dev, budget=None)
    r2 = plan_auto(model, n_dev=n_dev, budget=None)
    key = lambda r: [(e["plan"].name, round(e["cost_s"], 15))
                     for e in r.entries if "cost_s" in e]
    results["auto_choice"] = r1.chosen.name
    results["deterministic"] = bool(r1.chosen.name == r2.chosen.name
                                    and key(r1) == key(r2))
    results["candidates_scored"] = r1.compiled

    # 2) pruning before compile: 16 KB is below every candidate's
    # analytic lower bound, so all reject in the prefilter and the
    # report must show ZERO executables built
    try:
        plan_auto(model, n_dev=n_dev, budget=1 << 14)
        results["pruned_all"] = False
        results["pruned_compiles"] = -1
    except MXNetError as e:
        rep = getattr(e, "report", None)
        results["pruned_all"] = bool(rep is not None and all(
            x.get("status") == "rejected_hbm"
            for x in rep.entries if x.get("plan") is not None))
        results["pruned_compiles"] = rep.compiled if rep else -1

    # 3) degenerate plans construct the exact legacy trainers
    from .dp import DataParallelTrainer
    from .zero import ZeroTrainer
    tr_dp = make_trainer(model.symbol, model.shape_kwargs, plan="dp",
                         n_dev=n_dev, apply_knobs=False,
                         optimizer=model.optimizer,
                         **model.trainer_kwargs)
    tr_z2 = make_trainer(model.symbol, model.shape_kwargs, plan="zero2",
                         n_dev=n_dev, apply_knobs=False,
                         optimizer=model.optimizer,
                         **model.trainer_kwargs)
    results["degenerate_dp"] = bool(
        type(tr_dp) is DataParallelTrainer)
    results["degenerate_zero2"] = bool(
        isinstance(tr_z2, ZeroTrainer) and tr_z2._zero_stage == 2)

    # 4) ZeRO over dp×tp vs pure dp (fp32, 10 steps)
    rng = _np.random.RandomState(0)
    x = rng.normal(size=(batch, dim)).astype(_np.float32)
    y = rng.randint(0, nclass, size=(batch,)).astype(_np.float32)

    def _train(tr, steps=10):
        params, states, aux = tr.init_state(model.shape_kwargs)
        inputs = tr.shard_inputs([x, y])
        losses = []
        for _ in range(steps):
            params, states, aux, loss, _ = tr.step(params, states, aux,
                                                   inputs)
            losses.append(float(loss))
        return tr.host_params(params) if hasattr(tr, "host_params") \
            else {n: _np.asarray(p)
                  for n, p in zip(tr.param_names, params)}, losses

    t = 2 if n_dev % 2 == 0 and n_dev > 2 else 1
    if t > 1:
        tr_tz = make_trainer(model.symbol, model.shape_kwargs,
                             plan=f"dp{n_dev // t}.tp{t}+zero2",
                             n_dev=n_dev, apply_knobs=False,
                             optimizer=model.optimizer,
                             **model.trainer_kwargs)
        h_dp, l_dp = _train(tr_dp)
        h_tz, l_tz = _train(tr_tz)
        ulp = max(float(_np.abs(h_dp[n] - h_tz[n]).max())
                  / (float(_np.abs(h_dp[n]).max()) * 2.0 ** -23 + 1e-30)
                  for n in h_dp)
        results["zero_tp_param_ulp"] = round(ulp, 3)
        results["zero_tp_close"] = bool(ulp <= 8.0)
        results["zero_tp_loss_close"] = bool(all(
            abs(a - b) <= 8 * 2.0 ** -23 * max(abs(a), 1.0)
            for a, b in zip(l_dp, l_tz)))
        results["zero_tp_model_factor"] = tr_tz._model_factor
    else:
        results["zero_tp_close"] = True
        results["zero_tp_loss_close"] = True

    ok = (results["deterministic"] and results["pruned_all"]
          and results["pruned_compiles"] == 0
          and results["degenerate_dp"] and results["degenerate_zero2"]
          and results["zero_tp_close"]
          and results["zero_tp_loss_close"])
    results["ok"] = bool(ok)
    print(json.dumps(results), flush=True)
    return 0 if ok else 1


def explain(plan_spec="auto", devices=8):
    """Print the per-candidate score table (the --explain CLI) plus one
    planner_explain JSON line."""
    import json
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax
    n_dev = min(devices, len(jax.devices()))
    model, _, _, _ = _small_model(batch=32, dim=64, hidden=256,
                                  nclass=16, optimizer="adam")
    report = plan_auto(model, n_dev=n_dev)
    rows = []
    for e in report.entries:
        name = e["plan"].name if e.get("plan") else e["name"]
        if "cost_s" in e:
            rows.append((name, e["status"], e["cost_s"],
                         e["flops"], e["wire_bytes_hlo"],
                         e["peak_bytes"]))
            print(f"{name:>16}  {e['status']:>13}  "
                  f"cost={e['cost_s'] * 1e3:8.3f}ms  "
                  f"flops={e['flops'] / 1e6:8.1f}M  "
                  f"wire={e['wire_bytes_hlo'] / 1e6:7.2f}MB  "
                  f"peak={e['peak_bytes'] / 1e6:7.1f}MB")
        else:
            rows.append((name, e["status"], None, None, None, None))
            print(f"{name:>16}  {e['status']:>13}  {e['reason']}")
    print(f"{'-' * 72}\nselected: {report.chosen.name}  "
          f"knobs: {report.chosen.knobs()}")
    rec = {"metric": "planner_explain", "devices": n_dev}
    rec.update(report.to_dict())
    print(json.dumps(rec), flush=True)
    return 0


def bench(devices=8, steps=8):
    """bench.py's `plan` lane body: MXNET_PLAN=auto vs hand-picked dp
    and zero2 on the transformer-scale arm (wide FC stack, small batch,
    adam — parameter gather/reduce wire and de-replicated update work
    dominate). Reports measured steps/s per arm, the planner's decision
    and its predicted cost ranking; one plan_bench JSON line."""
    import json
    import time
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax
    n_dev = min(devices, len(jax.devices()))
    batch, dim, nclass, hidden = 16, 256, 16, 1024
    sym = _bench_sym(dim=dim, hidden=hidden, nclass=nclass)
    shape_kwargs = {"data": (batch, dim), "softmax_label": (batch,)}
    kw = dict(optimizer="adam", learning_rate=1e-3,
              rescale_grad=1.0 / batch)
    model = ModelSpec(sym, shape_kwargs, **kw)
    rng = _np.random.RandomState(0)
    x = rng.normal(size=(batch, dim)).astype(_np.float32)
    y = rng.randint(0, nclass, size=(batch,)).astype(_np.float32)

    report = plan_auto(model, n_dev=n_dev)
    predicted = sorted(
        ((e["plan"].name, e["cost_s"]) for e in report.entries
         if "cost_s" in e), key=lambda kv: (kv[1], kv[0]))

    def _measure(plan_spec):
        tr = make_trainer(sym, shape_kwargs, plan=plan_spec,
                          n_dev=n_dev, apply_knobs=False, **kw)
        params, states, aux = tr.init_state(shape_kwargs)
        inputs = tr.shard_inputs([x, y])
        for _ in range(2):
            params, states, aux, loss, _ = tr.step(params, states, aux,
                                                   inputs)
        float(loss)
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                params, states, aux, loss, _ = tr.step(params, states,
                                                       aux, inputs)
            float(loss)
            rates.append(steps / (time.perf_counter() - t0))
        return sorted(rates)[1]

    arms = {"dp": _measure("dp"), "zero2": _measure("zero2"),
            "auto": _measure(report.chosen.name)}
    measured = sorted(arms.items(), key=lambda kv: (-kv[1], kv[0]))
    best_hand = max(arms["dp"], arms["zero2"])
    rec = {"metric": "plan_bench", "devices": n_dev,
           "params": int(model.param_elems), "optimizer": "adam",
           "batch": batch, "steps_per_window": steps,
           "auto_choice": report.chosen.name,
           "predicted_rank": [n for n, _ in predicted],
           "predicted_cost_s": {n: round(c, 6) for n, c in predicted},
           "dp_steps_per_s": round(arms["dp"], 2),
           "zero2_steps_per_s": round(arms["zero2"], 2),
           "auto_steps_per_s": round(arms["auto"], 2),
           "measured_rank": [n for n, _ in measured],
           "auto_beats_hand": bool(arms["auto"] >= 0.95 * best_hand),
           "speedup_vs_dp": round(arms["auto"] / arms["dp"], 3)}
    print(json.dumps(rec), flush=True)
    return 0


def hlo_audit(devices=8):
    """hloaudit's fit_step_plan subprocess body: compile the planner's
    dp×tp+ZeRO-2 composition on an 8-device virtual mesh and report the
    invariants — reduce-scatter + all-gather present, no gradient-sized
    all-reduce, full donation, HLO wire bytes within 10% of the
    planner's analytic estimate. One planner_hlo_audit JSON line."""
    import json
    from mxnet_tpu.amp.__main__ import _pin_cpu
    _pin_cpu(devices)
    import jax
    from ..telemetry import devstats
    from ..analysis.hloaudit import (collectives_in_text,
                                     collective_wire_bytes,
                                     donated_param_indices,
                                     collective_pairing_ok, has_f64,
                                     convert_count, allreduce_counts)
    n_dev = min(devices, len(jax.devices()))
    t = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    model, batch, dim, nclass = _small_model(batch=16, dim=64,
                                             hidden=256, nclass=16)
    plan = parse_plan(f"dp{n_dev // t}.tp{t}+zero2", n_dev, model)
    tr = build_trainer(model, plan)
    fn, args = _abstract_args(model, tr)
    compiled = fn.lower(*args).compile()
    hlo = compiled.as_text()
    colls = collectives_in_text(hlo)
    wires = collective_wire_bytes(colls, n_dev)
    # scalar all-reduces (loss/finite) ride every plan; gradient-SIZED
    # ones mean the joint reduce-scatter regressed to dp
    grad_ars = [c for c in colls["all-reduce"] if c[1]]
    wire_hlo = sum(wires.values())
    est = estimate_wire_bytes(model, plan,
                              bucket_bytes=tr._bucket_bytes)
    donated = donated_param_indices(hlo)
    L = tr._layout
    expected = L.n_buckets * (1 + tr._n_states)   # masters + opt shards
    within = bool(est and abs(wire_hlo - est) <= 0.10 * est)
    n_sync, n_async = allreduce_counts(hlo)
    rec = {"metric": "planner_hlo_audit", "devices": n_dev,
           "plan": plan.name, "buckets": L.n_buckets,
           "allreduce_sync": n_sync, "allreduce_async": n_async,
           "reduce_scatter": len(colls["reduce-scatter"]),
           "all_gather": len(colls["all-gather"]),
           "grad_allreduce_nonscalar": len(grad_ars),
           "wire_bytes_hlo": int(wire_hlo),
           "wire_bytes_estimate": int(est),
           "wire_within_10pct": within,
           "donated": sorted(donated), "donate_expected": expected,
           "pairing_ok": collective_pairing_ok(hlo),
           "has_f64": has_f64(hlo),
           "convert_count": convert_count(hlo),
           "recompiles": 1,
           "cost": {k: devstats.extract(compiled)[k]
                    for k in ("flops", "bytes_accessed",
                              "argument_bytes", "peak_bytes")}}
    rec["ok"] = bool(rec["reduce_scatter"] and rec["all_gather"]
                     and not grad_ars and within
                     and len(donated) >= expected)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.parallel.planner")
    ap.add_argument("--selftest", action="store_true",
                    help="determinism/pruning/parity (ci.sh quick)")
    ap.add_argument("--explain", action="store_true",
                    help="per-candidate score table for the auto plan")
    ap.add_argument("--bench", action="store_true",
                    help="auto vs hand dp/zero2 steps/s (bench.py)")
    ap.add_argument("--hlo-audit", action="store_true",
                    help="fit_step_plan subprocess body (hloaudit)")
    ap.add_argument("--plan", default="auto")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args(argv)
    if args.hlo_audit:
        return hlo_audit(args.devices)
    if args.bench:
        return bench(devices=args.devices, steps=args.steps)
    if args.explain:
        return explain(args.plan, args.devices)
    if args.selftest:
        return selftest(args.devices)
    ap.print_help()
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
