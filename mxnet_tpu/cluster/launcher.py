"""Multi-process launcher/supervisor — the testable pod.

Spawns N real Python processes joined into one `jax.distributed` job
over localhost TCP (coordinator port auto-picked, the DMLC_* env
contract tools/launch.py already exports) and SUPERVISES them: per-rank
log streaming with `[rN]` prefixes, a wall-clock deadline that reaps the
whole tree, and a failure grace window — when any rank dies, survivors
get `failure_grace_s` to detect it themselves (dist.py's timeout
barriers turn the silence into a named `DistRankFailure`) before the
supervisor SIGKILLs whatever is left, stopped ranks included.

Each rank is pinned to its own virtual CPU device set
(`JAX_NUM_CPU_DEVICES` + `--xla_force_host_platform_device_count`, the
PR 8 elastic-selftest idiom) and gets the Gloo cross-process CPU
collectives backend (`JAX_CPU_COLLECTIVES_IMPLEMENTATION=gloo`) —
without it the CPU backend refuses multi-process computations, which is
why the three seed-era `tests/test_dist_*` suites never ran their
multi-rank path.

Every rank also keeps a flight-recorder black box (telemetry.flightrec)
flushed to `blackbox_dir`; after a failed run the launcher collects the
per-rank `flightrec-rank-K.json` files and prints the interleaved
last-N-seconds timeline, naming which rank went quiet first (a SIGKILLed
or hung rank's box stops updating while the survivors keep recording
their barrier waits — earliest last-event timestamp fingers the victim).

Multi-host: a host spec (`MXNET_CLUSTER_HOSTS=host1:4,host2:4`, a
hostfile, or `hosts=[(host, slots), ...]`) assigns ranks to hosts in
order; non-local ranks run over ssh (`SshTransport` — BatchMode, the
DMLC_/MXNET_/JAX_/XLA_ env contract shipped inside the remote command
line), local ones exactly as before. Rank 0's host becomes the
coordinator URI every rank dials. Localhost stays the default and the
test path; the ssh plane is unit-tested against a mocked transport.

Concurrency surfaces (analysis/locklint contract): each rank's log pump
is one daemon thread appending to that rank's own deque (GIL-atomic
appends, single writer) and to the shared stream under `_stream_lock`;
the supervisor loop only ever reads. No other cross-thread state.
"""
from __future__ import annotations

import collections
import glob
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

__all__ = ["ClusterLauncher", "ClusterResult", "RankProc", "free_port",
           "cpu_collectives_available", "parse_host_spec",
           "read_hostfile", "LocalTransport", "SshTransport"]

# analysis/locklint: RankProc.tail is a deque with exactly one writer
# (that rank's pump thread; appends are GIL-atomic) and read-only after
# the pump joins; ClusterResult fields are written before the result is
# published. Declared lock-free by design.
__analysis_thread_safe__ = {"RankProc.tail", "RankProc.exit_rc",
                            "RankProc.exit_t"}


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_host_spec(spec):
    """Parse `host1:4,host2:4` (or bare `host1,host2` — one slot each)
    into an ordered [(host, slots), ...]. Ranks fill hosts in order:
    host1 gets ranks 0..3, host2 gets 4..7."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, slots = part.rpartition(":")
        if sep and slots.isdigit():
            out.append((host.strip(), int(slots)))
        else:
            out.append((part, 1))
    for host, slots in out:
        if not host or slots < 1:
            raise ValueError(f"bad host spec entry {host!r}:{slots}")
    if not out:
        raise ValueError(f"empty host spec {spec!r}")
    return out


def read_hostfile(path):
    """Parse an MPI-style hostfile into [(host, slots), ...]. Accepted
    line forms: `host`, `host:4`, `host slots=4`; `#` comments and
    blank lines are skipped."""
    out = []
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            host, slots = fields[0], 1
            for tok in fields[1:]:
                if tok.startswith("slots="):
                    slots = int(tok[len("slots="):])
            if ":" in host:
                head, _, tail = host.rpartition(":")
                if tail.isdigit():
                    host, slots = head, int(tail)
            if not host or slots < 1:
                raise ValueError(f"bad hostfile line {raw.strip()!r}")
            out.append((host, slots))
    if not out:
        raise ValueError(f"hostfile {path} names no hosts")
    return out


def _is_local_host(host):
    if host in ("localhost", "127.0.0.1", "::1", ""):
        return True
    name = socket.gethostname()
    return host in (name, name.split(".")[0])


# env the ssh transport ships to the remote rank (everything the DMLC
# contract, the framework knobs, and the jax runtime pin live under)
_ENV_FORWARD_PREFIXES = ("DMLC_", "MXNET_", "MXIO_", "JAX_", "XLA_")
_ENV_FORWARD_EXACT = ("PYTHONPATH",)


class LocalTransport:
    """Plain Popen on this host — the default and the test path."""

    def popen(self, host, argv, env, **popen_kw):
        return subprocess.Popen(list(argv), env=env, **popen_kw)


class SshTransport:
    """Run a rank on a remote host over ssh, the tools/launch.py way:
    the contract env rides inside the remote command line (`env K=V ...
    argv`), shell-quoted, so no remote config is needed beyond
    passwordless ssh + the same repo checkout/venv path. The local ssh
    client process is what the launcher supervises; killing it drops
    the connection (and with it the remote process's stdin/stdout —
    best-effort remote teardown, same as the reference's ssh
    launcher)."""

    def __init__(self, ssh_args=()):
        self.ssh_args = list(ssh_args)

    def command(self, host, argv, env):
        fwd = {k: v for k, v in env.items()
               if k.startswith(_ENV_FORWARD_PREFIXES)
               or k in _ENV_FORWARD_EXACT}
        remote = " ".join(
            ["env"]
            + [f"{k}={shlex.quote(v)}" for k, v in sorted(fwd.items())]
            + [shlex.quote(a) for a in argv])
        return ["ssh", "-o", "BatchMode=yes",
                "-o", "StrictHostKeyChecking=accept-new",
                *self.ssh_args, host, remote]

    def popen(self, host, argv, env, **popen_kw):
        # the remote env travels inside the command; the local ssh
        # client just runs under the caller's environment
        return subprocess.Popen(self.command(host, argv, env),
                                env=dict(os.environ), **popen_kw)


def cpu_collectives_available():
    """True when this jaxlib can run cross-process collectives on the
    CPU backend (the Gloo TCP transport is compiled in). The dist tests
    skip-with-reason instead of failing when it is absent."""
    try:
        from jax._src.lib import xla_client
        return hasattr(xla_client._xla, "make_gloo_tcp_collectives")
    except Exception:
        return False


class RankProc:
    """One supervised rank: the Popen handle, its log tail, exit record."""

    def __init__(self, rank, proc, tail_lines):
        self.rank = rank
        self.proc = proc
        self.tail = collections.deque(maxlen=tail_lines)
        self.exit_rc = None         # set once by the supervisor loop
        self.exit_t = None
        self.reaped = False

    def log_text(self):
        return "".join(self.tail)


class ClusterResult:
    """What one launch() observed. `ok` iff every rank exited 0 with no
    reaping and no deadline; timing fields feed the bench lane."""

    def __init__(self, ranks, elapsed_s, deadline_fired, first_death_t,
                 t0, blackboxes=None, blackbox_dir=None):
        self.returncodes = [rp.exit_rc for rp in ranks]
        self.elapsed_s = elapsed_s
        self.deadline_fired = deadline_fired
        self.reaped_ranks = [rp.rank for rp in ranks if rp.reaped]
        self.failed_ranks = [rp.rank for rp in ranks
                             if rp.exit_rc not in (0, None)]
        # seconds-from-launch timeline (None when no rank died)
        self.first_death_s = (None if first_death_t is None
                              else first_death_t - t0)
        self.exit_s = [None if rp.exit_t is None else rp.exit_t - t0
                       for rp in ranks]
        self.tails = {rp.rank: rp.log_text() for rp in ranks}
        # per-rank flight-recorder black boxes (rank -> parsed dump)
        self.blackbox_dir = blackbox_dir
        self.blackboxes = dict(blackboxes or {})
        self.quiet_rank = self._quiet_rank()

    def _quiet_rank(self):
        """The rank whose black box stopped updating first — on a
        kill/hang injection that is the victim (survivors keep flushing
        while they wait out the dist timeout). Needs >= 2 boxes with
        events to be meaningful. Ties on `last_event_t` (coarse flush
        clocks) break toward the lowest last sequence number (`total`,
        the count of events ever recorded — the rank that logged least
        before the silence), then the lowest rank for determinism."""
        last = {r: (b.get("last_event_t"), b.get("total", 0))
                for r, b in self.blackboxes.items()
                if b.get("last_event_t")}
        if len(last) < 2:
            return None
        return min(last, key=lambda r: (last[r][0], last[r][1], r))

    @property
    def ok(self):
        return (not self.deadline_fired and not self.reaped_ranks
                and all(rc == 0 for rc in self.returncodes))

    def describe(self):
        quiet = "" if self.quiet_rank is None \
            else f" quiet_rank={self.quiet_rank}"
        return (f"rcs={self.returncodes} reaped={self.reaped_ranks} "
                f"deadline_fired={self.deadline_fired} "
                f"elapsed={self.elapsed_s:.1f}s{quiet}")

    def triage(self, last_s=20.0, max_events=120):
        """The postmortem: every rank's flight-recorder events from the
        last `last_s` seconds, interleaved on the shared wall clock,
        headed by which rank went quiet first. Timestamps are printed
        as seconds-before-the-end (-0.00s is the newest event in the
        pod) so the silence gap is visible at a glance."""
        if not self.blackboxes:
            return "cluster triage: no flight-recorder black boxes " \
                   "were collected\n"
        last = {r: b.get("last_event_t") or 0.0
                for r, b in self.blackboxes.items()}
        t_end = max(last.values())
        lines = ["cluster triage: flight-recorder timeline "
                 f"(last {last_s:.0f}s, {len(self.blackboxes)} black "
                 "box(es))"]
        if self.quiet_rank is not None:
            q = self.quiet_rank
            lines.append(
                f"cluster triage: rank {q} went quiet FIRST — its last "
                f"event is {t_end - last[q]:.2f}s older than the pod's "
                "newest")
        for r in sorted(self.blackboxes):
            box = self.blackboxes[r]
            lines.append(
                f"  r{r}: {len(box.get('events', []))} event(s) "
                f"buffered, {box.get('dropped', 0)} dropped, reason="
                f"{box.get('reason', '?')!r}, last event "
                f"{t_end - last[r]:.2f}s before end")
        merged = []
        for r, box in self.blackboxes.items():
            for e in box.get("events", []):
                t = e.get("t", 0.0)
                if t >= t_end - float(last_s):
                    merged.append((t, r, e))
        merged.sort(key=lambda x: (x[0], x[1]))
        for t, r, e in merged[-int(max_events):]:
            dur = f" {e['dur_us'] / 1000.0:.3f}ms" if "dur_us" in e \
                else ""
            extra = {k: v for k, v in e.items()
                     if k not in ("t", "thr", "kind", "name", "dur_us")}
            lines.append(f"  [{t - t_end:+8.3f}s r{r} "
                         f"{e.get('thr', '?')}] {e.get('kind', 'ev')} "
                         f"{e.get('name', '?')}{dur}"
                         f"{' ' + json.dumps(extra) if extra else ''}")
        return "\n".join(lines) + "\n"


class ClusterLauncher:
    """Launch + supervise an N-rank localhost gang.

    Parameters
    ----------
    nprocs : gang size (default MXNET_CLUSTER_NPROCS, 2)
    devices_per_rank : virtual CPU devices pinned per rank (default 1)
    deadline_s : wall-clock budget; past it the whole tree is SIGKILLed
        and `deadline_fired` is set (default 120)
    failure_grace_s : after the first rank exits, how long the remaining
        ranks get to finish on their own before the supervisor reaps
        them (default: MXNET_DIST_TIMEOUT_S * (retries+1) + 15 — enough
        for every survivor's barrier timeout to fire and name the dead)
    dist_timeout_s / dist_retries : exported to the ranks as
        MXNET_DIST_TIMEOUT_S / MXNET_DIST_RETRIES when given
    inject : MXNET_CLUSTER_INJECT spec exported to every rank (the spec
        itself selects the victim rank)
    env : extra env vars for every rank
    stream : echo per-rank output with `[rN] ` prefixes (always captured
        in the per-rank tail either way)
    blackbox_dir : where each rank's flight recorder flushes its black
        box (default: a fresh temp dir per launcher); collected into
        `ClusterResult.blackboxes` after every launch
    hosts : multi-host gang spec — `"host1:4,host2:4"`, `[(host,
        slots), ...]`, or default MXNET_CLUSTER_HOSTS; ranks fill hosts
        in order, rank 0's host is the coordinator URI, non-local hosts
        run over `transport` (default SshTransport). When set, nprocs
        must equal (or defaults to) the slot total. Black boxes are
        collected from blackbox_dir as usual — remote ranks' boxes
        appear when it is on a shared filesystem.
    transport : transport for non-local hosts (tests pass a mock)
    """

    def __init__(self, nprocs=None, devices_per_rank=1, deadline_s=120.0,
                 failure_grace_s=None, dist_timeout_s=None,
                 dist_retries=None, inject=None, env=None, stream=True,
                 tail_lines=500, python=None, blackbox_dir=None,
                 hosts=None, transport=None):
        if hosts is None:
            hosts = os.environ.get("MXNET_CLUSTER_HOSTS") or None
        if hosts is not None:
            hosts = parse_host_spec(hosts) if isinstance(hosts, str) \
                else [(str(h), int(n)) for h, n in hosts]
            total = sum(n for _, n in hosts)
            if nprocs is None:
                nprocs = total
            elif int(nprocs) != total:
                raise ValueError(
                    f"nprocs={nprocs} != host-spec slot total {total}")
        self.hosts = hosts
        self.transport = transport or SshTransport()
        if nprocs is None:
            try:
                nprocs = int(os.environ.get("MXNET_CLUSTER_NPROCS", "2"))
            except ValueError:
                nprocs = 2
        self.nprocs = max(1, int(nprocs))
        self.devices_per_rank = max(1, int(devices_per_rank))
        self.deadline_s = float(deadline_s)
        self.dist_timeout_s = dist_timeout_s
        self.dist_retries = dist_retries
        if failure_grace_s is None:
            t = float(dist_timeout_s if dist_timeout_s is not None
                      else os.environ.get("MXNET_DIST_TIMEOUT_S") or 60.0)
            r = int(dist_retries if dist_retries is not None
                    else os.environ.get("MXNET_DIST_RETRIES") or 1)
            failure_grace_s = t * (r + 1) + 15.0
        self.failure_grace_s = float(failure_grace_s)
        self.inject = inject
        self.env = dict(env or {})
        self.stream = stream
        self.tail_lines = int(tail_lines)
        self.python = python or sys.executable
        self.blackbox_dir = blackbox_dir or tempfile.mkdtemp(
            prefix="mxnet_blackbox_")
        self._stream_lock = threading.Lock()

    # -- environment ---------------------------------------------------------

    def rank_hosts(self):
        """The host each rank lands on ([None] * nprocs when no host
        spec — plain localhost gang)."""
        if self.hosts is None:
            return [None] * self.nprocs
        out = []
        for host, slots in self.hosts:
            out.extend([host] * slots)
        return out

    def coordinator_host(self):
        """What every rank dials for the jax coordination service: rank
        0's host under a host spec, loopback otherwise."""
        if self.hosts is not None:
            host = self.hosts[0][0]
            if not _is_local_host(host):
                return host
        return "127.0.0.1"

    def rank_env(self, rank, port):
        """The env one rank runs under: DMLC_* contract + per-rank CPU
        device pin + the Gloo CPU-collectives backend."""
        env = dict(os.environ)
        env.update(self.env)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": self.coordinator_host(),
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(self.nprocs),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
        })
        d = self.devices_per_rank
        env["JAX_NUM_CPU_DEVICES"] = str(d)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={d}")
        env["XLA_FLAGS"] = " ".join(flags)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
        # black-box contract: every rank flushes its flight recorder
        # under the launcher's dir so a SIGKILLed rank still leaves a
        # postmortem (setdefault — an explicit caller env wins)
        env.setdefault("MXNET_FLIGHTREC_DIR", self.blackbox_dir)
        env.setdefault("MXNET_FLIGHTREC_FLUSH_S", "0.5")
        if self.dist_timeout_s is not None:
            env["MXNET_DIST_TIMEOUT_S"] = str(self.dist_timeout_s)
        if self.dist_retries is not None:
            env["MXNET_DIST_RETRIES"] = str(self.dist_retries)
        if self.inject:
            env["MXNET_CLUSTER_INJECT"] = str(self.inject)
        else:
            env.pop("MXNET_CLUSTER_INJECT", None)
        # gang topology is the launcher's, not the workers': a worker
        # that itself launches a gang must not inherit this host spec
        env.pop("MXNET_CLUSTER_HOSTS", None)
        return env

    # -- launch / supervise --------------------------------------------------

    def launch(self, argv):
        """Run `argv` (a full command list) as every rank; supervise to
        completion. Returns a ClusterResult; never raises on rank
        failure (the result carries the verdict)."""
        port = free_port()
        ranks = []
        hosts = self.rank_hosts()
        local = LocalTransport()
        t0 = time.monotonic()
        try:
            for r in range(self.nprocs):
                host = hosts[r]
                transport = local if host is None or _is_local_host(host) \
                    else self.transport
                proc = transport.popen(
                    host, list(argv), self.rank_env(r, port),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, errors="replace",
                    start_new_session=True)     # own pgid: killpg reaps
                ranks.append(RankProc(r, proc, self.tail_lines))
        except Exception:
            for rp in ranks:
                self._kill_tree(rp)
            raise
        pumps = [threading.Thread(target=self._pump, args=(rp,),
                                  name=f"cluster-log-r{rp.rank}",
                                  daemon=True) for rp in ranks]
        for p in pumps:
            p.start()
        deadline_fired = False
        first_exit_t = None
        first_death_t = None
        while True:
            alive = 0
            now = time.monotonic()
            for rp in ranks:
                if rp.exit_rc is None:
                    rc = rp.proc.poll()
                    if rc is None:
                        alive += 1
                    else:
                        rp.exit_rc = rc
                        rp.exit_t = now
                        if first_exit_t is None:
                            first_exit_t = now
                        if rc != 0 and first_death_t is None:
                            first_death_t = now
            if not alive:
                break
            if now - t0 > self.deadline_s:
                # the harness's last line of defense; the selftest matrix
                # asserts this never fires (survivors always self-abort
                # through the dist timeout first)
                deadline_fired = True
                self._emit("cluster: DEADLINE after "
                           f"{self.deadline_s:.0f}s — reaping "
                           f"{alive} live rank(s)\n")
                self._reap_live(ranks)
                break
            if (first_exit_t is not None
                    and now - first_exit_t > self.failure_grace_s):
                self._emit("cluster: rank(s) still running "
                           f"{self.failure_grace_s:.0f}s after the first "
                           "exit — reaping\n")
                self._reap_live(ranks)
                break
            time.sleep(0.05)
        now = time.monotonic()
        for rp in ranks:                    # collect post-reap statuses
            if rp.exit_rc is None:
                try:
                    rp.exit_rc = rp.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:   # pragma: no cover
                    rp.exit_rc = -signal.SIGKILL
                rp.exit_t = now
                if rp.exit_rc != 0 and first_death_t is None:
                    first_death_t = now
        for p in pumps:
            p.join(timeout=5)
        result = ClusterResult(ranks, time.monotonic() - t0,
                               deadline_fired, first_death_t, t0,
                               blackboxes=self.collect_blackboxes(),
                               blackbox_dir=self.blackbox_dir)
        if not result.ok and result.blackboxes:
            self._emit(result.triage())
        return result

    def collect_blackboxes(self):
        """Parse every rank's flight-recorder dump from blackbox_dir
        (rank -> box dict). Tolerant of missing/torn files — a rank that
        died before its first flush simply has no box."""
        boxes = {}
        pat = os.path.join(self.blackbox_dir, "flightrec-rank-*.json")
        for path in sorted(glob.glob(pat)):
            try:
                with open(path, encoding="utf-8") as f:
                    box = json.load(f)
                boxes[int(box.get("rank", -1))] = box
            except (OSError, ValueError):   # pragma: no cover - torn file
                continue
        return boxes

    def launch_python(self, source, args=(), workdir=None):
        """Write `source` to a worker script and launch it on every rank
        (the subprocess-worker idiom the dist tests already use)."""
        wd = workdir or tempfile.mkdtemp(prefix="mxnet_cluster_")
        script = os.path.join(wd, "cluster_worker.py")
        with open(script, "w", encoding="utf-8") as f:
            f.write(source)
        return self.launch([self.python, script, *map(str, args)])

    # -- internals -----------------------------------------------------------

    def _pump(self, rp):
        try:
            for line in rp.proc.stdout:
                rp.tail.append(line)
                if self.stream:
                    self._emit(f"[r{rp.rank}] {line}")
        except ValueError:                  # pragma: no cover - closed fd
            pass
        finally:
            try:
                rp.proc.stdout.close()
            except OSError:                 # pragma: no cover
                pass

    def _emit(self, text):
        with self._stream_lock:
            sys.stdout.write(text)
            sys.stdout.flush()

    def _reap_live(self, ranks):
        for rp in ranks:
            if rp.proc.poll() is None:
                rp.reaped = True
                self._kill_tree(rp)

    @staticmethod
    def _kill_tree(rp):
        """SIGKILL the rank's whole process group (start_new_session made
        it a group leader); SIGKILL lands on SIGSTOPped ranks too."""
        try:
            os.killpg(os.getpgid(rp.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                rp.proc.kill()
            except OSError:                 # pragma: no cover
                pass
