"""Self-healing pod supervisor — the auto-restart loop over the launcher.

PRs 12/13 built world-class *detection*: a dead or wedged rank turns
into a named `DistRankFailure` in ~5 s, every rank leaves a
flight-recorder black box, and the launcher's triage names who went
quiet first. But recovery was still "a human relaunches". The
`Supervisor` closes the loop the way the reference's ps-lite tolerated
worker death by design (the server kept state; workers rejoined): it
wraps `ClusterLauncher` in a restart loop that, on gang failure,

  1. collects the black boxes and classifies what died
     (`classify_result`): a SIGKILL/SIGSTOP victim (transient,
     preemption-shaped), an abrupt nonzero exit (deterministic-crash
     candidate), or rank 0 (coordinator death — jax's coordination
     service lives in rank 0's process and is NOT HA, so losing it
     always costs the whole gang; the supervisor recovers it like any
     other fault, with a full-gang restart);
  2. decides what to do (`decide` — the decision table in
     docs/CLUSTER.md): restart-in-place at N, shrink to N−1 when the
     same rank keeps dying (its host slot is dropped; surviving hosts
     only — the elastic format-2 checkpoint reshards onto the smaller
     gang), or give up with exit `GIVEUP_EXIT` (44) when the
     exponential-backoff restart budget (`MXNET_SUPERVISE_MAX_RESTARTS`
     consecutive relaunches without a new sealed commit,
     `MXNET_SUPERVISE_BACKOFF_S` base backoff) is exhausted or a
     deterministic crash loops;
  3. relaunches every rank from the last *sealed* checkpoint commit
     (`checkpoint.last_sealed_commit` — the TOPOLOGY.json seal is the
     durability line; the restarted workers get a `resume` argv token
     and restore it themselves), and
  4. stamps `restarts_total` / `mttr_s` / `shrink_events` into the
     telemetry registry, the profiler counter export, the JSONL
     steplog, and (through `--bench`) the dist_recovery bench lane.

MTTR is measured from the victim's death (wall clock of the failed
incarnation's first death) to the first post-restart training step the
relaunched workers report (`{"evt": "step", "t": ...}` JSON lines in
the rank tails — the cluster selftest workers and BaseModule's steplog
both emit them); when a workload reports no step events, the relaunch
instant is used, so the metric degrades to time-to-gang-up instead of
lying.

Progress — what resets the restart budget and the repeat-offender
streak — is a NEW sealed checkpoint step appearing between
incarnations. A job that keeps sealing commits between faults restarts
forever (flaky fleet, fine); a job that cannot seal anything burns the
budget and exits 44 so a pod scheduler can tell "needs a human" from
"recovering".

Concurrency surfaces (analysis/locklint contract): the supervisor runs
entirely on the calling thread — every launch() is synchronous and the
counters dict has a single writer. No locks, no threads of its own.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

from .launcher import ClusterLauncher

__all__ = ["Supervisor", "SupervisorResult", "FailureInfo", "Decision",
           "classify_result", "decide", "GIVEUP_EXIT"]

# analysis/locklint: supervisor state is single-threaded by design (the
# restart loop blocks in launch(); nothing else touches it)
__analysis_thread_safe__ = {"Supervisor._counters"}

GIVEUP_EXIT = 44        # the supervisor's "needs a human" exit status

# consecutive failures of the SAME victim rank before it is treated as
# a repeat offender (shrink) / a deterministic crash loop (give up)
REPEAT_THRESHOLD = 2

_BACKOFF_CAP_S = 30.0


def _max_restarts(override=None):
    if override is not None:
        return max(0, int(override))
    from .. import config
    try:
        return max(0, int(config.get("MXNET_SUPERVISE_MAX_RESTARTS")))
    except (TypeError, ValueError):
        return 3


def _backoff_s(override=None):
    if override is not None:
        return max(0.0, float(override))
    from .. import config
    try:
        return max(0.0, float(config.get("MXNET_SUPERVISE_BACKOFF_S")))
    except (TypeError, ValueError):
        return 1.0


class FailureInfo:
    """What killed one incarnation: the victim rank (black-box triage
    first, exit records second), how it died, and whether the victim
    was the coordinator (rank 0 — its loss takes jax's coordination
    service with it)."""

    __slots__ = ("victim", "kind", "rc", "coordinator", "detail")

    def __init__(self, victim, kind, rc=None, detail=""):
        self.victim = victim
        self.kind = kind            # kill | hang | crash | deadline | unknown
        self.rc = rc
        self.coordinator = victim == 0
        self.detail = detail

    def __repr__(self):
        coord = " coordinator" if self.coordinator else ""
        return (f"FailureInfo(victim={self.victim}, kind={self.kind}, "
                f"rc={self.rc}{coord})")


def classify_result(result):
    """Classify a failed ClusterResult into a FailureInfo.

    Victim attribution order: a SINGLE non-reaped signal death (the
    inject plane's `os._exit(41)` counts; SIGABRT does not — peers of a
    dead coordinator abort themselves when the jax coordination service
    vanishes, so an abort is a symptom, not a murder), then the
    flight-recorder quiet-rank triage (the box that stopped updating
    first — tie-broken by lowest last sequence number; the only
    evidence for a SIGSTOP hang), then reaped ranks, then any signal
    death or abrupt exit, then plain nonzero exits. Ranks that exited
    `dist.RANK_FAILURE_EXIT` (43) died OF a peer's death and are never
    the victim."""
    from ..dist import RANK_FAILURE_EXIT
    from .inject import EXIT_CODE
    rcs = result.returncodes
    reaped = set(result.reaped_ranks)

    def rc_of(r):
        return rcs[r] if r is not None and r < len(rcs) else None

    if getattr(result, "deadline_fired", False):
        victim = result.quiet_rank
        return FailureInfo(victim, "deadline", rc_of(victim),
                           "harness deadline reaper fired")
    murders = [r for r, rc in enumerate(rcs)
               if rc is not None and r not in reaped
               and ((rc < 0 and rc != -signal.SIGABRT)
                    or rc == EXIT_CODE)]
    victim = murders[0] if len(murders) == 1 else None
    if victim is None:
        victim = result.quiet_rank
    if victim is None and reaped:
        victim = min(reaped)
    if victim is None:
        for r, rc in enumerate(rcs):
            if rc is not None and (rc < 0 or rc == EXIT_CODE):
                victim = r
                break
    if victim is None:
        for r, rc in enumerate(rcs):
            if rc not in (0, None, RANK_FAILURE_EXIT):
                victim = r
                break
    if victim is None:
        return FailureInfo(None, "unknown", None,
                           "no attributable victim in exit records")
    rc = rc_of(victim)
    if victim in reaped:
        kind = "hang"               # only the supervisor's SIGKILL ends
        detail = "reaped by the launcher (wedged/SIGSTOPped)"
    elif rc is not None and rc < 0:
        kind = "kill"
        detail = f"died by signal {-rc}"
    elif rc == EXIT_CODE:
        kind = "crash"
        detail = f"abrupt exit {EXIT_CODE} (inject plane)"
    else:
        kind = "crash"
        detail = f"exited rc={rc}"
    return FailureInfo(victim, kind, rc, detail)


class Decision:
    __slots__ = ("action", "reason")

    def __init__(self, action, reason):
        self.action = action        # restart | shrink | give_up
        self.reason = reason

    def __repr__(self):
        return f"Decision({self.action}: {self.reason})"


def decide(info, *, nprocs, min_nprocs, consecutive_no_progress,
           max_restarts, repeat_count, progressed, allow_shrink,
           repeat_threshold=REPEAT_THRESHOLD):
    """The supervisor decision table (docs/CLUSTER.md):

    1. restart budget: more than `max_restarts` consecutive relaunches
       without a new sealed commit -> give up (exit 44);
    2. deterministic crash loop: the same rank exits nonzero
       `repeat_threshold` times in a row with no progress -> give up
       (a code/data bug restarts cannot fix);
    3. repeat offender: the same rank dies `repeat_threshold` times in
       a row (kill/hang — flaky host shape) and the gang can shrink ->
       shrink to N−1, dropping the victim's slot;
    4. otherwise -> restart-in-place at N (transient fault; rank-0 /
       coordinator death lands here too — full-gang restart, because
       jax's coordination service is not HA)."""
    if consecutive_no_progress > max_restarts:
        return Decision("give_up",
                        f"restart budget exhausted: {consecutive_no_progress}"
                        f" consecutive relaunches without a sealed commit "
                        f"(budget {max_restarts})")
    if (info.kind == "crash" and repeat_count >= repeat_threshold
            and not progressed):
        return Decision("give_up",
                        f"deterministic crash loop: rank {info.victim} "
                        f"exited rc={info.rc} {repeat_count}x in a row "
                        "with no progress")
    if (repeat_count >= repeat_threshold and allow_shrink
            and info.victim is not None and nprocs - 1 >= min_nprocs):
        return Decision("shrink",
                        f"repeat offender: rank {info.victim} died "
                        f"{repeat_count}x in a row — dropping its slot, "
                        f"continuing at {nprocs - 1}")
    why = ("coordinator (rank 0) death — full-gang restart, jax's "
           "coordination service is not HA"
           if info.coordinator else f"transient {info.kind}")
    return Decision("restart", f"{why}; restart-in-place at {nprocs}")


class SupervisorResult:
    """One supervised run, end to end: per-incarnation records (victim,
    classification, decision, sealed step), the final ClusterResult,
    and the recovery metrics the bench lane records."""

    def __init__(self):
        self.incarnations = []      # dicts: one per launch
        self.results = []           # the ClusterResults, same order
        self.restarts_total = 0
        self.shrink_events = 0
        self.mttr_s_all = []
        self.gave_up = None         # reason string when the budget blew
        self.final_nprocs = None
        self.ok = False
        self.exit_code = 1

    @property
    def mttr_s(self):
        return self.mttr_s_all[0] if self.mttr_s_all else None

    def describe(self):
        mttr = ("none" if self.mttr_s is None
                else f"{self.mttr_s:.2f}s")
        tail = f" gave_up={self.gave_up!r}" if self.gave_up else ""
        return (f"ok={self.ok} exit={self.exit_code} "
                f"incarnations={len(self.incarnations)} "
                f"restarts={self.restarts_total} "
                f"shrinks={self.shrink_events} mttr={mttr} "
                f"final_nprocs={self.final_nprocs}{tail}")


class Supervisor:
    """Run a gang workload under automatic fault recovery.

    Parameters
    ----------
    argv : command list every rank runs (or use `source`)
    source : worker python source (written once, launched per rank)
    args : extra argv for `source` workers
    nprocs : initial gang size (default MXNET_CLUSTER_NPROCS)
    min_nprocs : smallest gang the shrink path may reach (default 1)
    checkpoint_dir : where the workload seals commits; drives both the
        progress signal (restart budget resets on a new sealed step)
        and the restart-point log line
    resume_arg : argv token appended on relaunches (and on the first
        launch when a sealed commit already exists) so workers restore;
        None disables
    max_restarts : consecutive no-progress relaunches before giving up
        (default MXNET_SUPERVISE_MAX_RESTARTS, 3)
    backoff_s : base of the exponential relaunch backoff applied after
        no-progress failures (default MXNET_SUPERVISE_BACKOFF_S, 1.0)
    allow_shrink : permit shrink-to-(N-1) for repeat offenders
    hosts : multi-host spec forwarded to ClusterLauncher (string
        "host1:4,host2:4", or [(host, slots), ...]); shrink drops the
        victim's slot from it
    inject : MXNET_CLUSTER_INJECT spec for incarnation 0 ONLY (the
        injected fault must not re-arm after recovery)
    inject_plan : dict/callable incarnation->spec overriding `inject`
        (selftests re-injecting to prove the shrink path)
    launcher_factory : callable(nprocs, inject, hosts) -> launcher
        (tests substitute fakes; default builds ClusterLauncher with
        `launcher_kwargs`)
    launcher_kwargs : extra ClusterLauncher kwargs (deadline_s, env,
        dist_timeout_s, ...)
    """

    def __init__(self, argv=None, source=None, args=(), nprocs=None,
                 min_nprocs=1, checkpoint_dir=None, resume_arg="resume",
                 max_restarts=None, backoff_s=None, allow_shrink=True,
                 hosts=None, inject=None, inject_plan=None,
                 launcher_factory=None, launcher_kwargs=None,
                 progress_evt="step", stream=True):
        if (argv is None) == (source is None):
            raise ValueError("Supervisor needs exactly one of argv= / "
                             "source=")
        self._argv = list(argv) if argv else None
        self._source = source
        self._args = tuple(args)
        if hosts is None:
            # own the host spec here: shrink must be able to rewrite it,
            # and an explicit hosts= to the launcher outranks the env
            hosts = os.environ.get("MXNET_CLUSTER_HOSTS") or None
        if nprocs is None and hosts is not None:
            from .launcher import parse_host_spec
            pairs = parse_host_spec(hosts) if isinstance(hosts, str) \
                else hosts
            nprocs = sum(int(n) for _, n in pairs)
        if nprocs is None:
            try:
                nprocs = int(os.environ.get("MXNET_CLUSTER_NPROCS", "2"))
            except ValueError:
                nprocs = 2
        self.nprocs = max(1, int(nprocs))
        self.min_nprocs = max(1, int(min_nprocs))
        self.checkpoint_dir = checkpoint_dir
        self.resume_arg = resume_arg
        self.max_restarts = _max_restarts(max_restarts)
        self.backoff_s = _backoff_s(backoff_s)
        self.allow_shrink = bool(allow_shrink)
        self.hosts = hosts
        self._inject = inject
        self._inject_plan = inject_plan
        self._factory = launcher_factory
        self._launcher_kwargs = dict(launcher_kwargs or {})
        self.progress_evt = progress_evt
        self.stream = stream
        self._counters = {"restarts_total": 0, "shrink_events": 0,
                          "give_ups": 0, "mttr_s_last": 0.0,
                          "gang_size": self.nprocs}
        try:
            from .. import profiler
            profiler.register_counter_export("supervisor", self.counters)
        except Exception:               # pragma: no cover
            pass

    # -- observability -------------------------------------------------------

    def counters(self):
        return dict(self._counters)

    def _emit(self, text):
        if self.stream:
            sys.stdout.write(f"supervisor: {text}\n")
            sys.stdout.flush()

    def _note_metrics(self, result):
        """Stamp the recovery metrics into the telemetry registry + the
        JSONL steplog (never raises: recovery must not die of
        observability)."""
        try:
            from ..telemetry import counter, gauge
            counter("mxnet_supervisor_restarts_total",
                    help="gang relaunches performed by the cluster "
                         "supervisor")
            # counters are cumulative: re-sync to the result totals
            gauge("mxnet_supervisor_gang_size",
                  help="current supervised gang size").set(
                result.final_nprocs or self.nprocs)
            if result.mttr_s_all:
                gauge("mxnet_supervisor_mttr_seconds",
                      help="last measured mean-time-to-recovery: victim "
                           "death to first post-restart step").set(
                    result.mttr_s_all[-1])
        except Exception:               # pragma: no cover
            pass
        try:
            from ..telemetry.steplog import log_event
            log_event("supervisor_recovery",
                      restarts_total=result.restarts_total,
                      shrink_events=result.shrink_events,
                      mttr_s=result.mttr_s,
                      gave_up=bool(result.gave_up),
                      final_nprocs=result.final_nprocs)
        except Exception:               # pragma: no cover
            pass

    # -- plumbing ------------------------------------------------------------

    def _inject_for(self, incarnation):
        plan = self._inject_plan
        if callable(plan):
            return plan(incarnation)
        if isinstance(plan, dict):
            return plan.get(incarnation)
        if isinstance(plan, (list, tuple)):
            return plan[incarnation] if incarnation < len(plan) else None
        return self._inject if incarnation == 0 else None

    def _make_launcher(self, nprocs, inject, hosts):
        if self._factory is not None:
            return self._factory(nprocs, inject, hosts)
        kw = dict(self._launcher_kwargs)
        kw.update(nprocs=nprocs, inject=inject)
        if hosts is not None:
            kw["hosts"] = hosts
        kw.setdefault("stream", self.stream)
        return ClusterLauncher(**kw)

    def _sealed_step(self):
        if not self.checkpoint_dir:
            return None
        try:
            from ..checkpoint import last_sealed_commit
            info = last_sealed_commit(self.checkpoint_dir)
            return None if info is None else info["step"]
        except Exception:               # pragma: no cover
            return None

    def _base_argv(self):
        if self._argv is not None:
            return list(self._argv)
        # write the worker source ONCE; every incarnation reuses the path
        wd = tempfile.mkdtemp(prefix="mxnet_supervise_")
        script = os.path.join(wd, "supervised_worker.py")
        with open(script, "w", encoding="utf-8") as f:
            f.write(self._source)
        return [sys.executable, script, *map(str, self._args)]

    def _first_progress_t(self, result):
        """Earliest wall timestamp of a progress (`step`) event any rank
        printed — the recovery instant MTTR ends at."""
        best = None
        for text in result.tails.values():
            for line in text.splitlines():
                line = line.strip()
                if not (line.startswith("{") and '"evt"' in line):
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if d.get("evt") == self.progress_evt and "t" in d:
                    t = float(d["t"])
                    if best is None or t < best:
                        best = t
        return best

    @staticmethod
    def _shrink_hosts(hosts, victim, nprocs):
        """Drop the victim rank's slot from a host spec (ranks fill
        hosts in order). None spec (localhost) stays None — the gang
        just shrinks."""
        if hosts is None:
            return None
        from .launcher import parse_host_spec
        pairs = parse_host_spec(hosts) if isinstance(hosts, str) \
            else [(h, int(n)) for h, n in hosts]
        out, rank = [], 0
        for host, slots in pairs:
            keep = slots
            if rank <= victim < rank + slots:
                keep = slots - 1
            if keep > 0:
                out.append((host, keep))
            rank += slots
        return out or None

    # -- the loop ------------------------------------------------------------

    def run(self):
        """Supervise to completion. Returns a SupervisorResult; never
        raises on workload failure (the result carries the verdict)."""
        out = SupervisorResult()
        base_argv = self._base_argv()
        nprocs, hosts = self.nprocs, self.hosts
        incarnation = 0
        consecutive_no_progress = 0
        repeat_count, last_victim = 0, None
        pending_death_wall = None
        sealed_before = self._sealed_step()
        while True:
            argv = list(base_argv)
            if self.resume_arg and (incarnation > 0
                                    or sealed_before is not None):
                argv.append(self.resume_arg)
            inject = self._inject_for(incarnation)
            launcher = self._make_launcher(nprocs, inject, hosts)
            self._emit(f"incarnation {incarnation}: launching {nprocs} "
                       f"rank(s)"
                       + (f" from sealed step {sealed_before}"
                          if sealed_before is not None else " fresh")
                       + (f" [inject={inject}]" if inject else ""))
            launch_wall = time.time()
            res = launcher.launch(argv)
            out.results.append(res)
            self._counters["gang_size"] = nprocs
            if pending_death_wall is not None:
                t_rec = self._first_progress_t(res) or launch_wall
                mttr = max(0.0, t_rec - pending_death_wall)
                out.mttr_s_all.append(round(mttr, 3))
                self._counters["mttr_s_last"] = round(mttr, 3)
                self._emit(f"recovered: MTTR {mttr:.2f}s (death -> first "
                           "post-restart step)")
                pending_death_wall = None
            rec = {"incarnation": incarnation, "nprocs": nprocs,
                   "ok": res.ok, "deadline_fired": res.deadline_fired,
                   "returncodes": list(res.returncodes),
                   "sealed_step": sealed_before}
            if res.ok:
                rec.update(decision="done", victim=None)
                out.incarnations.append(rec)
                out.ok = True
                out.exit_code = 0
                break
            info = classify_result(res)
            sealed_now = self._sealed_step()
            progressed = (sealed_now is not None
                          and (sealed_before is None
                               or sealed_now > sealed_before))
            sealed_before = sealed_now
            if progressed:
                consecutive_no_progress = 1
            else:
                consecutive_no_progress += 1
            if info.victim is not None and info.victim == last_victim:
                repeat_count += 1
            else:
                repeat_count = 1
            last_victim = info.victim
            decision = decide(
                info, nprocs=nprocs, min_nprocs=self.min_nprocs,
                consecutive_no_progress=consecutive_no_progress,
                max_restarts=self.max_restarts,
                repeat_count=repeat_count, progressed=progressed,
                allow_shrink=self.allow_shrink)
            rec.update(victim=info.victim, kind=info.kind,
                       coordinator=info.coordinator, detail=info.detail,
                       decision=decision.action, reason=decision.reason,
                       progressed=progressed,
                       sealed_step=sealed_now)
            out.incarnations.append(rec)
            self._emit(f"incarnation {incarnation} failed: {info!r} — "
                       f"{decision.action} ({decision.reason})")
            if decision.action == "give_up":
                out.gave_up = decision.reason
                out.ok = False
                out.exit_code = GIVEUP_EXIT
                self._counters["give_ups"] += 1
                break
            if decision.action == "shrink":
                hosts = self._shrink_hosts(hosts, info.victim, nprocs)
                nprocs -= 1
                out.shrink_events += 1
                self._counters["shrink_events"] += 1
                try:
                    from ..telemetry import counter
                    counter("mxnet_supervisor_shrink_events_total",
                            help="gang shrink-to-(N-1) recoveries").inc()
                except Exception:           # pragma: no cover
                    pass
            death_s = res.first_death_s if res.first_death_s is not None \
                else res.elapsed_s
            pending_death_wall = launch_wall + death_s
            out.restarts_total += 1
            self._counters["restarts_total"] += 1
            try:
                from ..telemetry import counter
                counter("mxnet_supervisor_restarts_total",
                        help="gang relaunches performed by the cluster "
                             "supervisor").inc()
            except Exception:               # pragma: no cover
                pass
            if not progressed and consecutive_no_progress > 1:
                delay = min(_BACKOFF_CAP_S, self.backoff_s
                            * (2 ** (consecutive_no_progress - 2)))
                if delay > 0:
                    self._emit(f"backing off {delay:.2f}s before "
                               "relaunch (no progress)")
                    time.sleep(delay)
            incarnation += 1
        out.final_nprocs = nprocs
        if not out.ok and out.exit_code != GIVEUP_EXIT:
            out.exit_code = next(
                (rc for rc in out.results[-1].returncodes
                 if rc not in (0, None)), 1)
        self._note_metrics(out)
        self._emit(out.describe())
        return out
