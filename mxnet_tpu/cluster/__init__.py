"""mxnet_tpu.cluster — multi-process launch/supervise/fault-inject harness.

Beyond-reference subsystem (docs/CLUSTER.md) closing ROADMAP's
"multi-process collective harness" item: the reference's dmlc-tracker
launched remote worker/server gangs; here the testable pod is N real
Python processes joined into one `jax.distributed` job on localhost.

Three pieces:

  - **launcher** (launcher.py): `ClusterLauncher` spawns the gang with
    per-rank CPU-device pinning + the Gloo CPU-collectives backend,
    streams rank-prefixed logs, enforces a wall-clock deadline, and
    reaps the whole tree when ranks wedge after a death.
  - **inject** (inject.py): `MXNET_CLUSTER_INJECT=<kill|hang|exit>@
    <point>[:rank][@<n>]` — named injection points threaded through
    dist.py and the cooperative checkpoint commit.
  - **selftest** (__main__.py): `python -m mxnet_tpu.cluster --selftest
    --nprocs 2` (the ci.sh quick smoke), `--matrix` for the full
    injection matrix including the kill-mid-cooperative-commit
    sha256-identity proof, `--bench` for the bench.py dist_recovery
    lane.

The runtime-hardening half lives in `mxnet_tpu.dist`: timeout barriers,
`DistRankFailure` naming missing ranks, coordinated abort
(`MXNET_DIST_TIMEOUT_S` / `MXNET_DIST_RETRIES`).
"""
from __future__ import annotations

from .launcher import (ClusterLauncher, ClusterResult, RankProc,
                       cpu_collectives_available, free_port)
from .inject import (ACTIONS, ENV_VAR, INJECTION_POINTS, InjectSpec,
                     maybe_inject, parse_spec)
from ..dist import DistRankFailure

__all__ = ["ClusterLauncher", "ClusterResult", "RankProc",
           "cpu_collectives_available", "free_port", "DistRankFailure",
           "ACTIONS", "ENV_VAR", "INJECTION_POINTS", "InjectSpec",
           "maybe_inject", "parse_spec"]
