"""mxnet_tpu.cluster — multi-process launch/supervise/fault-inject harness.

Beyond-reference subsystem (docs/CLUSTER.md) closing ROADMAP's
"multi-process collective harness" item: the reference's dmlc-tracker
launched remote worker/server gangs; here the testable pod is N real
Python processes joined into one `jax.distributed` job on localhost.

Four pieces:

  - **launcher** (launcher.py): `ClusterLauncher` spawns the gang with
    per-rank CPU-device pinning + the Gloo CPU-collectives backend,
    streams rank-prefixed logs, enforces a wall-clock deadline, and
    reaps the whole tree when ranks wedge after a death. Multi-host
    via `MXNET_CLUSTER_HOSTS=host1:4,host2:4` / a hostfile: non-local
    ranks ride ssh carrying the DMLC env contract, rank 0's host is
    the coordinator.
  - **supervisor** (supervisor.py): the self-healing loop — on gang
    death it classifies the failure off the black boxes, decides
    restart-in-place vs shrink-to-(N−1) vs give-up (exit 44,
    `MXNET_SUPERVISE_MAX_RESTARTS`/`_BACKOFF_S` budget), relaunches
    from the last sealed checkpoint commit, and stamps
    restarts_total / mttr_s / shrink_events into telemetry.
  - **inject** (inject.py): `MXNET_CLUSTER_INJECT=<kill|hang|exit>@
    <point>[:rank][@<n>]` — named injection points threaded through
    dist.py and the cooperative checkpoint commit.
  - **selftest** (__main__.py): `python -m mxnet_tpu.cluster --selftest
    --nprocs 2` (the ci.sh quick smoke), `--supervise` for the
    self-healing phases (SIGKILL at N=3 → automatic recovery),
    `--matrix` for the full injection matrix including the
    kill-mid-cooperative-commit sha256-identity proof, `--bench` for
    the bench.py dist_recovery lane.

The runtime-hardening half lives in `mxnet_tpu.dist`: timeout barriers,
`DistRankFailure` naming missing ranks, coordinated abort
(`MXNET_DIST_TIMEOUT_S` / `MXNET_DIST_RETRIES`).
"""
from __future__ import annotations

from .launcher import (ClusterLauncher, ClusterResult, RankProc,
                       cpu_collectives_available, free_port,
                       parse_host_spec, read_hostfile, LocalTransport,
                       SshTransport)
from .inject import (ACTIONS, ENV_VAR, INJECTION_POINTS, InjectSpec,
                     maybe_inject, parse_spec)
from .supervisor import (Supervisor, SupervisorResult, FailureInfo,
                         Decision, classify_result, decide, GIVEUP_EXIT)
from ..dist import DistRankFailure

__all__ = ["ClusterLauncher", "ClusterResult", "RankProc",
           "cpu_collectives_available", "free_port", "DistRankFailure",
           "parse_host_spec", "read_hostfile", "LocalTransport",
           "SshTransport", "Supervisor", "SupervisorResult",
           "FailureInfo", "Decision", "classify_result", "decide",
           "GIVEUP_EXIT", "ACTIONS", "ENV_VAR", "INJECTION_POINTS",
           "InjectSpec", "maybe_inject", "parse_spec"]
