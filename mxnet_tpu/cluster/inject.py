"""Fault-injection plane for the cluster harness.

`MXNET_CLUSTER_INJECT=<kill|hang|exit>@<point>[:rank][@<n>]` arms ONE
named injection point (the `MXNET_CHECKPOINT_INJECT_CRASH=<point>@<step>`
idiom generalized to the multi-process runtime): when the `n`-th hit of
`<point>` lands on the selected rank, the process is SIGKILLed (`kill`),
SIGSTOPped (`hang` — the process stays alive but silent, the shape of a
wedged NIC or a GIL-stuck rank), or `os._exit(41)`s (`exit`).
Omitting `:rank` fires on every rank; omitting `@<n>` fires on the first
hit. The spec is parsed per call straight from the environment — a dict
lookup when unarmed — so workers can arm/disarm dynamically and the
launcher can arm a single rank by env alone.

Injection points (docs/CLUSTER.md carries the table):

  pre-barrier / post-barrier   dist.barrier entry / exit
  mid-step                     dist.allreduce_sum, before the collective
                               (the kvstore push gradient reduce)
  pre-commit                   cooperative checkpoint commit entry
  mid-cooperative-commit       after this rank wrote its owned shards,
                               before the all-shards barrier
  pre-seal                     rank 0 only: all shards on disk, before
                               the TOPOLOGY.json seal

This module must stay import-light (no jax, no mxnet_tpu package hooks):
dist.py and checkpoint/manager.py import it inside hot functions.
"""
from __future__ import annotations

import os
import signal
import sys
import threading

__all__ = ["INJECTION_POINTS", "ACTIONS", "ENV_VAR", "InjectSpec",
           "parse_spec", "current_rank", "maybe_inject", "reset_counters"]

ENV_VAR = "MXNET_CLUSTER_INJECT"

INJECTION_POINTS = {
    "pre-barrier": "dist.barrier entry, before the rendezvous",
    "post-barrier": "dist.barrier exit, after the rendezvous",
    "mid-step": "dist.allreduce_sum before the cross-process reduce "
                "(the kvstore push path)",
    "pre-commit": "cooperative checkpoint commit entry, before staging",
    "mid-cooperative-commit": "own shards written, before the "
                              "all-shards barrier",
    "pre-seal": "rank 0 only: every shard on disk, before the "
                "TOPOLOGY.json seal",
}

ACTIONS = ("kill", "hang", "exit")

EXIT_CODE = 41          # the `exit` action's recognizable status

_lock = threading.Lock()
_hits = {}              # point -> hit count (this process)
_fired = set()          # points whose action already ran (`exit` may be
                        # caught upstream; never fire twice)


class InjectSpec:
    """Parsed `<action>@<point>[:rank][@<n>]`."""

    __slots__ = ("action", "point", "rank", "nth")

    def __init__(self, action, point, rank=None, nth=1):
        self.action = action
        self.point = point
        self.rank = rank
        self.nth = nth

    def __repr__(self):
        r = "" if self.rank is None else f":{self.rank}"
        n = "" if self.nth == 1 else f"@{self.nth}"
        return f"{self.action}@{self.point}{r}{n}"


def parse_spec(spec):
    """Parse an injection spec string; raises ValueError on malformed
    input (unknown action/point, non-integer rank/nth)."""
    spec = str(spec).strip()
    action, sep, rest = spec.partition("@")
    if not sep or action not in ACTIONS:
        raise ValueError(
            f"{ENV_VAR}: want <kill|hang|exit>@<point>[:rank][@<n>], "
            f"got {spec!r}")
    point, sep, nth_s = rest.partition("@")
    nth = 1
    if sep:
        try:
            nth = int(nth_s)
        except ValueError:
            raise ValueError(f"{ENV_VAR}: hit index {nth_s!r} not an int")
        if nth < 1:
            raise ValueError(f"{ENV_VAR}: hit index must be >= 1")
    point, sep, rank_s = point.partition(":")
    rank = None
    if sep:
        try:
            rank = int(rank_s)
        except ValueError:
            raise ValueError(f"{ENV_VAR}: rank {rank_s!r} not an int")
    if point not in INJECTION_POINTS:
        raise ValueError(
            f"{ENV_VAR}: unknown point {point!r} "
            f"(known: {', '.join(sorted(INJECTION_POINTS))})")
    return InjectSpec(action, point, rank, nth)


def current_rank():
    """This process's rank per the DMLC env contract (the launcher always
    exports DMLC_WORKER_ID; 0 outside a launched gang)."""
    try:
        return int(os.environ.get("DMLC_WORKER_ID", "0"))
    except ValueError:
        return 0


def reset_counters():
    """Forget hit counts (tests that parse/fire in-process repeatedly)."""
    with _lock:
        _hits.clear()
        _fired.clear()


def _fire(spec, point):
    sys.stderr.write(
        f"[cluster-inject] firing {spec.action}@{point} "
        f"rank {current_rank()} pid {os.getpid()}\n")
    sys.stderr.flush()
    sys.stdout.flush()
    if spec.action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif spec.action == "hang":
        os.kill(os.getpid(), signal.SIGSTOP)    # frozen until SIGCONT/KILL
    else:                                       # exit
        # os._exit, not SystemExit: interpreter teardown would try to
        # shut the jax distributed client down against peers that are
        # NOT exiting and block — the simulated crash must be prompt
        os._exit(EXIT_CODE)
    return True


def maybe_inject(point):
    """Hot-path hook: fire the armed action if `point` matches the
    MXNET_CLUSTER_INJECT spec on this rank's n-th hit. Returns True when
    a non-fatal action (hang, resumed later) fired, False otherwise.
    Cost when unarmed: one os.environ lookup."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return False
    try:
        spec = parse_spec(raw)
    except ValueError as e:
        sys.stderr.write(f"[cluster-inject] ignoring bad spec: {e}\n")
        return False
    if point != spec.point:
        return False
    if spec.rank is not None and current_rank() != spec.rank:
        return False
    with _lock:
        if point in _fired:
            return False
        _hits[point] = _hits.get(point, 0) + 1
        if _hits[point] != spec.nth:
            return False
        _fired.add(point)
    return _fire(spec, point)
