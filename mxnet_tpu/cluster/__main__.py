"""Cluster harness selftest CLI — fault-injection proof of the
multi-process runtime.

    python -m mxnet_tpu.cluster --selftest --nprocs 2   # ci smoke (~20s)
    python -m mxnet_tpu.cluster --selftest --matrix     # full injection matrix
    python -m mxnet_tpu.cluster --selftest --supervise  # self-healing proofs (N=3)
    python -m mxnet_tpu.cluster --bench                 # dist_recovery JSON
    python -m mxnet_tpu.cluster -n 2 [--deadline S] <cmd...>   # launch/supervise
    python -m mxnet_tpu.cluster --supervise [--hosts h1:2,h2:2] <cmd...>

Smoke phases (ci.sh quick): a 2-process barrier/collective round-trip;
an injected SIGKILL pre-barrier whose survivor raises `DistRankFailure`
naming the dead rank within MXNET_DIST_TIMEOUT_S — and whose postmortem
(every rank's flight-recorder black box, plus the merged span-trace
timeline) names the same victim rank; a kill mid-cooperative
checkpoint commit (torn step never sealed) followed by a
supervisor-driven restart that resumes from the last sealed commit and
finishes the run.

`--matrix` adds the acceptance proofs: the torn step's restored
`state_sha256` equals an uninterrupted baseline's same-step hash (and so
do every post-resume commit's), a SIGSTOP hang whose survivor aborts and
whose frozen rank the supervisor reaps, an `exit` mid-step whose
survivor turns the dead collective into `DistRankFailure`, and a rank-0
kill pre-seal (taking the coordination service with it). Every phase
asserts the harness deadline reaper did NOT fire — injected faults must
end in named failures, never in the supervisor's last-resort kill.

`--supervise` proves the SELF-HEALING loop at N=3, no human relaunch
anywhere: a SIGKILLed non-zero rank and (separately) rank 0 — the
coordinator — both end in automatic resume from the last sealed commit
with every subsequent commit sha equal to the uninterrupted baseline;
a repeat-offender rank triggers shrink-to-(N−1) whose smaller gang
STILL lands on the baseline shas (the workload's global gradient is a
fixed sum of dyadic rationals over virtual shards, so the trajectory is
bitwise gang-size-independent); a deterministic crash loop exhausts the
restart budget and exits 44.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

from .launcher import (ClusterLauncher, cpu_collectives_available,
                       parse_host_spec, read_hostfile)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# short fuse for the injection phases: every survivor must detect and
# abort well inside the phase deadline
_TIMEOUT_S = 5.0
# even shorter under supervision: a false-positive abort self-heals (the
# supervisor just relaunches), so the detect fuse can be tighter — this
# is what drives mttr_s down vs the old human-relaunch measurement
_SUP_TIMEOUT_S = 4.0
_STEPS, _PERIOD = 12, 4         # commits at steps 4, 8, 12; faults
_TORN_STEP = 8                  # target the 2nd commit (@2): step 8


class SelftestFailure(AssertionError):
    pass


def _check(cond, msg):
    if not cond:
        raise SelftestFailure(msg)


def _events(result):
    """Parse the per-rank JSON event lines the workers print."""
    evs = []
    for rank, text in sorted(result.tails.items()):
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{") and '"evt"' in line:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                d["_rank"] = rank
                evs.append(d)
    return evs


def _base_env():
    env = {"PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                            ""),
           "MXNET_TELEMETRY": "0"}
    # injection specs/timeouts must come from each phase alone, not leak
    # in from the caller's environment
    return env


_BARRIER_WORKER = r"""
import json, os, sys, time
import numpy as np
import mxnet_tpu as mx              # joins dist via the DMLC_* contract
from mxnet_tpu import dist

rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
n = int(os.environ.get("DMLC_NUM_WORKER", "1"))
assert dist.is_initialized(), "worker did not join the dist job"

total = dist.allreduce_sum(np.full((4,), float(rank + 1), np.float32))
assert float(total[0]) == n * (n + 1) / 2.0, total
got = dist.broadcast_from_root(
    np.full((2,), 5.0 if rank == 0 else -1.0, np.float32))
assert float(got[0]) == 5.0, got

lat = []
for i in range(3):
    t0 = time.perf_counter()
    dist.barrier(f"smoke_{i}")
    lat.append(time.perf_counter() - t0)
print(json.dumps({"evt": "barrier_ok", "rank": rank,
                  "barrier_us": [round(x * 1e6, 1) for x in lat],
                  "t": time.time()}), flush=True)
"""


_TRAIN_WORKER = r"""
'''Deterministic 2-rank dist_sync "fit": seeded params, grads a pure
function of (step, rank, key) so any resumed run retraces the baseline
trajectory bit-for-bit; cooperative sharded checkpoint every PERIOD
steps.'''
import json, os, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import dist
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.checkpoint.state import TrainingState, state_sha256

ckdir, steps, period = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
resume = len(sys.argv) > 4 and sys.argv[4] == "resume"
rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
nranks = int(os.environ.get("DMLC_NUM_WORKER", "1"))

kv = mx.kv.create("dist_sync")
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))

names = ["w0", "w1", "w2", "w3"]
rng = np.random.RandomState(7)
init = {n: rng.normal(size=(16, 4)).astype(np.float32) for n in names}

mgr = CheckpointManager(ckdir, sharded=True, async_save=False,
                        keep_last_n=0, num_shards=4)
start, vals = 0, init
if resume:
    st = mgr.restore()
    if st is not None:
        start = int(st.meta["step"])
        vals = {n: st.arrays[f"param:{n}"] for n in names}
        print(json.dumps({"evt": "resumed", "rank": rank, "step": start,
                          "t": time.time()}), flush=True)
for n in names:
    kv.init(n, mx.nd.array(vals[n]))        # broadcasts rank 0's values

def snap(step):
    arrays = {}
    for n in names:
        out = mx.nd.zeros(init[n].shape)
        kv.pull(n, out=out)
        arrays[f"param:{n}"] = out.asnumpy()
    return TrainingState(arrays=arrays, meta={"step": int(step)})

for step in range(start + 1, steps + 1):
    for i, n in enumerate(names):
        g = (np.cos(0.37 * step * (i + 1) + float(rank))
             * np.ones(init[n].shape, np.float32) * 0.01)
        kv.push(n, mx.nd.array(g))
    print(json.dumps({"evt": "step", "rank": rank, "step": step,
                      "t": time.time()}), flush=True)
    if step % period == 0:
        st = snap(step)
        mgr.save(st, step)
        if rank == 0:
            print(json.dumps({"evt": "commit", "step": step,
                              "sha": state_sha256(st),
                              "t": time.time()}), flush=True)

dist.barrier("selftest_end")
print(json.dumps({"evt": "final", "rank": rank, "step": steps,
                  "sha": state_sha256(snap(steps)), "ok": True,
                  "t": time.time()}), flush=True)
"""


_ELASTIC_WORKER = r"""
'''Gang-size-ELASTIC deterministic trainer: the global gradient each
step is a sum over NSHARDS fixed virtual shards (shard s belongs to
rank s % nranks) of dyadic-rational constants k/2^14 with |k| <= 1024 —
every partial sum is exactly representable in float32, so the cross-
rank allreduce total is bitwise independent of how the shards are
partitioned. The whole trajectory (and every state_sha256) is therefore
identical at ANY gang size, which is what lets the supervisor's
shrink-to-(N-1) restart be held to the N-rank baseline shas.'''
import json, math, os, sys, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import dist
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.checkpoint.state import TrainingState, state_sha256

ckdir, steps, period = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
resume = len(sys.argv) > 4 and sys.argv[4] == "resume"
rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
nranks = int(os.environ.get("DMLC_NUM_WORKER", "1"))
NSHARDS = 12

kv = mx.kv.create("dist_sync")
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))

names = ["w0", "w1", "w2", "w3"]
rng = np.random.RandomState(7)
init = {n: rng.normal(size=(16, 4)).astype(np.float32) for n in names}

mgr = CheckpointManager(ckdir, sharded=True, async_save=False,
                        keep_last_n=0, num_shards=4)
start, vals = 0, init
if resume:
    st = mgr.restore()
    if st is not None:
        start = int(st.meta["step"])
        vals = {n: st.arrays[f"param:{n}"] for n in names}
        print(json.dumps({"evt": "resumed", "rank": rank, "step": start,
                          "t": time.time()}), flush=True)
for n in names:
    kv.init(n, mx.nd.array(vals[n]))        # broadcasts rank 0's values

def snap(step):
    arrays = {}
    for n in names:
        out = mx.nd.zeros(init[n].shape)
        kv.pull(n, out=out)
        arrays[f"param:{n}"] = out.asnumpy()
    return TrainingState(arrays=arrays, meta={"step": int(step)})

for step in range(start + 1, steps + 1):
    for i, n in enumerate(names):
        g = np.float32(0.0)
        for s in range(NSHARDS):
            if s % nranks == rank:
                c = round(math.cos(0.37 * step * (i + 1) + 0.11 * s)
                          * 1024.0) / 16384.0
                g = g + np.float32(c)      # exact: dyadic, |sum| < 1
        kv.push(n, mx.nd.array(np.full(init[n].shape, g, np.float32)))
    print(json.dumps({"evt": "step", "rank": rank, "step": step,
                      "t": time.time()}), flush=True)
    if step % period == 0:
        st = snap(step)
        mgr.save(st, step)
        if rank == 0:
            print(json.dumps({"evt": "commit", "step": step,
                              "sha": state_sha256(st),
                              "t": time.time()}), flush=True)

dist.barrier("selftest_end")
print(json.dumps({"evt": "final", "rank": rank, "step": steps,
                  "sha": state_sha256(snap(steps)), "ok": True,
                  "t": time.time()}), flush=True)
"""


_CRASH_WORKER = r"""
'''Deterministic crash-loop: exits nonzero immediately, every time — no
restart can help, no checkpoint ever seals. The supervisor must burn
its budget and give up with exit 44, never loop forever.'''
import os
print("crash_worker: failing deterministically", flush=True)
os._exit(3)
"""


def _launcher(nprocs, deadline_s, inject=None, retries=0, stream=True,
              extra_env=None):
    env = _base_env()
    if extra_env:
        env.update(extra_env)
    return ClusterLauncher(nprocs=nprocs, deadline_s=deadline_s,
                           dist_timeout_s=_TIMEOUT_S,
                           dist_retries=retries, inject=inject,
                           env=env, stream=stream)


def _trace_env(trace_dir):
    """Arm span tracing in every rank (the launcher arms the flight
    recorder on its own): fast periodic shard/box flushes so a rank
    killed within its first half second of useful work — the barrier
    worker's whole post-import life — still leaves a recent
    trace-rank-K.json and flight-recorder box on disk."""
    return {"MXNET_TRACE": "1", "MXNET_TRACE_DIR": trace_dir,
            "MXNET_TRACE_FLUSH_S": "0.05",
            "MXNET_FLIGHTREC_FLUSH_S": "0.05"}


def _check_postmortem(res, victim, trace_dir, phase, report):
    """Observability acceptance gate for an injected kill/hang: every
    rank left a flight-recorder black box, the launcher's quiet-rank
    triage names the victim, and the per-rank trace shards merge into
    valid chrome-trace JSON whose summary names the victim too."""
    from ..telemetry import tracing
    _check(len(res.blackboxes) >= 2,
           f"{phase}: expected a black box from every rank, got "
           f"{sorted(res.blackboxes)} in {res.blackbox_dir}")
    _check(victim in res.blackboxes,
           f"{phase}: the victim rank {victim} left no black box "
           "(flusher never wrote before the fault)")
    _check(res.quiet_rank == victim,
           f"{phase}: triage named rank {res.quiet_rank} quiet-first, "
           f"expected the injected victim {victim}")
    out, summary = tracing.merge(trace_dir)
    with open(out, encoding="utf-8") as f:
        trace = json.load(f)
    evs = trace.get("traceEvents")
    _check(isinstance(evs, list) and len(evs) > 0,
           f"{phase}: merged trace has no traceEvents list")
    _check(all(isinstance(e, dict) and "ph" in e and "pid" in e
               for e in evs),
           f"{phase}: merged trace carries malformed events")
    _check(all("ts" in e and "dur" in e and "tid" in e
               for e in evs if e.get("ph") == "X"),
           f"{phase}: merged complete-events are missing ts/dur/tid")
    q = summary.get("quiet_first") or {}
    _check(q.get("rank") == victim,
           f"{phase}: merged-timeline summary named rank "
           f"{q.get('rank')} quiet-first, expected {victim}")
    report[f"{phase}_blackboxes"] = len(res.blackboxes)
    report[f"{phase}_merged_events"] = summary["events"]
    report["quiet_rank"] = res.quiet_rank
    print(f"cluster-selftest: {phase} postmortem OK "
          f"({len(res.blackboxes)} black boxes, {summary['events']} "
          f"merged trace events, quiet-first = rank {victim})")


def _no_reap(result, phase):
    _check(not result.deadline_fired,
           f"{phase}: harness deadline reaper fired "
           f"({result.describe()}) — an injected fault hung past every "
           "runtime timeout")


def _survivor_failed(result, victim, phase):
    """Common injected-fault postcondition: the victim is dead by the
    injected means, every survivor exited nonzero on its own with a
    DistRankFailure on record, and nobody needed the deadline reaper."""
    _no_reap(result, phase)
    for rank, rc in enumerate(result.returncodes):
        if rank == victim:
            continue
        _check(rc not in (0, None),
               f"{phase}: surviving rank {rank} exited rc={rc}; "
               "expected a nonzero DistRankFailure exit")
        # when the COORDINATOR (rank 0) is the victim, jax's own
        # coordination client detects the death at the C++ layer and
        # terminates the survivor before Python sees an exception —
        # that is prompt coordinated abort too, just jax's spelling
        _check(rank in result.reaped_ranks
               or "DistRankFailure" in result.tails[rank]
               or "JAX distributed service detected fatal errors"
               in result.tails[rank],
               f"{phase}: rank {rank} log has no DistRankFailure:\n"
               + result.tails[rank][-2000:])


# -- phases ------------------------------------------------------------------

def phase_barrier_roundtrip(nprocs, report):
    res = _launcher(nprocs, deadline_s=60.0).launch_python(
        _BARRIER_WORKER)
    _no_reap(res, "barrier_roundtrip")
    _check(res.ok, "barrier_roundtrip: " + res.describe()
           + "\n" + "".join(res.tails.values())[-2000:])
    evs = [e for e in _events(res) if e["evt"] == "barrier_ok"]
    _check(len(evs) == nprocs, f"barrier_roundtrip: {len(evs)}/{nprocs} "
                               "ranks reported")
    lats = [u for e in evs for u in e["barrier_us"]]
    report["barrier_us_mean"] = round(sum(lats) / len(lats), 1)
    report["barrier_us_max"] = round(max(lats), 1)
    print(f"cluster-selftest: barrier_roundtrip OK "
          f"(mean {report['barrier_us_mean']}us over {len(lats)} waits)")


def phase_kill_pre_barrier(nprocs, report):
    victim = nprocs - 1
    trace_dir = tempfile.mkdtemp(prefix="mxnet_cluster_trace_")
    res = _launcher(nprocs, deadline_s=90.0,
                    inject=f"kill@pre-barrier:{victim}@2",
                    extra_env=_trace_env(trace_dir)).launch_python(
        _BARRIER_WORKER)
    _check(res.returncodes[victim] == -9,
           f"kill_pre_barrier: victim rc={res.returncodes[victim]}, "
           "expected SIGKILL (-9)")
    _survivor_failed(res, victim, "kill_pre_barrier")
    _check(f"missing rank(s): {victim}" in res.tails[0],
           "kill_pre_barrier: survivor did not NAME the dead rank:\n"
           + res.tails[0][-2000:])
    detect = res.exit_s[0] - res.exit_s[victim]
    _check(detect < _TIMEOUT_S + 6.0,
           f"kill_pre_barrier: detection took {detect:.1f}s, expected "
           f"within timeout {_TIMEOUT_S}s (+scheduling margin)")
    report["detect_s"] = round(detect, 2)
    print(f"cluster-selftest: kill_pre_barrier OK "
          f"(DistRankFailure named rank {victim} in {detect:.1f}s)")
    _check_postmortem(res, victim, trace_dir, "kill_pre_barrier", report)


def phase_restart_resume(nprocs, report, check_shas=None):
    """Kill a rank mid-cooperative-commit (2nd commit, step 8): the torn
    step must never seal; a supervisor restart resumes from the last
    sealed commit and finishes. With `check_shas` (the matrix's baseline
    {step: sha}), also prove restored + post-resume hashes match the
    uninterrupted baseline."""
    ckdir = tempfile.mkdtemp(prefix="mxnet_cluster_ck_")
    victim = nprocs - 1
    args = (ckdir, _STEPS, _PERIOD)
    t_run1 = time.time()
    res = _launcher(nprocs, deadline_s=90.0,
                    inject=f"kill@mid-cooperative-commit:{victim}@2",
                    ).launch_python(_TRAIN_WORKER, args)
    _check(res.returncodes[victim] == -9,
           f"restart_resume: victim rc={res.returncodes[victim]}, "
           "expected SIGKILL (-9)")
    _survivor_failed(res, victim, "restart_resume")
    death_wall = t_run1 + (res.first_death_s or res.elapsed_s)

    from ..checkpoint import CheckpointManager
    mgr = CheckpointManager(ckdir, keep_last_n=0)
    sealed = mgr.steps()
    _check(sealed == [_PERIOD],
           f"restart_resume: sealed steps {sealed}, expected only "
           f"[{_PERIOD}] — the torn step-{_TORN_STEP} commit must never "
           "seal")
    if check_shas:
        from ..checkpoint.state import state_sha256
        st = mgr.restore()
        _check(st is not None, "restart_resume: restore() of the last "
                               "sealed commit failed")
        _check(int(st.meta["step"]) == _PERIOD,
               f"restart_resume: restored step {st.meta['step']}")
        got = state_sha256(st)
        _check(got == check_shas[_PERIOD],
               f"restart_resume: restored step-{_PERIOD} sha {got[:12]} "
               f"!= uninterrupted baseline {check_shas[_PERIOD][:12]}")
    mgr.close()

    res2 = _launcher(nprocs, deadline_s=90.0).launch_python(
        _TRAIN_WORKER, (*args, "resume"))
    _no_reap(res2, "restart_resume(2)")
    _check(res2.ok, "restart_resume: restarted run failed: "
           + res2.describe() + "\n"
           + "".join(res2.tails.values())[-2000:])
    evs = _events(res2)
    resumed = [e for e in evs if e["evt"] == "resumed"]
    _check(len(resumed) == nprocs and
           all(e["step"] == _PERIOD for e in resumed),
           f"restart_resume: ranks did not resume from step {_PERIOD}: "
           f"{resumed}")
    finals = [e for e in evs if e["evt"] == "final"]
    _check(len(finals) == nprocs and
           len({e["sha"] for e in finals}) == 1,
           f"restart_resume: final states disagree across ranks: "
           f"{finals}")
    steps_evs = [e for e in evs if e["evt"] == "step"]
    first_step_t = min(e["t"] for e in steps_evs)
    report["mttr_s"] = round(first_step_t - death_wall, 2)
    if check_shas:
        commits = {e["step"]: e["sha"] for e in evs
                   if e["evt"] == "commit"}
        for s in (_TORN_STEP, _STEPS):
            _check(commits.get(s) == check_shas.get(s),
                   f"restart_resume: post-resume commit sha at step {s} "
                   "diverged from the uninterrupted baseline")
    print(f"cluster-selftest: restart_resume OK (resumed from step "
          f"{_PERIOD}, MTTR {report['mttr_s']}s)")
    return ckdir


def phase_baseline_shas(nprocs, report):
    """Uninterrupted 2-rank run: the reference {step: sha} trajectory."""
    ckdir = tempfile.mkdtemp(prefix="mxnet_cluster_base_")
    res = _launcher(nprocs, deadline_s=90.0).launch_python(
        _TRAIN_WORKER, (ckdir, _STEPS, _PERIOD))
    _no_reap(res, "baseline")
    _check(res.ok, "baseline: " + res.describe())
    shas = {e["step"]: e["sha"] for e in _events(res)
            if e["evt"] == "commit"}
    _check(sorted(shas) == [_PERIOD, _TORN_STEP, _STEPS],
           f"baseline: commits at {sorted(shas)}")
    print("cluster-selftest: baseline trajectory recorded "
          f"(commits at {sorted(shas)})")
    return shas


def phase_hang_pre_barrier(nprocs, report):
    """SIGSTOP (not death — a wedged rank): the survivor's barrier
    timeout must fire and the supervisor must reap the frozen rank."""
    victim = nprocs - 1
    trace_dir = tempfile.mkdtemp(prefix="mxnet_cluster_trace_")
    res = _launcher(nprocs, deadline_s=90.0,
                    inject=f"hang@pre-barrier:{victim}@2",
                    extra_env=_trace_env(trace_dir)).launch_python(
        _BARRIER_WORKER)
    _survivor_failed(res, victim, "hang_pre_barrier")
    _check(victim in res.reaped_ranks,
           f"hang_pre_barrier: frozen rank {victim} was not reaped "
           f"({res.describe()})")
    print("cluster-selftest: hang_pre_barrier OK (survivor aborted, "
          "frozen rank reaped)")
    _check_postmortem(res, victim, trace_dir, "hang_pre_barrier", report)


def phase_exit_mid_step(nprocs, report):
    """Abrupt `os._exit(41)` mid-step: the survivor's in-flight
    collective loses its peer and must become DistRankFailure, not a
    hang."""
    from .inject import EXIT_CODE
    victim = nprocs - 1
    ckdir = tempfile.mkdtemp(prefix="mxnet_cluster_exit_")
    res = _launcher(nprocs, deadline_s=90.0,
                    inject=f"exit@mid-step:{victim}@3").launch_python(
        _TRAIN_WORKER, (ckdir, _STEPS, _PERIOD))
    _check(res.returncodes[victim] == EXIT_CODE,
           f"exit_mid_step: victim rc={res.returncodes[victim]}, "
           f"expected {EXIT_CODE}")
    _survivor_failed(res, victim, "exit_mid_step")
    print("cluster-selftest: exit_mid_step OK")


def phase_kill_pre_seal(nprocs, report, baseline_shas):
    """SIGKILL rank 0 pre-seal: the coordination service dies with it;
    survivors must still abort promptly, the torn step must not seal,
    and a restart resumes from the last sealed commit."""
    ckdir = tempfile.mkdtemp(prefix="mxnet_cluster_seal_")
    args = (ckdir, _STEPS, _PERIOD)
    res = _launcher(nprocs, deadline_s=90.0,
                    inject="kill@pre-seal:0@2").launch_python(
        _TRAIN_WORKER, args)
    _check(res.returncodes[0] == -9,
           f"kill_pre_seal: victim rc={res.returncodes[0]}")
    _survivor_failed(res, 0, "kill_pre_seal")
    from ..checkpoint import CheckpointManager
    mgr = CheckpointManager(ckdir, keep_last_n=0)
    _check(mgr.steps() == [_PERIOD],
           f"kill_pre_seal: sealed steps {mgr.steps()}, expected "
           f"[{_PERIOD}]")
    mgr.close()
    res2 = _launcher(nprocs, deadline_s=90.0).launch_python(
        _TRAIN_WORKER, (*args, "resume"))
    _no_reap(res2, "kill_pre_seal(2)")
    _check(res2.ok, "kill_pre_seal: restarted run failed: "
           + res2.describe())
    commits = {e["step"]: e["sha"] for e in _events(res2)
               if e["evt"] == "commit"}
    _check(commits.get(_STEPS) == baseline_shas.get(_STEPS),
           "kill_pre_seal: post-resume final commit sha diverged from "
           "baseline")
    print("cluster-selftest: kill_pre_seal OK (survived losing the "
          "coordinator, resumed, sha matches baseline)")


# -- supervised (self-healing) phases ----------------------------------------

def _supervisor(nprocs, ckdir, inject=None, inject_plan=None,
                min_nprocs=1, allow_shrink=True, max_restarts=3):
    from .supervisor import Supervisor
    return Supervisor(
        source=_ELASTIC_WORKER, args=(ckdir, _STEPS, _PERIOD),
        nprocs=nprocs, min_nprocs=min_nprocs, checkpoint_dir=ckdir,
        inject=inject, inject_plan=inject_plan, max_restarts=max_restarts,
        backoff_s=0.1, allow_shrink=allow_shrink,
        launcher_kwargs=dict(deadline_s=90.0,
                             dist_timeout_s=_SUP_TIMEOUT_S,
                             dist_retries=0, env=_base_env()))


def _check_healed(out, phase, shas, expect_nprocs, commit_steps):
    """Common self-healing postconditions: the supervised run ended ok
    with the harness reaper silent, the final gang has the expected
    size, and every commit the final incarnation sealed matches the
    uninterrupted baseline sha at the same step."""
    _check(out.ok and out.exit_code == 0,
           f"{phase}: supervised run failed: {out.describe()}")
    _check(not any(i["deadline_fired"] for i in out.incarnations),
           f"{phase}: the harness deadline reaper fired during a "
           "supervised incarnation")
    _check(out.final_nprocs == expect_nprocs,
           f"{phase}: final gang size {out.final_nprocs}, expected "
           f"{expect_nprocs}")
    evs = _events(out.results[-1])
    commits = {e["step"]: e["sha"] for e in evs if e["evt"] == "commit"}
    _check(sorted(commits) == sorted(commit_steps),
           f"{phase}: final incarnation sealed {sorted(commits)}, "
           f"expected {sorted(commit_steps)}")
    for s in commits:
        _check(commits[s] == shas.get(s),
               f"{phase}: commit sha at step {s} diverged from the "
               "uninterrupted baseline — recovery broke the trajectory")
    finals = [e for e in evs if e["evt"] == "final"]
    _check(len(finals) == expect_nprocs
           and len({e["sha"] for e in finals}) == 1,
           f"{phase}: final states disagree across ranks: {finals}")
    return evs


def phase_supervised_baseline(nprocs, report):
    """Uninterrupted elastic-worker run: the {step: sha} trajectory
    every supervised recovery (including the shrunk gang) must stay
    on."""
    ckdir = tempfile.mkdtemp(prefix="mxnet_sup_base_")
    res = _launcher(nprocs, deadline_s=90.0).launch_python(
        _ELASTIC_WORKER, (ckdir, _STEPS, _PERIOD))
    _no_reap(res, "supervised_baseline")
    _check(res.ok, "supervised_baseline: " + res.describe()
           + "\n" + "".join(res.tails.values())[-2000:])
    shas = {e["step"]: e["sha"] for e in _events(res)
            if e["evt"] == "commit"}
    _check(sorted(shas) == [_PERIOD, _TORN_STEP, _STEPS],
           f"supervised_baseline: commits at {sorted(shas)}")
    print("cluster-selftest: supervised_baseline recorded "
          f"(commits at {sorted(shas)})")
    return shas


def phase_supervised_recovery(nprocs, report, shas):
    """SIGKILL a non-zero rank mid-cooperative-commit (2nd commit): the
    supervisor must classify the kill, restart in place at N from the
    last sealed commit with NO human step, and land back on the
    baseline sha trajectory. This is the dist_recovery lane's mttr_s."""
    victim = nprocs - 1
    ckdir = tempfile.mkdtemp(prefix="mxnet_sup_rec_")
    out = _supervisor(
        nprocs, ckdir,
        inject=f"kill@mid-cooperative-commit:{victim}@2").run()
    _check(out.restarts_total == 1 and out.shrink_events == 0,
           f"supervised_recovery: {out.describe()}, expected exactly "
           "one restart and no shrink")
    inc0 = out.incarnations[0]
    _check(inc0["victim"] == victim and inc0["kind"] == "kill",
           f"supervised_recovery: classified {inc0}, expected victim "
           f"{victim} killed")
    _check(inc0["decision"] == "restart" and not inc0["coordinator"],
           f"supervised_recovery: decision {inc0['decision']}, expected "
           "restart-in-place")
    _check(inc0["sealed_step"] == _PERIOD,
           f"supervised_recovery: restart point {inc0['sealed_step']}, "
           f"expected the sealed step {_PERIOD} (torn step must never "
           "seal)")
    evs = _check_healed(out, "supervised_recovery", shas, nprocs,
                        (_TORN_STEP, _STEPS))
    resumed = [e for e in evs if e["evt"] == "resumed"]
    _check(len(resumed) == nprocs
           and all(e["step"] == _PERIOD for e in resumed),
           f"supervised_recovery: ranks did not resume from step "
           f"{_PERIOD}: {resumed}")
    _check(out.mttr_s is not None and out.mttr_s < 30.0,
           f"supervised_recovery: implausible mttr_s={out.mttr_s}")
    report["mttr_s"] = round(out.mttr_s, 2)
    report["restarts_total"] = out.restarts_total
    report["shrink_events"] = out.shrink_events
    print(f"cluster-selftest: supervised_recovery OK (victim {victim} "
          f"auto-restarted, MTTR {report['mttr_s']}s)")


def phase_supervised_coordinator(nprocs, report, shas):
    """SIGKILL rank 0 — the coordinator — mid-commit (pre-seal): jax's
    coordination service dies with it, so recovery MUST be a full-gang
    restart; the supervisor classifies the victim as coordinator and
    heals automatically onto the baseline trajectory."""
    ckdir = tempfile.mkdtemp(prefix="mxnet_sup_coord_")
    out = _supervisor(nprocs, ckdir, inject="kill@pre-seal:0@2").run()
    _check(out.restarts_total == 1 and out.shrink_events == 0,
           f"supervised_coordinator: {out.describe()}, expected exactly "
           "one restart and no shrink")
    inc0 = out.incarnations[0]
    _check(inc0["victim"] == 0 and inc0["coordinator"] is True,
           f"supervised_coordinator: classified {inc0}, expected "
           "victim 0 flagged as coordinator")
    _check(inc0["decision"] == "restart",
           f"supervised_coordinator: decision {inc0['decision']}, "
           "expected full-gang restart-in-place")
    evs = _check_healed(out, "supervised_coordinator", shas, nprocs,
                        (_TORN_STEP, _STEPS))
    resumed = [e for e in evs if e["evt"] == "resumed"]
    _check(len(resumed) == nprocs
           and all(e["step"] == _PERIOD for e in resumed),
           f"supervised_coordinator: ranks did not resume from step "
           f"{_PERIOD}: {resumed}")
    report["coordinator_mttr_s"] = (round(out.mttr_s, 2)
                                    if out.mttr_s is not None else None)
    print("cluster-selftest: supervised_coordinator OK (rank-0 death "
          "healed by full-gang restart, MTTR "
          f"{report['coordinator_mttr_s']}s)")


def phase_supervised_shrink(nprocs, report, shas):
    """The same rank dies twice in a row with no progress (injected at
    the FIRST commit both incarnations): repeat offender → the
    supervisor drops its slot and completes at N−1 — and because the
    workload's gradient is gang-size-invariant, the shrunk gang's
    commits still equal the N-rank baseline shas."""
    victim = nprocs - 1
    spec = f"kill@mid-cooperative-commit:{victim}@1"
    ckdir = tempfile.mkdtemp(prefix="mxnet_sup_shrink_")
    out = _supervisor(nprocs, ckdir, inject_plan={0: spec, 1: spec},
                      min_nprocs=nprocs - 1).run()
    decisions = [i["decision"] for i in out.incarnations]
    _check(decisions == ["restart", "shrink", "done"],
           f"supervised_shrink: decisions {decisions}, expected "
           "['restart', 'shrink', 'done']")
    _check(out.shrink_events == 1 and out.restarts_total == 2,
           f"supervised_shrink: {out.describe()}, expected 2 restarts "
           "incl. 1 shrink")
    _check(out.incarnations[1]["victim"] == victim,
           f"supervised_shrink: shrink decision named victim "
           f"{out.incarnations[1]['victim']}, expected {victim}")
    _check_healed(out, "supervised_shrink", shas, nprocs - 1,
                  (_PERIOD, _TORN_STEP, _STEPS))
    report["shrink_events"] = report.get("shrink_events", 0) \
        + out.shrink_events
    print(f"cluster-selftest: supervised_shrink OK (repeat offender "
          f"rank {victim} dropped, N−1={nprocs - 1} gang landed on the "
          "baseline shas)")


def phase_supervised_giveup(report):
    """A deterministic crash loop (every rank exits 3 instantly, nothing
    ever seals) must exhaust the restart budget and end with the
    supervisor's exit 44 — 'needs a human', not an infinite loop."""
    from .supervisor import Supervisor, GIVEUP_EXIT
    sup = Supervisor(source=_CRASH_WORKER, nprocs=2, max_restarts=1,
                     backoff_s=0.05, resume_arg=None,
                     launcher_kwargs=dict(deadline_s=30.0,
                                          failure_grace_s=10.0,
                                          env=_base_env()))
    out = sup.run()
    _check(not out.ok and out.exit_code == GIVEUP_EXIT,
           f"supervised_giveup: {out.describe()}, expected exit "
           f"{GIVEUP_EXIT}")
    _check(out.gave_up and out.restarts_total == 1,
           f"supervised_giveup: {out.describe()}, expected give-up "
           "after exactly max_restarts=1 relaunch")
    report["giveup_exit"] = out.exit_code
    print("cluster-selftest: supervised_giveup OK (crash loop exited "
          f"{GIVEUP_EXIT} after the budget)")


# -- entry points ------------------------------------------------------------

def selftest(nprocs=2, matrix=False, bench=False, supervise=False):
    if not cpu_collectives_available():
        print(json.dumps({"metric": ("dist_recovery" if bench
                                     else "cluster_selftest"),
                          "ok": False,
                          "skipped": "no CPU collectives backend "
                                     "(gloo) in this jaxlib"}))
        return 0            # can't run ≠ broken: report and step aside
    t0 = time.time()
    report = {"metric": "dist_recovery" if bench else "cluster_selftest",
              "nprocs": nprocs}
    try:
        if bench:
            # the dist_recovery lane: detection half (detect_s at N)
            # then the self-healing half (mttr_s / restarts_total
            # through the supervisor, partial-gang survival at N=3)
            phase_barrier_roundtrip(nprocs, report)
            phase_kill_pre_barrier(nprocs, report)
            shas = phase_supervised_baseline(nprocs, report)
            phase_supervised_recovery(nprocs, report, shas)
        elif supervise:
            shas = phase_supervised_baseline(nprocs, report)
            phase_supervised_recovery(nprocs, report, shas)
            phase_supervised_coordinator(nprocs, report, shas)
            phase_supervised_shrink(nprocs, report, shas)
            phase_supervised_giveup(report)
        else:
            phase_barrier_roundtrip(nprocs, report)
            phase_kill_pre_barrier(nprocs, report)
            if matrix:
                shas = phase_baseline_shas(nprocs, report)
                phase_restart_resume(nprocs, report, check_shas=shas)
                phase_hang_pre_barrier(nprocs, report)
                phase_exit_mid_step(nprocs, report)
                phase_kill_pre_seal(nprocs, report, shas)
            else:
                phase_restart_resume(nprocs, report)
    except SelftestFailure as e:
        report.update(ok=False, error=str(e))
        print(json.dumps(report), flush=True)
        return 1
    report.update(ok=True, matrix=bool(matrix),
                  supervise=bool(supervise),
                  elapsed_s=round(time.time() - t0, 1))
    print(json.dumps(report), flush=True)
    return 0


def run_command(nprocs, deadline_s, command, hosts=None, supervise=False,
                checkpoint_dir=None):
    """Launch/supervise an arbitrary command across a gang (localhost by
    default; multi-host with a host spec). With `supervise`, the
    self-healing restart loop wraps the launch."""
    # the launcher scrubs MXNET_CLUSTER_INJECT from rank env unless armed
    # explicitly; honor the operator's env spec on the CLI path
    inject = os.environ.get("MXNET_CLUSTER_INJECT")
    if supervise:
        from .supervisor import Supervisor
        sup = Supervisor(argv=command, nprocs=nprocs, hosts=hosts,
                         checkpoint_dir=checkpoint_dir, inject=inject,
                         launcher_kwargs=dict(deadline_s=deadline_s))
        out = sup.run()
        print(f"cluster: {out.describe()}", file=sys.stderr)
        return out.exit_code
    launcher = ClusterLauncher(nprocs=nprocs, deadline_s=deadline_s,
                               hosts=hosts, inject=inject)
    res = launcher.launch(command)
    print(f"cluster: {res.describe()}", file=sys.stderr)
    if res.ok:
        return 0
    return next((rc for rc in res.returncodes if rc not in (0, None)), 1)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.cluster",
        description="multi-process launch/supervise/fault-inject harness")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--matrix", action="store_true",
                    help="full injection matrix incl. sha-identity proofs")
    ap.add_argument("--supervise", action="store_true",
                    help="with --selftest: the self-healing phase battery "
                         "(N=3); with a command: wrap the launch in the "
                         "auto-restart supervisor")
    ap.add_argument("--bench", action="store_true",
                    help="selftest emitting the dist_recovery JSON line")
    ap.add_argument("-n", "--nprocs", type=int, default=None)
    ap.add_argument("--hosts",
                    help="multi-host gang spec: host1:4,host2:4 "
                         "(default MXNET_CLUSTER_HOSTS)")
    ap.add_argument("--hostfile",
                    help="hostfile path (host[:slots] or 'host slots=N' "
                         "per line)")
    ap.add_argument("--checkpoint-dir",
                    help="sealed-commit dir the supervisor restarts from "
                         "(progress detection for the restart budget)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="wall-clock budget for launched commands")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    hosts = None
    if args.hostfile:
        hosts = read_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_host_spec(args.hosts)
    try:
        env_nprocs = int(os.environ.get("MXNET_CLUSTER_NPROCS", "2"))
    except ValueError:
        env_nprocs = 2
    if args.selftest or args.bench:
        n = args.nprocs or env_nprocs
        # partial-gang survival (shrink, N-1 >= 2) needs at least 3
        n = max(3, n) if (args.supervise or args.bench) else max(2, n)
        return selftest(nprocs=n, matrix=args.matrix, bench=args.bench,
                        supervise=args.supervise)
    if not args.command:
        ap.error("no command given (or pass --selftest)")
    nprocs = args.nprocs if args.nprocs else (None if hosts
                                              else env_nprocs)
    return run_command(nprocs, args.deadline, args.command, hosts=hosts,
                       supervise=args.supervise,
                       checkpoint_dir=args.checkpoint_dir)


if __name__ == "__main__":
    sys.exit(main())
