"""INT8 quantization operators.

Parity target: src/operator/quantization/ (SURVEY.md §2.2 — quantize/
dequantize/requantize, quantized_conv, quantized_fully_connected,
quantized_pooling, quantized_flatten; range math in quantization_utils.h).

TPU-first notes. int8 is the MXU-native low-precision integer path: XLA
lowers int8 x int8 -> int32 `dot_general`/`conv_general_dilated`
(preferred_element_type=int32) straight onto the MXU, so the quantized ops
here are plain jax calls — no assembly kernels, no per-backend variants.
Symmetric (zero-offset) int8 is the default lane, matching the reference's
int8 calibration flow; uint8 in/out is supported in quantize/dequantize for
API parity. Ranges ride through the graph as (min, max) scalar arrays
exactly like the reference's extra op outputs, so the quantized graph stays
a pure dataflow program that XLA fuses.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .registry import Param, register

_INT32_MAX = float(2 ** 31 - 1)


def _t(*outs):
    return tuple(outs)


def _qrange(dtype_str):
    if dtype_str == "int8":
        return 127.0
    if dtype_str == "uint8":
        return 255.0
    if dtype_str == "int32":
        return _INT32_MAX
    raise MXNetError(f"unsupported quantized dtype {dtype_str!r}")


def _float_to_quantized(x, real_range, qrange):
    """Symmetric quantization (quantization_utils.h FloatToQuantized :78):
    sign(x) * min(|x| * scale + 0.5, qrange)."""
    scale = qrange / real_range
    return jnp.sign(x) * jnp.minimum(jnp.abs(x) * scale + 0.5, qrange)


def _quantize(attrs, octx, data, min_range, max_range):
    ot = attrs["out_type"]
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    if ot == "int8":
        real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
        q = jnp.trunc(_float_to_quantized(data, real, 127.0))
        return _t(q.astype(jnp.int8), -real, real)
    elif ot == "uint8":
        # affine uint8 (quantize-inl.h uint8 lane)
        scale = 255.0 / (mx - mn)
        q = jnp.clip((data - mn) * scale + 0.5, 0.0, 255.0)
        return _t(jnp.trunc(q).astype(jnp.uint8), mn, mx)
    raise MXNetError(f"quantize: unsupported out_type {ot!r}")


def _quantize_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = [ds, (1,), (1,)]
    return in_shapes, [ds, (1,), (1,)]


register("_contrib_quantize", _quantize,
         params={"out_type": Param("str", "int8")},
         inputs=("data", "min_range", "max_range"), num_outputs=3,
         infer_shape=_quantize_infer)


def _dequantize(attrs, octx, data, min_range, max_range):
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / 255.0
        return _t(data.astype(jnp.float32) * scale + mn)
    qrange = 127.0 if data.dtype == jnp.int8 else _INT32_MAX
    return _t(data.astype(jnp.float32) * (real / qrange))


def _dequantize_infer(attrs, in_shapes):
    ds = in_shapes[0]
    return [ds, (1,), (1,)], [ds]


register("_contrib_dequantize", _dequantize,
         params={"out_type": Param("str", "float32")},
         inputs=("data", "min_range", "max_range"),
         infer_shape=_dequantize_infer,
         infer_type=lambda attrs, in_types: ["float32"])


def _requantize(attrs, octx, data, min_range, max_range):
    """int32 -> int8. With calib ranges: fixed rescale. Without: the output
    range is the actual min/max of the data (requantize-inl.h online mode)."""
    mn = jnp.reshape(min_range, ())
    mx = jnp.reshape(max_range, ())
    in_real = jnp.maximum(jnp.abs(mn), jnp.abs(mx))
    f = data.astype(jnp.float32) * (in_real / _INT32_MAX)
    if attrs["min_calib_range"] is not None and \
            attrs["max_calib_range"] is not None:
        out_real = max(abs(attrs["min_calib_range"]),
                       abs(attrs["max_calib_range"]))
        out_real = jnp.asarray(out_real, jnp.float32)
    else:
        out_real = jnp.maximum(jnp.max(jnp.abs(f)), 1e-20)
    q = jnp.trunc(_float_to_quantized(f, out_real, 127.0))
    return _t(q.astype(jnp.int8), -out_real, out_real)


def _requantize_infer(attrs, in_shapes):
    ds = in_shapes[0]
    return [ds, (1,), (1,)], [ds, (1,), (1,)]


register("_contrib_requantize", _requantize,
         params={"min_calib_range": Param("float", None),
                 "max_calib_range": Param("float", None)},
         inputs=("data", "min_range", "max_range"), num_outputs=3,
         infer_shape=_requantize_infer,
         infer_type=lambda attrs, in_types: ["int8", "float32", "float32"])


def _mult_range(min_a, max_a, min_b, max_b, qa=127.0, qb=127.0):
    """Output range of int8 x int8 -> int32
    (QuantizationRangeForMultiplication, quantization_utils.h:138)."""
    a_level = jnp.maximum(jnp.abs(min_a), jnp.abs(max_a)) / qa
    b_level = jnp.maximum(jnp.abs(min_b), jnp.abs(max_b)) / qb
    c_level = a_level * b_level
    return -c_level * _INT32_MAX, c_level * _INT32_MAX


def _bias_to_int32(bias, min_bias, max_bias, out_level):
    """Fold an int8 bias into the int32 accumulator scale."""
    b_real = jnp.maximum(jnp.abs(jnp.reshape(min_bias, ())),
                         jnp.abs(jnp.reshape(max_bias, ())))
    f = bias.astype(jnp.float32) * (b_real / 127.0)
    return jnp.round(f / out_level).astype(jnp.int32)


def _quantized_conv(attrs, octx, data, weight, *rest):
    no_bias = attrs["no_bias"]
    if no_bias:
        bias = None
        min_d, max_d, min_w, max_w = rest
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    ns = len(attrs["kernel"])
    stride = tuple(attrs["stride"] or (1,) * ns)
    dilate = tuple(attrs["dilate"] or (1,) * ns)
    pad = tuple(attrs["pad"] or (0,) * ns)
    specs = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
             3: ("NCDHW", "OIDHW", "NCDHW")}
    out = jax.lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=specs[ns],
        feature_group_count=attrs["num_group"],
        preferred_element_type=jnp.int32)
    mn_d = jnp.reshape(min_d, ())
    mx_d = jnp.reshape(max_d, ())
    mn_w = jnp.reshape(min_w, ())
    mx_w = jnp.reshape(max_w, ())
    min_o, max_o = _mult_range(mn_d, mx_d, mn_w, mx_w)
    if bias is not None:
        out_level = max_o / _INT32_MAX
        b32 = _bias_to_int32(bias, min_b, max_b, out_level)
        out = out + b32.reshape((1, -1) + (1,) * ns)
    return _t(out, min_o, max_o)


def _qlinear_inputs(attrs):
    """Input names shared by quantized conv and FC (quantized_conv.cc:120,
    quantized_fully_connected.cc:95): data/weight[/bias] + their ranges."""
    if attrs["no_bias"]:
        return ["data", "weight", "min_data", "max_data", "min_weight",
                "max_weight"]
    return ["data", "weight", "bias", "min_data", "max_data", "min_weight",
            "max_weight", "min_bias", "max_bias"]


_qconv_inputs = _qlinear_inputs


def _qconv_infer(attrs, in_shapes):
    from .nn import _conv_out_dim
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None, (1,), (1,)]
    nf = attrs["num_filter"]
    k = attrs["kernel"]
    ns = len(k)
    stride = tuple(attrs["stride"] or (1,) * ns)
    dilate = tuple(attrs["dilate"] or (1,) * ns)
    pad = tuple(attrs["pad"] or (0,) * ns)
    in_shapes = list(in_shapes)
    if in_shapes[1] is None:
        in_shapes[1] = (nf, ds[1] // attrs["num_group"]) + tuple(k)
    names = _qconv_inputs(attrs)
    for i, nm in enumerate(names):
        if i >= 2 and in_shapes[i] is None:
            in_shapes[i] = (nf,) if nm == "bias" else (1,)
    spatial = tuple(_conv_out_dim(d, kk, s, p, dl) for d, kk, s, p, dl in
                    zip(ds[2:], k, stride, pad, dilate))
    return in_shapes, [(ds[0], nf) + spatial, (1,), (1,)]


_qconv_schema = register(
    "_contrib_quantized_conv", _quantized_conv,
    params={"kernel": Param("shape", None, True),
            "stride": Param("shape", None),
            "dilate": Param("shape", None),
            "pad": Param("shape", None),
            "num_filter": Param("int", None, True),
            "num_group": Param("int", 1),
            "no_bias": Param("bool", False),
            "workspace": Param("int", 1024),
            "cudnn_tune": Param("str", None),
            "cudnn_off": Param("bool", False),
            "layout": Param("str", None)},
    inputs=("data", "weight", "bias", "min_data", "max_data", "min_weight",
            "max_weight", "min_bias", "max_bias"),
    num_outputs=3, infer_shape=_qconv_infer,
    infer_type=lambda attrs, in_types: ["int32", "float32", "float32"])
_qconv_schema.list_inputs = _qconv_inputs  # type: ignore
_qconv_schema.num_inputs = lambda attrs: len(_qconv_inputs(attrs))  # type: ignore


def _quantized_fc(attrs, octx, data, weight, *rest):
    no_bias = attrs["no_bias"]
    if no_bias:
        bias = None
        min_d, max_d, min_w, max_w = rest
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    x = data.reshape(data.shape[0], -1) if attrs["flatten"] else data
    out = jax.lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    min_o, max_o = _mult_range(jnp.reshape(min_d, ()), jnp.reshape(max_d, ()),
                               jnp.reshape(min_w, ()), jnp.reshape(max_w, ()))
    if bias is not None:
        out_level = max_o / _INT32_MAX
        b32 = _bias_to_int32(bias, min_b, max_b, out_level)
        out = out + b32
    return _t(out, min_o, max_o)


_qfc_inputs = _qlinear_inputs


def _qfc_infer(attrs, in_shapes):
    ds = in_shapes[0]
    nh = attrs["num_hidden"]
    if ds is None:
        return in_shapes, [None, (1,), (1,)]
    in_shapes = list(in_shapes)
    if in_shapes[1] is None:
        in_dim = 1
        for d in ds[1:]:
            in_dim *= d
        in_shapes[1] = (nh, in_dim if attrs["flatten"] else ds[-1])
    names = _qfc_inputs(attrs)
    for i, nm in enumerate(names):
        if i >= 2 and in_shapes[i] is None:
            in_shapes[i] = (nh,) if nm == "bias" else (1,)
    out = (ds[0], nh) if attrs["flatten"] else tuple(ds[:-1]) + (nh,)
    return in_shapes, [out, (1,), (1,)]


_qfc_schema = register(
    "_contrib_quantized_fully_connected", _quantized_fc,
    params={"num_hidden": Param("int", None, True),
            "no_bias": Param("bool", False),
            "flatten": Param("bool", True)},
    inputs=("data", "weight", "bias", "min_data", "max_data", "min_weight",
            "max_weight", "min_bias", "max_bias"),
    num_outputs=3, infer_shape=_qfc_infer,
    infer_type=lambda attrs, in_types: ["int32", "float32", "float32"])
_qfc_schema.list_inputs = _qfc_inputs  # type: ignore
_qfc_schema.num_inputs = lambda attrs: len(_qfc_inputs(attrs))  # type: ignore


def _quantized_pooling(attrs, octx, data, min_data, max_data):
    from .nn import _pooling
    # pool in int32, return to int8: max-pool is exact; avg-pool rounds
    f = _pooling(attrs, octx, data.astype(jnp.float32))[0]
    if attrs["pool_type"] == "avg":
        f = jnp.round(f)
    q = jnp.clip(f, -127, 127).astype(jnp.int8)
    return _t(q, jnp.reshape(min_data, ()), jnp.reshape(max_data, ()))


def _qpool_infer(attrs, in_shapes):
    from .nn import _pool_infer
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None, (1,), (1,)]
    _, outs = _pool_infer(attrs, [ds])
    return [ds, (1,), (1,)], [outs[0], (1,), (1,)]


register("_contrib_quantized_pooling", _quantized_pooling,
         params={"kernel": Param("shape", ()),
                 "pool_type": Param("str", "max"),
                 "global_pool": Param("bool", False),
                 "stride": Param("shape", None),
                 "pad": Param("shape", None),
                 "pooling_convention": Param("str", "valid"),
                 "count_include_pad": Param("bool", True),
                 "cudnn_off": Param("bool", False)},
         inputs=("data", "min_data", "max_data"), num_outputs=3,
         infer_shape=_qpool_infer,
         infer_type=lambda attrs, in_types: ["int8", "float32", "float32"])


def _quantized_flatten(attrs, octx, data, min_data, max_data):
    return _t(data.reshape(data.shape[0], -1), jnp.reshape(min_data, ()),
              jnp.reshape(max_data, ()))


def _qflatten_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None, (1,), (1,)]
    flat = 1
    for d in ds[1:]:
        flat *= d
    return [ds, (1,), (1,)], [(ds[0], flat), (1,), (1,)]


register("_contrib_quantized_flatten", _quantized_flatten,
         inputs=("data", "min_data", "max_data"), num_outputs=3,
         infer_shape=_qflatten_infer,
         infer_type=lambda attrs, in_types: [in_types[0], "float32",
                                             "float32"])


# -- weight-only quantization (decode/serving bandwidth path) ---------------
#
# The ops above mirror the reference's activation+weight int8 graph rewrite
# (int8 x int8 -> int32 on the MXU). Decode serving wants something simpler
# and strictly bandwidth-motivated: weights stored narrow (int8 / fp8
# e4m3), activations left in bf16/fp32, dequant fused INTO the matmul so
# the wide weight tensor never exists in HBM. Per-OUTPUT-channel symmetric
# scales keep the error per channel; because the scale is constant along
# the contraction axis it factors out of the dot —
#     x @ (q * s[None, :]) == (x @ q_wide) * s
# — which is exactly the algebra both consumers below rely on.

_WEIGHT_QDTYPES = ("int8", "fp8")


def _fp8_dtype():
    """float8_e4m3fn when this jax build has it (e4m3: decode wants the
    mantissa, matching parallel/zero.py's wire-dtype choice); None
    disables the fp8 lane rather than silently aliasing to bf16 — a
    "quantized" artifact must actually be narrow."""
    return getattr(jnp, "float8_e4m3fn", None)


def quantize_rows(w, dtype="int8"):
    """Per-output-channel symmetric weight quantization.

    w: (..., K, N) float array; the LAST axis is the output-feature axis.
    Returns (q, scale): q is int8 (or fp8 e4m3) with the same shape,
    scale is (N,) float32 with w ~= q.astype(f32) * scale. Channels that
    are entirely zero get scale 1.0 (q is zero there either way).
    """
    w = _np.asarray(w, _np.float32)
    if w.ndim < 2:
        raise MXNetError("quantize_rows: need a matrix (ndim >= 2), got "
                         f"shape {w.shape}")
    amax = _np.max(_np.abs(w), axis=tuple(range(w.ndim - 1)))
    if dtype == "int8":
        scale = _np.where(amax > 0, amax / 127.0, 1.0).astype(_np.float32)
        q = _np.clip(_np.rint(w / scale), -127, 127).astype(_np.int8)
    elif dtype == "fp8":
        f8 = _fp8_dtype()
        if f8 is None:
            raise MXNetError("quantize_rows: this jax build has no "
                             "float8_e4m3fn — use dtype='int8'")
        # e4m3fn max finite value is 448
        scale = _np.where(amax > 0, amax / 448.0, 1.0).astype(_np.float32)
        q = _np.asarray(jnp.asarray(w / scale).astype(f8))
    else:
        raise MXNetError(f"quantize_rows: dtype must be one of "
                         f"{_WEIGHT_QDTYPES}, got {dtype!r}")
    return q, scale


def dequantize_rows(q, scale):
    """Inverse of quantize_rows (the oracle the fused matmul is tested
    against): wide float32 weights."""
    return _np.asarray(q, _np.float32) * _np.asarray(scale, _np.float32)


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, *, block_k, k_dim):
    """One (m-block, n-block) grid cell of the fused quantized matmul:
    stream K-blocks of the NARROW weight, widen in VMEM, MXU dot with
    fp32 accumulation, one per-channel scale multiply at the end (the
    scale factors out of the contraction)."""
    acc0 = jnp.zeros((x_ref.shape[0], o_ref.shape[1]), jnp.float32)
    n_blocks = k_dim // block_k

    def body(i, acc):
        import jax.experimental.pallas as pl
        xk = x_ref[:, pl.dslice(i * block_k, block_k)]
        qk = q_ref[pl.dslice(i * block_k, block_k), :]
        return acc + jax.lax.dot_general(
            xk, qk.astype(xk.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc = jax.lax.fori_loop(0, n_blocks, body, acc0)
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _qmm_block(dim, prefs=(256, 128, 8)):
    for blk in prefs:
        if dim % blk == 0:
            return blk
    return dim


def _qmm_pallas(x, q, scale, interpret=False):
    import functools
    import jax.experimental.pallas as pl
    m, k = x.shape
    n = q.shape[1]
    block_m = _qmm_block(m)
    block_n = _qmm_block(n, (512, 256, 128))
    block_k = _qmm_block(k, (512, 256, 128))
    kernel = functools.partial(_qmm_kernel, block_k=block_k, k_dim=k)
    return pl.pallas_call(
        kernel,
        grid=(m // block_m, n // block_n),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda mi, ni: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda mi, ni: (0, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda mi, ni: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x, q, scale.reshape(1, n))


def _qmm_eligible(x, q, platform=None):
    if x.ndim != 2 or q.ndim != 2:
        return False
    m, k = x.shape
    n = q.shape[1]
    if k % 128 or n % 128:
        return False
    if platform is not None:
        return platform == "tpu"
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def quantized_matmul(x, q, scale, force=None, platform=None):
    """x @ dequant(q, scale) without materializing the wide weight.

    x: (..., K) activations (bf16/f32); q: (K, N) int8 or fp8 weights;
    scale: (N,) per-output-channel float32. On TPU (tile-friendly K/N)
    a Pallas kernel widens weight blocks in VMEM and fuses the scale
    into the epilogue; elsewhere the XLA spelling
    ``dot(x, q.astype(x.dtype)) * scale`` is used — XLA fuses the
    narrow->wide convert into the dot fusion, so the HLO still reads the
    s8/f8 buffer (hloaudit's fit_decode audit pins this).

    force: None (auto) | 'pallas' | 'xla' | 'interpret'.
    """
    if q.ndim != 2 or x.shape[-1] != q.shape[0]:
        raise MXNetError(f"quantized_matmul: x {x.shape} @ q {q.shape}")
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    use_pallas = (force in ("pallas", "interpret") or
                  (force is None and _qmm_eligible(x2, q, platform)))
    if use_pallas:
        out = _qmm_pallas(x2, q, scale, interpret=force == "interpret")
    else:
        out = jax.lax.dot_general(
            x2, q.astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = (out * scale.astype(jnp.float32)).astype(x.dtype)
    return out.reshape(lead + (q.shape[1],))
