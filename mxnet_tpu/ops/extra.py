"""Long-tail operators closing named gaps against the reference registry.

Each op cites its reference registration site. These are the remaining
`NNVM_REGISTER_OP`/`MXNET_REGISTER_OP_PROPERTY` names after the core
tensor/nn/contrib/quantization families; legacy _v1 ops and backend-
specific names are registered as aliases of their modern twins (the _v1
kernels differ only in implementation, not semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .registry import Param, register, register_alias, get_op, _REGISTRY


def _t(*o):
    return tuple(o)


# ---------------------------------------------------------------------------
# softmax_cross_entropy (src/operator/loss_binary_op.cc)
# ---------------------------------------------------------------------------

def _softmax_cross_entropy(attrs, octx, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    li = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, li[:, None], axis=1)[:, 0]
    return _t(-jnp.sum(picked))


register("softmax_cross_entropy", _softmax_cross_entropy,
         inputs=("data", "label"),
         infer_shape=lambda attrs, s: ([s[0], (s[0][0],) if s[0] else s[1]],
                                       [(1,)]))


# ---------------------------------------------------------------------------
# linalg tail: gelqf (LQ factorization), syevd (symmetric eigendecomposition)
# (src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------

def _linalg_gelqf(attrs, octx, a):
    # LQ of a (wide) matrix: A = L @ Q with Q orthonormal rows — computed
    # from the QR of A^T (jnp.linalg.qr is the XLA-native factorization)
    qt, rt = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    q = jnp.swapaxes(qt, -1, -2)
    l = jnp.swapaxes(rt, -1, -2)
    # sign convention: diag(L) >= 0 (LAPACK gelqf parity)
    d = jnp.sign(jnp.diagonal(l, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, 1.0, d)
    l = l * d[..., None, :]
    q = q * d[..., :, None]
    return _t(l, q)


def _gelqf_infer(attrs, in_shapes):
    s = in_shapes[0]
    if s is None:
        return in_shapes, [None, None]
    m = s[-2]
    return in_shapes, [tuple(s[:-1]) + (m,), tuple(s)]


register("_linalg_gelqf", _linalg_gelqf, inputs=("A",), num_outputs=2,
         infer_shape=_gelqf_infer, aliases=("linalg_gelqf",))


def _linalg_syevd(attrs, octx, a):
    w, u = jnp.linalg.eigh(a)
    # reference returns (U, L): rows of U are eigenvectors
    return _t(jnp.swapaxes(u, -1, -2), w)


def _syevd_infer(attrs, in_shapes):
    s = in_shapes[0]
    if s is None:
        return in_shapes, [None, None]
    return in_shapes, [tuple(s), tuple(s[:-1])]


register("_linalg_syevd", _linalg_syevd, inputs=("A",), num_outputs=2,
         infer_shape=_syevd_infer, aliases=("linalg_syevd",))


# ---------------------------------------------------------------------------
# image ops (src/operator/image/image_random.cc): to_tensor, normalize
# ---------------------------------------------------------------------------

def _image_to_tensor(attrs, octx, data):
    # HWC uint8 [0,255] -> CHW float32 [0,1]
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return _t(jnp.transpose(x, (2, 0, 1)))
    return _t(jnp.transpose(x, (0, 3, 1, 2)))


def _to_tensor_infer(attrs, in_shapes):
    s = in_shapes[0]
    if s is None:
        return in_shapes, [None]
    if len(s) == 3:
        return in_shapes, [(s[2], s[0], s[1])]
    return in_shapes, [(s[0], s[3], s[1], s[2])]


register("_image_to_tensor", _image_to_tensor, inputs=("data",),
         infer_shape=_to_tensor_infer, aliases=("image_to_tensor",))


def _image_normalize(attrs, octx, data):
    mean = jnp.asarray(attrs["mean"], data.dtype)
    std = jnp.asarray(attrs["std"], data.dtype)
    shape = (-1, 1, 1) if data.ndim == 3 else (1, -1, 1, 1)
    return _t((data - mean.reshape(shape)) / std.reshape(shape))


register("_image_normalize", _image_normalize,
         params={"mean": Param("floats", (0.0,)),
                 "std": Param("floats", (1.0,))},
         inputs=("data",), aliases=("image_normalize",))


# ---------------------------------------------------------------------------
# mutation ops backing __setitem__ (src/operator/tensor/matrix_op.cc
# _slice_assign, indexing_op.cc _scatter_set_nd)
# ---------------------------------------------------------------------------

def _slice_params():
    return {"begin": Param("shape", None, True),
            "end": Param("shape", None, True),
            "step": Param("shape", None)}


def _norm_slices(attrs, shape):
    begin, end = attrs["begin"], attrs["end"]
    step = attrs["step"] or (1,) * len(begin)
    out = []
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else 1
        out.append(slice(b, e, s if s != 0 else None))
    return tuple(out)


def _slice_assign(attrs, octx, lhs, rhs):
    return _t(lhs.at[_norm_slices(attrs, lhs.shape)].set(rhs))


register("_slice_assign", _slice_assign, params=_slice_params(),
         inputs=("lhs", "rhs"),
         infer_shape=lambda attrs, s: (s, [s[0]]))


def _slice_assign_scalar(attrs, octx, data):
    return _t(data.at[_norm_slices(attrs, data.shape)].set(
        attrs["scalar"]))


register("_slice_assign_scalar", _slice_assign_scalar,
         params={**_slice_params(), "scalar": Param("float", 0.0)},
         inputs=("data",),
         infer_shape=lambda attrs, s: (s, [s[0]]))


def _scatter_set_nd(attrs, octx, lhs, rhs, indices):
    idx = tuple(indices.astype(jnp.int32))
    return _t(lhs.at[idx].set(rhs))


register("_scatter_set_nd", _scatter_set_nd,
         params={"shape": Param("shape", None)},
         inputs=("lhs", "rhs", "indices"),
         infer_shape=lambda attrs, s: (s, [s[0]]))


# ---------------------------------------------------------------------------
# sparse-facade tail (dense-backed per SURVEY §7 stage 11)
# ---------------------------------------------------------------------------

def _cast_storage(attrs, octx, data):
    # dense-backed sparse: storage casts are identity on the buffer; stype
    # bookkeeping lives on the NDArray wrapper (ndarray/sparse.py tostype)
    return _t(data)


register("cast_storage", _cast_storage,
         params={"stype": Param("str", None, True)}, inputs=("data",))


def _sparse_retain(attrs, octx, data, indices):
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), bool).at[idx].set(True)
    keep_shape = (-1,) + (1,) * (data.ndim - 1)
    return _t(jnp.where(mask.reshape(keep_shape), data, 0))


register("_sparse_retain", _sparse_retain, inputs=("data", "indices"),
         infer_shape=lambda attrs, s: (s, [s[0]]))


def _sparse_adagrad_update(attrs, octx, weight, grad, history):
    # dense execution of the rowwise-sparse AdaGrad update
    # (optimizer_op.cc _sparse_adagrad_update); grads are dense here so the
    # update touches every row — numerically identical when grads are dense
    if attrs["wd"] != 0.0:
        # reference hard-fails too (optimizer_op-inl.h:1747
        # "sparse adagrad_update does not support wd")
        raise MXNetError("_sparse_adagrad_update does not support wd != 0")
    lr = attrs["lr"]
    eps = attrs["epsilon"]
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] is not None and attrs["clip_gradient"] > 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    new_hist = history + jnp.square(g)
    new_w = weight - lr * g / (jnp.sqrt(new_hist) + eps)
    return _t(new_w, new_hist)


register("_sparse_adagrad_update", _sparse_adagrad_update,
         params={"lr": Param("float", None, True),
                 "epsilon": Param("float", 1e-7),
                 "wd": Param("float", 0.0),
                 "rescale_grad": Param("float", 1.0),
                 "clip_gradient": Param("float", -1.0)},
         inputs=("weight", "grad", "history"), num_outputs=1,
         aux=("history",), mutates_aux=True, aux_always=True)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg (src/operator/identity_attach_KL_sparse_reg.cc):
# identity forward; backward adds the KL-sparseness penalty gradient
# ---------------------------------------------------------------------------

def _identity_kl_sparse_reg(attrs, octx, data, moving_avg):
    """Identity forward; backward adds penalty * d/drho KL(s || rho) with
    rho the MOMENTUM-smoothed batch-mean activation kept in the
    `moving_avg` aux state — matching identity_attach_KL_sparse_reg-inl.h
    (EMA aux, per-element addition, no batch-size division)."""
    penalty = attrs["penalty"]
    sparseness = attrs["sparseness_target"]
    momentum = attrs["momentum"]

    rho = jnp.mean(data, axis=0)
    new_avg = momentum * moving_avg + (1 - momentum) *         jax.lax.stop_gradient(rho) if octx.is_train else moving_avg

    @jax.custom_vjp
    def fn(x, avg):
        return x

    def fwd(x, avg):
        return x, avg

    def bwd(avg, g):
        a = jnp.clip(avg, 1e-6, 1 - 1e-6)
        kl_grad = penalty * (-sparseness / a + (1 - sparseness) / (1 - a))
        return (g + kl_grad[None, :], jnp.zeros_like(avg))

    fn.defvjp(fwd, bwd)
    return _t(fn(data, new_avg), new_avg)


def _kl_reg_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = list(in_shapes)
    if ds is not None and in_shapes[1] is None:
        in_shapes[1] = (ds[-1],)
    return in_shapes, [ds]


register("IdentityAttachKLSparseReg", _identity_kl_sparse_reg,
         params={"sparseness_target": Param("float", 0.1),
                 "penalty": Param("float", 0.001),
                 "momentum": Param("float", 0.9)},
         inputs=("data", "moving_avg"), aux=("moving_avg",),
         mutates_aux=True, infer_shape=_kl_reg_infer)


# ---------------------------------------------------------------------------
# graph-internal / placement ops
# ---------------------------------------------------------------------------

def _cross_device_copy(attrs, octx, data):
    # placement is the executor's job (group2ctx -> eager segmented run);
    # inside a single program this is the identity
    return _t(data)


register("_CrossDeviceCopy", _cross_device_copy, inputs=("data",))


def _identity_with_attr_like_rhs(attrs, octx, lhs, rhs):
    return _t(lhs)


register("_identity_with_attr_like_rhs", _identity_with_attr_like_rhs,
         inputs=("lhs", "rhs"),
         infer_shape=lambda attrs, s: (s, [s[0]]))


# ---------------------------------------------------------------------------
# legacy _v1 / backend-specific names -> modern twins
# ---------------------------------------------------------------------------




register_alias("Convolution_v1", "Convolution")
register_alias("Pooling_v1", "Pooling")
register_alias("BatchNorm_v1", "BatchNorm")
register_alias("CuDNNBatchNorm", "BatchNorm")
register_alias("_contrib_SparseEmbedding", "Embedding")
register_alias("_add", "elemwise_add")
register_alias("_sub", "elemwise_sub")
register_alias("_mod", "broadcast_mod")
register_alias("_Mod", "broadcast_mod")
register_alias("_Maximum", "broadcast_maximum")
register_alias("_Minimum", "broadcast_minimum")
register_alias("_Hypot", "broadcast_hypot")
register_alias("_Greater_Equal", "broadcast_greater_equal")
register_alias("_Lesser_Equal", "broadcast_lesser_equal")
register_alias("_Logical_And", "broadcast_logical_and")
register_alias("_Logical_Or", "broadcast_logical_or")
register_alias("_Logical_Xor", "broadcast_logical_xor")
register_alias("_LogicalAndScalar", "_logical_and_scalar")
register_alias("_LogicalOrScalar", "_logical_or_scalar")
register_alias("_LogicalXorScalar", "_logical_xor_scalar")
# Crop-assign legacy names (src/operator/tensor/matrix_op.cc add_alias)
register_alias("_crop_assign", "_slice_assign")
register_alias("_crop_assign_scalar", "_slice_assign_scalar")
# Sparse-storage scatter variants: dense-backed storage makes these the
# plain elementwise ops (stored rows == all rows)
register_alias("_scatter_plus_scalar", "_plus_scalar")
register_alias("_scatter_minus_scalar", "_minus_scalar")
register_alias("_scatter_elemwise_div", "elemwise_div")
register_alias("_sparse_cast_storage", "cast_storage")
register_alias("_sparse_dot", "dot")
register_alias("_sparse_zeros_like", "zeros_like")
