"""Operator registry — the TPU-native analog of the NNVM op registry.

Reference model (SURVEY.md §2.2): every op registers FInferShape/FInferType/
FCompute<cpu|gpu> attributes (include/mxnet/op_attr_types.h:183-268) and is
dispatched through the dependency engine. Here an op is a *pure jax-traceable
function* plus typed parameter schema and (optional) backward shape inference:

  - `fcompute(attrs, octx, *inputs) -> tuple of jnp arrays` is traced by XLA;
    gradients come from jax.vjp — no hand-written _backward_* ops, except where
    the reference defines a *semantically different* backward (SoftmaxOutput,
    MakeLoss), which use jax.custom_vjp inside fcompute.
  - `infer_shape(attrs, in_shapes) -> (in_shapes, out_shapes)` fills unknown
    input shapes (None entries) so `simple_bind` can derive weight shapes from
    the data shape, exactly like FInferShape's bidirectional contract. Ops
    without one fall back to jax.eval_shape (forward-only inference).

Parsed attrs are *static* arguments: each (op, attrs, is_train) triple maps to
one jit-compiled XLA executable, cached by jax on input avals.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as _np

from ..base import (MXNetError, parse_bool, parse_float, parse_int,
                    parse_shape)

__all__ = ["Param", "OpSchema", "OpCtx", "register", "register_alias",
           "get_op", "list_ops", "AttrDict"]


def _parse_floats(v):
    """Tuple-of-float attr ((1.0, 2.0), "[1,2]", 0.5 -> tuple of float) —
    role of nnvm::Tuple<float> params (sizes/ratios/variances)."""
    if isinstance(v, (int, float, _np.floating, _np.integer)):
        return (float(v),)
    if isinstance(v, str):
        import ast
        v = ast.literal_eval(v.strip())
        if not isinstance(v, (tuple, list)):
            return (float(v),)
    return tuple(float(x) for x in v)


_PARSERS = {
    "int": parse_int,
    "float": parse_float,
    "bool": parse_bool,
    "str": lambda v: str(v),
    "shape": parse_shape,
    "floats": _parse_floats,
    "dtype": lambda v: v if isinstance(v, str) else _np.dtype(v).name,
    "any": lambda v: v,
}


@dataclasses.dataclass
class Param:
    """Typed op parameter (role of a dmlc::Parameter field)."""
    type: str = "any"
    default: object = None
    required: bool = False

    def parse(self, v):
        if v is None:
            return None
        return _PARSERS[self.type](v)


class AttrDict(dict):
    """Parsed-attr dict, attribute access + hashable freeze for jit cache keys."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def frozen(self):
        return tuple(sorted((k, _freeze(v)) for k, v in self.items()))


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


@dataclasses.dataclass
class OpCtx:
    """Per-invocation execution context handed to fcompute.

    `is_train` is static (affects tracing: dropout/BN branches); `rng` is a
    traced jax PRNG key array for ops with needs_rng=True. This is the analog
    of OpContext (include/mxnet/op_attr_types.h:64-85) minus streams, which
    XLA owns.
    """
    is_train: bool = False
    rng: object = None
    # target platform ("cpu"/"tpu") when the caller compiles for a specific
    # device — backend-specialized ops (pallas kernels) must not key off
    # jax.default_backend(), which may differ from the jit target
    platform: str = None


@dataclasses.dataclass
class OpSchema:
    name: str
    fcompute: Callable
    params: dict
    # input names in order; auxiliary-state inputs (e.g. BN moving stats) are
    # listed too and flagged by aux_indices (MXNet ListAuxiliaryStates model)
    input_names: Sequence[str]
    num_outputs: int = 1
    aux_indices: Sequence[int] = ()
    # if True, fcompute returns num_outputs + len(aux_indices) arrays; the
    # trailing ones are updated aux values written back by the caller
    mutates_aux: bool = False
    # aux writeback normally happens only under is_train (BatchNorm moving
    # stats); optimizer update ops mutate their state inputs unconditionally
    # (reference marks them TakeParamAsInput/mutable, optimizer_op.cc)
    aux_always: bool = False
    needs_rng: bool = False
    # variadic ops (Concat, add_n): attr naming the input count
    key_var_num_args: Optional[str] = None
    infer_shape: Optional[Callable] = None
    # dtype of outputs when not simply inputs' common dtype
    infer_type: Optional[Callable] = None
    # aliases under which this op is also exposed (e.g. snake_case)
    aliases: Sequence[str] = ()

    def parse_attrs(self, kwargs) -> AttrDict:
        out = AttrDict()
        for k, p in self.params.items():
            if k in kwargs and kwargs[k] is not None:
                out[k] = p.parse(kwargs[k])
            elif p.required:
                raise MXNetError(f"op {self.name}: required param {k!r} missing")
            else:
                out[k] = p.default
        unknown = set(kwargs) - set(self.params)
        # MXNet tolerates and round-trips unknown attrs on symbols; we keep
        # string extras out of the static attr set but don't hard error on
        # the conventional ones.
        unknown -= {"name", "attr", "out", "dtype_hint", "__layout__"}
        if unknown:
            raise MXNetError(f"op {self.name}: unknown params {sorted(unknown)}")
        return out

    def num_inputs(self, attrs) -> int:
        if self.key_var_num_args:
            return int(attrs[self.key_var_num_args])
        return len(self.input_names)

    def list_inputs(self, attrs):
        if self.key_var_num_args:
            n = int(attrs[self.key_var_num_args])
            base = self.input_names[0] if self.input_names else "arg"
            return [f"{base}{i}" for i in range(n)]
        return list(self.input_names)


_REGISTRY: dict = {}


def register(name, fcompute, *, params=None, inputs=("data",), num_outputs=1,
             aux=(), mutates_aux=False, aux_always=False, needs_rng=False,
             key_var_num_args=None, infer_shape=None, infer_type=None,
             aliases=()):
    """Register an operator. `aux` is a list of input names that are auxiliary
    states. Returns the OpSchema."""
    params = {k: (v if isinstance(v, Param) else Param(*v) if isinstance(v, tuple)
                  else Param(default=v)) for k, v in (params or {}).items()}
    inputs = list(inputs)
    aux_idx = tuple(inputs.index(a) for a in aux)
    schema = OpSchema(name=name, fcompute=fcompute, params=params,
                      input_names=inputs, num_outputs=num_outputs,
                      aux_indices=aux_idx, mutates_aux=mutates_aux,
                      aux_always=aux_always,
                      needs_rng=needs_rng, key_var_num_args=key_var_num_args,
                      infer_shape=infer_shape, infer_type=infer_type,
                      aliases=tuple(aliases))
    for n in (name, *aliases):
        if n in _REGISTRY:
            raise MXNetError(f"op {n!r} already registered")
        _REGISTRY[n] = schema
    return schema


def register_alias(alias, name):
    """Expose an already-registered op under an additional public name
    (role of nnvm ``.add_alias``; e.g. legacy CamelCase / sparse names).
    Unknown targets and clashes with a DIFFERENT op raise; re-aliasing to
    the same op is a no-op."""
    schema = get_op(name)
    existing = _REGISTRY.get(alias)
    if existing is not None:
        if existing is schema:
            return schema
        raise MXNetError(f"op {alias!r} already registered to "
                         f"{existing.name!r}")
    _REGISTRY[alias] = schema
    return schema


def get_op(name) -> OpSchema:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} not registered") from None


def list_ops():
    return sorted(set(s.name for s in _REGISTRY.values()))


def canonical_names():
    """name -> schema for primary names only (no aliases)."""
    return {s.name: s for s in _REGISTRY.values()}
