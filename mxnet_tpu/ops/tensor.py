"""Tensor operators: elementwise / broadcast / scalar / reduce / matrix /
indexing / init / ordering families.

Parity target: src/operator/tensor/ (SURVEY.md §2.2 — elemwise_unary_op*,
elemwise_binary_op*, broadcast_reduce-inl, matrix_op, indexing_op.h, dot-inl.h,
init_op, ordering_op, la_op). Every op is a pure jax function registered in the
op registry; XLA fuses elementwise chains into surrounding matmuls so the
mshadow kernel-per-op model is unnecessary on TPU.

Semantics notes (MXNet parity):
  - comparison ops return the *input* dtype (1.0/0.0), not bool
  - argmax/argmin/topk indices are float32 by default
  - Reshape supports the 0/-1/-2/-3/-4 special codes
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .registry import Param, register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _t(*outs):
    return tuple(outs)


def _same_shape_infer(n_in):
    """Bidirectional same-shape inference for elementwise ops."""
    def infer(attrs, in_shapes):
        known = next((s for s in in_shapes if s is not None), None)
        if known is None:
            return in_shapes, [None]
        filled = [known if s is None else s for s in in_shapes]
        for s in filled:
            if tuple(s) != tuple(known):
                # let broadcast ops through; same-shape family must match
                pass
        return filled, [known]
    return infer


def _unary(name, fn, aliases=(), float_out=False):
    def fcompute(attrs, octx, x):
        y = fn(x)
        return _t(y)
    register(name, fcompute, inputs=("data",), aliases=aliases,
             infer_shape=_same_shape_infer(1))


def _binary_broadcast(name, fn, aliases=(), cast_to_input=False):
    def fcompute(attrs, octx, lhs, rhs):
        y = fn(lhs, rhs)
        if cast_to_input:
            y = y.astype(lhs.dtype)
        return _t(y)
    register(name, fcompute, inputs=("lhs", "rhs"), aliases=aliases)


def _binary_elemwise(name, fn, aliases=(), cast_to_input=False):
    def fcompute(attrs, octx, lhs, rhs):
        y = fn(lhs, rhs)
        if cast_to_input:
            y = y.astype(lhs.dtype)
        return _t(y)
    register(name, fcompute, inputs=("lhs", "rhs"), aliases=aliases,
             infer_shape=_same_shape_infer(2))


def _scalar_op(name, fn, aliases=(), cast_to_input=False):
    def fcompute(attrs, octx, x):
        s = attrs["scalar"]
        y = fn(x, jnp.asarray(s, dtype=x.dtype) if not isinstance(s, bool) else s)
        if cast_to_input:
            y = y.astype(x.dtype)
        return _t(y)
    register(name, fcompute, params={"scalar": Param("float", 0.0, True)},
             inputs=("data",), aliases=aliases, infer_shape=_same_shape_infer(1))


# ---------------------------------------------------------------------------
# elementwise unary (src/operator/tensor/elemwise_unary_op_basic.cc etc.)
# ---------------------------------------------------------------------------

_unary("relu", lambda x: jnp.maximum(x, 0), aliases=("_relu",))
_unary("sigmoid", jax.nn.sigmoid)
_unary("softsign", lambda x: x / (1 + jnp.abs(x)))
_unary("tanh", jnp.tanh)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("square", jnp.square)
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)
_unary("negative", jnp.negative, aliases=("_np_negative",))
_unary("reciprocal", jnp.reciprocal)
_unary("erf", jax.scipy.special.erf)
_unary("erfinv", jax.scipy.special.erfinv)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", jax.scipy.special.gammaln)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))


def _identity(attrs, octx, x):
    return _t(x)

register("_copy", _identity, aliases=("identity",),
         infer_shape=_same_shape_infer(1))


def _blockgrad(attrs, octx, x):
    return _t(jax.lax.stop_gradient(x))

register("BlockGrad", _blockgrad, aliases=("stop_gradient",),
         infer_shape=_same_shape_infer(1))


def _make_loss_t(attrs, octx, x):
    # tensor-level make_loss: identity fwd, grad == 1 (src/operator/tensor/
    # elemwise_unary_op_basic.cc make_loss). Implemented via custom_vjp.
    return _t(_make_loss_fn(x))

@jax.custom_vjp
def _make_loss_fn(x):
    return x

def _ml_fwd(x):
    return x, None

def _ml_bwd(res, g):
    return (jnp.ones_like(g),)

_make_loss_fn.defvjp(_ml_fwd, _ml_bwd)
register("make_loss", _make_loss_t, infer_shape=_same_shape_infer(1))


def _cast(attrs, octx, x):
    from ..base import np_dtype
    return _t(x.astype(np_dtype(attrs["dtype"])))

register("Cast", _cast, params={"dtype": Param("dtype", "float32", True)},
         aliases=("cast",), infer_shape=_same_shape_infer(1))


def _smooth_l1(attrs, octx, x):
    s2 = attrs["scalar"] ** 2
    ax = jnp.abs(x)
    return _t(jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2))

register("smooth_l1", _smooth_l1, params={"scalar": Param("float", 1.0)},
         infer_shape=_same_shape_infer(1))


def _hard_sigmoid(attrs, octx, x):
    return _t(jnp.clip(attrs["alpha"] * x + attrs["beta"], 0.0, 1.0))


register("hard_sigmoid", _hard_sigmoid,
         params={"alpha": Param("float", 0.2), "beta": Param("float", 0.5)},
         infer_shape=_same_shape_infer(1))

# ---------------------------------------------------------------------------
# elementwise binary + broadcast families
# ---------------------------------------------------------------------------

_binary_elemwise("elemwise_add", jnp.add, aliases=("_plus", "_Plus"))
_binary_elemwise("elemwise_sub", jnp.subtract, aliases=("_minus", "_Minus"))
_binary_elemwise("elemwise_mul", jnp.multiply, aliases=("_mul", "_Mul"))
_binary_elemwise("elemwise_div", jnp.divide, aliases=("_div", "_Div"))
_binary_elemwise("_grad_add", jnp.add)

_binary_broadcast("broadcast_add", jnp.add, aliases=("broadcast_plus",))
_binary_broadcast("broadcast_sub", jnp.subtract, aliases=("broadcast_minus",))
_binary_broadcast("broadcast_mul", jnp.multiply)
_binary_broadcast("broadcast_div", jnp.divide)
_binary_broadcast("broadcast_mod", jnp.mod)
_binary_broadcast("broadcast_power", jnp.power, aliases=("_power", "_Power"))
_binary_broadcast("broadcast_maximum", jnp.maximum, aliases=("_maximum",))
_binary_broadcast("broadcast_minimum", jnp.minimum, aliases=("_minimum",))
_binary_broadcast("broadcast_hypot", jnp.hypot, aliases=("_hypot",))
_binary_broadcast("broadcast_equal", jnp.equal, cast_to_input=True,
                  aliases=("_equal", "_Equal"))
_binary_broadcast("broadcast_not_equal", jnp.not_equal, cast_to_input=True,
                  aliases=("_not_equal", "_Not_Equal"))
_binary_broadcast("broadcast_greater", jnp.greater, cast_to_input=True,
                  aliases=("_greater", "_Greater"))
_binary_broadcast("broadcast_greater_equal", jnp.greater_equal,
                  cast_to_input=True, aliases=("_greater_equal",))
_binary_broadcast("broadcast_lesser", jnp.less, cast_to_input=True,
                  aliases=("_lesser", "_Lesser"))
_binary_broadcast("broadcast_lesser_equal", jnp.less_equal,
                  cast_to_input=True, aliases=("_lesser_equal",))
_binary_broadcast("broadcast_logical_and",
                  lambda a, b: jnp.logical_and(a != 0, b != 0),
                  cast_to_input=True, aliases=("_logical_and",))
_binary_broadcast("broadcast_logical_or",
                  lambda a, b: jnp.logical_or(a != 0, b != 0),
                  cast_to_input=True, aliases=("_logical_or",))
_binary_broadcast("broadcast_logical_xor",
                  lambda a, b: jnp.logical_xor(a != 0, b != 0),
                  cast_to_input=True, aliases=("_logical_xor",))

_scalar_op("_plus_scalar", jnp.add, aliases=("_PlusScalar",))
_scalar_op("_minus_scalar", jnp.subtract, aliases=("_MinusScalar",))
_scalar_op("_rminus_scalar", lambda x, s: s - x, aliases=("_RMinusScalar",))
_scalar_op("_mul_scalar", jnp.multiply, aliases=("_MulScalar",))
_scalar_op("_div_scalar", jnp.divide, aliases=("_DivScalar",))
_scalar_op("_rdiv_scalar", lambda x, s: s / x, aliases=("_RDivScalar",))
_scalar_op("_mod_scalar", jnp.mod, aliases=("_ModScalar",))
_scalar_op("_rmod_scalar", lambda x, s: jnp.mod(s, x), aliases=("_RModScalar",))
_scalar_op("_power_scalar", jnp.power, aliases=("_PowerScalar",))
_scalar_op("_rpower_scalar", lambda x, s: jnp.power(s, x),
           aliases=("_RPowerScalar",))
_scalar_op("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_scalar_op("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))
_scalar_op("_hypot_scalar", jnp.hypot, aliases=("_HypotScalar",))
_scalar_op("_equal_scalar", jnp.equal, cast_to_input=True,
           aliases=("_EqualScalar",))
_scalar_op("_not_equal_scalar", jnp.not_equal, cast_to_input=True,
           aliases=("_NotEqualScalar",))
_scalar_op("_greater_scalar", jnp.greater, cast_to_input=True,
           aliases=("_GreaterScalar",))
_scalar_op("_greater_equal_scalar", jnp.greater_equal, cast_to_input=True,
           aliases=("_GreaterEqualScalar",))
_scalar_op("_lesser_scalar", jnp.less, cast_to_input=True,
           aliases=("_LesserScalar",))
_scalar_op("_lesser_equal_scalar", jnp.less_equal, cast_to_input=True,
           aliases=("_LesserEqualScalar",))
_scalar_op("_logical_and_scalar",
           lambda x, s: jnp.logical_and(x != 0, s != 0), cast_to_input=True)
_scalar_op("_logical_or_scalar",
           lambda x, s: jnp.logical_or(x != 0, s != 0), cast_to_input=True)
_scalar_op("_logical_xor_scalar",
           lambda x, s: jnp.logical_xor(x != 0, s != 0), cast_to_input=True)


def _add_n(attrs, octx, *inputs):
    out = inputs[0]
    for x in inputs[1:]:
        out = out + x
    return _t(out)

register("add_n", _add_n, params={"num_args": Param("int", None, True)},
         inputs=("args",), key_var_num_args="num_args",
         aliases=("ElementWiseSum", "_sum"))

# ---------------------------------------------------------------------------
# reductions (src/operator/tensor/broadcast_reduce_op*)
# ---------------------------------------------------------------------------

def _norm_axes(axis, ndim, exclude=False):
    if axis is None:
        axes = tuple(range(ndim))
    elif isinstance(axis, int):
        axes = (axis % ndim,)
    else:
        axes = tuple(a % ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reduce_op(name, fn, aliases=()):
    def fcompute(attrs, octx, x):
        axes = _norm_axes(attrs["axis"], x.ndim, attrs["exclude"])
        y = fn(x, axis=axes, keepdims=attrs["keepdims"])
        return _t(y)
    register(name, fcompute,
             params={"axis": Param("shape", None),
                     "keepdims": Param("bool", False),
                     "exclude": Param("bool", False)},
             aliases=aliases)


_reduce_op("sum", jnp.sum, aliases=("sum_axis",))
# sum-of-squares reduction (reference: sparse-aware square_sum.cc `_square_sum`;
# dense-backed here, same numerics)
_reduce_op("_square_sum",
           lambda x, axis=None, keepdims=False: jnp.sum(
               jnp.square(x), axis=axis, keepdims=keepdims))
_reduce_op("mean", jnp.mean)
_reduce_op("prod", jnp.prod)
_reduce_op("nansum", jnp.nansum)
_reduce_op("nanprod", jnp.nanprod)
_reduce_op("max", jnp.max, aliases=("max_axis",))
_reduce_op("min", jnp.min, aliases=("min_axis",))


def _argmax(attrs, octx, x):
    ax = attrs["axis"]
    y = jnp.argmax(x, axis=ax)
    if attrs["keepdims"] and ax is not None:
        y = jnp.expand_dims(y, ax)
    return _t(y.astype(jnp.float32))

def _argmin(attrs, octx, x):
    ax = attrs["axis"]
    y = jnp.argmin(x, axis=ax)
    if attrs["keepdims"] and ax is not None:
        y = jnp.expand_dims(y, ax)
    return _t(y.astype(jnp.float32))

register("argmax", _argmax, params={"axis": Param("int", None),
                                    "keepdims": Param("bool", False)})
register("argmin", _argmin, params={"axis": Param("int", None),
                                    "keepdims": Param("bool", False)})


def _argmax_channel(attrs, octx, x):
    return _t(jnp.argmax(x, axis=1).astype(jnp.float32))

register("argmax_channel", _argmax_channel)


def _norm(attrs, octx, x):
    ord_ = attrs["ord"]
    axis = attrs["axis"]
    axes = None if axis is None else _norm_axes(axis, x.ndim)
    if ord_ == 1:
        y = jnp.sum(jnp.abs(x), axis=axes, keepdims=attrs["keepdims"])
    else:
        y = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes,
                             keepdims=attrs["keepdims"]))
    return _t(y)

register("norm", _norm, params={"ord": Param("int", 2),
                                "axis": Param("shape", None),
                                "keepdims": Param("bool", False)})

# ---------------------------------------------------------------------------
# dot / batch_dot / linalg (dot-inl.h, la_op)
# ---------------------------------------------------------------------------

def _dot(attrs, octx, lhs, rhs):
    a = lhs.T if attrs["transpose_a"] else lhs
    b = rhs.T if attrs["transpose_b"] else rhs
    if a.ndim == 1 and b.ndim == 1:
        return _t(jnp.dot(a, b).reshape(1))
    # MXNet dot: contract last axis of a with first axis of b (tensordot)
    return _t(jnp.tensordot(a, b, axes=([a.ndim - 1], [0])))

register("dot", _dot, params={"transpose_a": Param("bool", False),
                              "transpose_b": Param("bool", False)},
         inputs=("lhs", "rhs"))


def _batch_dot(attrs, octx, lhs, rhs):
    a = jnp.swapaxes(lhs, -1, -2) if attrs["transpose_a"] else lhs
    b = jnp.swapaxes(rhs, -1, -2) if attrs["transpose_b"] else rhs
    return _t(jnp.matmul(a, b))

register("batch_dot", _batch_dot,
         params={"transpose_a": Param("bool", False),
                 "transpose_b": Param("bool", False)},
         inputs=("lhs", "rhs"))


def _linalg_gemm2(attrs, octx, a, b):
    x = jnp.swapaxes(a, -1, -2) if attrs["transpose_a"] else a
    y = jnp.swapaxes(b, -1, -2) if attrs["transpose_b"] else b
    return _t(attrs["alpha"] * jnp.matmul(x, y))

register("_linalg_gemm2", _linalg_gemm2,
         params={"transpose_a": Param("bool", False),
                 "transpose_b": Param("bool", False),
                 "alpha": Param("float", 1.0)},
         inputs=("A", "B"), aliases=("linalg_gemm2",))


def _linalg_gemm(attrs, octx, a, b, c):
    x = jnp.swapaxes(a, -1, -2) if attrs["transpose_a"] else a
    y = jnp.swapaxes(b, -1, -2) if attrs["transpose_b"] else b
    return _t(attrs["alpha"] * jnp.matmul(x, y) + attrs["beta"] * c)

register("_linalg_gemm", _linalg_gemm,
         params={"transpose_a": Param("bool", False),
                 "transpose_b": Param("bool", False),
                 "alpha": Param("float", 1.0), "beta": Param("float", 1.0)},
         inputs=("A", "B", "C"), aliases=("linalg_gemm",))


def _linalg_potrf(attrs, octx, a):
    return _t(jnp.linalg.cholesky(a))

register("_linalg_potrf", _linalg_potrf, inputs=("A",),
         aliases=("linalg_potrf",))


def _linalg_potri(attrs, octx, a):
    # inverse from Cholesky factor: A = L L^T input is L; potri returns A^-1
    li = jnp.linalg.inv(a)
    return _t(jnp.matmul(jnp.swapaxes(li, -1, -2), li))

register("_linalg_potri", _linalg_potri, inputs=("A",),
         aliases=("linalg_potri",))


def _linalg_trsm(attrs, octx, a, b):
    import jax.scipy.linalg as jsl
    alpha = attrs["alpha"]
    lower = not attrs["transpose"]
    if attrs["rightside"]:
        xt = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                  jnp.swapaxes(b, -1, -2),
                                  lower=not lower, trans=0)
        return _t(alpha * jnp.swapaxes(xt, -1, -2))
    return _t(alpha * jsl.solve_triangular(a, b, lower=True,
                                           trans=1 if attrs["transpose"] else 0))

register("_linalg_trsm", _linalg_trsm,
         params={"transpose": Param("bool", False),
                 "rightside": Param("bool", False),
                 "alpha": Param("float", 1.0)},
         inputs=("A", "B"), aliases=("linalg_trsm",))


def _linalg_trmm(attrs, octx, a, b):
    at = jnp.swapaxes(a, -1, -2) if attrs["transpose"] else a
    if attrs["rightside"]:
        return _t(attrs["alpha"] * jnp.matmul(b, at))
    return _t(attrs["alpha"] * jnp.matmul(at, b))

register("_linalg_trmm", _linalg_trmm,
         params={"transpose": Param("bool", False),
                 "rightside": Param("bool", False),
                 "alpha": Param("float", 1.0)},
         inputs=("A", "B"), aliases=("linalg_trmm",))


def _linalg_sumlogdiag(attrs, octx, a):
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return _t(jnp.sum(jnp.log(d), axis=-1))

register("_linalg_sumlogdiag", _linalg_sumlogdiag, inputs=("A",),
         aliases=("linalg_sumlogdiag",))


def _linalg_syrk(attrs, octx, a):
    at = jnp.swapaxes(a, -1, -2)
    if attrs["transpose"]:
        return _t(attrs["alpha"] * jnp.matmul(at, a))
    return _t(attrs["alpha"] * jnp.matmul(a, at))

register("_linalg_syrk", _linalg_syrk,
         params={"transpose": Param("bool", False),
                 "alpha": Param("float", 1.0)},
         inputs=("A",), aliases=("linalg_syrk",))

# ---------------------------------------------------------------------------
# shape manipulation (matrix_op)
# ---------------------------------------------------------------------------

def _reshape_infer_target(shape_attr, in_shape):
    """Implement MXNet Reshape special codes 0,-1,-2,-3,-4
    (src/operator/tensor/matrix_op-inl.h ReshapeParam)."""
    out = []
    src = list(in_shape)
    i = 0  # index into src
    k = 0
    spec = list(shape_attr)
    while k < len(spec):
        d = spec[k]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            d1, d2 = spec[k + 1], spec[k + 2]
            cur = src[i]; i += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); k += 2
        else:
            out.append(d); i += 1
        k += 1
    # resolve a single -1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in in_shape:
            total *= d
        out[out.index(-1)] = total // known if known else 0
    return tuple(out)


def _reshape(attrs, octx, x):
    tgt = attrs["shape"]
    if attrs["reverse"]:
        rt = _reshape_infer_target(tuple(reversed(tgt)),
                                   tuple(reversed(x.shape)))
        return _t(jnp.reshape(x, tuple(reversed(rt))))
    return _t(jnp.reshape(x, _reshape_infer_target(tgt, x.shape)))

register("Reshape", _reshape,
         params={"shape": Param("shape", (), True),
                 "reverse": Param("bool", False)},
         aliases=("reshape",))


def _flatten(attrs, octx, x):
    return _t(jnp.reshape(x, (x.shape[0], -1)))

def _flatten_infer(attrs, in_shapes):
    s = in_shapes[0]
    if s is None:
        return in_shapes, [None]
    n = 1
    for d in s[1:]:
        n *= d
    return in_shapes, [(s[0], n)]

register("Flatten", _flatten, aliases=("flatten",), infer_shape=_flatten_infer)


def _transpose(attrs, octx, x):
    axes = attrs["axes"]
    return _t(jnp.transpose(x, axes if axes else None))

register("transpose", _transpose, params={"axes": Param("shape", ())})


def _expand_dims(attrs, octx, x):
    return _t(jnp.expand_dims(x, attrs["axis"]))

register("expand_dims", _expand_dims,
         params={"axis": Param("int", None, True)})


def _squeeze(attrs, octx, x):
    ax = attrs["axis"]
    return _t(jnp.squeeze(x, None if ax is None else tuple(ax)))

register("squeeze", _squeeze, params={"axis": Param("shape", None)})


def _slice(attrs, octx, x):
    begin, end, step = attrs["begin"], attrs["end"], attrs["step"]
    idx = []
    for i in range(len(begin)):
        b = begin[i]
        e = end[i] if i < len(end) else None
        s = step[i] if step and i < len(step) else None
        idx.append(builtins_slice(b, e, s))
    return _t(x[tuple(idx)])


def builtins_slice(b, e, s):
    return slice(None if b is None else int(b),
                 None if e is None else int(e),
                 None if s is None or s == 0 else int(s))


def _parse_slice_list(v):
    # begin/end attrs may contain None entries: "(0, None)"
    if v is None:
        return None
    if isinstance(v, (tuple, list)):
        return tuple(None if x is None else int(x) for x in v)
    import ast
    val = ast.literal_eval(str(v).replace("None", "None"))
    if not isinstance(val, (tuple, list)):
        val = (val,)
    return tuple(None if x is None else int(x) for x in val)

register("slice", _slice,
         params={"begin": Param("any", None, True),
                 "end": Param("any", None, True),
                 "step": Param("any", None)},
         aliases=("crop",))
# patch parsers for slice's tolerant None-tuples
_slice_schema = None
from .registry import get_op as _get_op
for _pname in ("begin", "end", "step"):
    _get_op("slice").params[_pname].parse = _parse_slice_list  # type: ignore
    _get_op("slice").params[_pname] = _get_op("slice").params[_pname]


def _slice_axis(attrs, octx, x):
    ax = attrs["axis"] % x.ndim
    b = attrs["begin"] or 0
    e = attrs["end"]
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(b, None if e is None else e)
    return _t(x[tuple(idx)])

register("slice_axis", _slice_axis,
         params={"axis": Param("int", None, True),
                 "begin": Param("int", 0),
                 "end": Param("int", None)})


def _slice_like(attrs, octx, x, shape_like):
    axes = attrs["axes"]
    tgt = list(x.shape)
    if not axes:
        axes = tuple(range(min(x.ndim, shape_like.ndim)))
    for a in axes:
        tgt[a % x.ndim] = shape_like.shape[a % shape_like.ndim]
    idx = tuple(slice(0, t) for t in tgt)
    return _t(x[idx])

register("slice_like", _slice_like, params={"axes": Param("shape", ())},
         inputs=("data", "shape_like"))


def _reshape_like(attrs, octx, x, shape_like):
    return _t(jnp.reshape(x, shape_like.shape))

register("reshape_like", _reshape_like, inputs=("lhs", "rhs"))


def _clip(attrs, octx, x):
    return _t(jnp.clip(x, attrs["a_min"], attrs["a_max"]))

register("clip", _clip, params={"a_min": Param("float", None, True),
                                "a_max": Param("float", None, True)},
         infer_shape=_same_shape_infer(1))


def _repeat(attrs, octx, x):
    return _t(jnp.repeat(x, attrs["repeats"], axis=attrs["axis"]))

register("repeat", _repeat, params={"repeats": Param("int", None, True),
                                    "axis": Param("int", None)})


def _tile(attrs, octx, x):
    return _t(jnp.tile(x, attrs["reps"]))

register("tile", _tile, params={"reps": Param("shape", None, True)})


def _reverse(attrs, octx, x):
    return _t(jnp.flip(x, axis=tuple(attrs["axis"])))

register("reverse", _reverse, params={"axis": Param("shape", None, True)},
         aliases=("flip",))


def _swapaxes(attrs, octx, x):
    return _t(jnp.swapaxes(x, attrs["dim1"], attrs["dim2"]))

register("SwapAxis", _swapaxes, params={"dim1": Param("int", 0),
                                        "dim2": Param("int", 0)},
         aliases=("swapaxes",))


def _depth_to_space(attrs, octx, x):
    b = attrs["block_size"]
    n, c, h, w = x.shape
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return _t(y.reshape(n, c // (b * b), h * b, w * b))

register("depth_to_space", _depth_to_space,
         params={"block_size": Param("int", None, True)})


def _space_to_depth(attrs, octx, x):
    b = attrs["block_size"]
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return _t(y.reshape(n, c * b * b, h // b, w // b))

register("space_to_depth", _space_to_depth,
         params={"block_size": Param("int", None, True)})


def _stack(attrs, octx, *xs):
    return _t(jnp.stack(xs, axis=attrs["axis"]))

register("stack", _stack, params={"axis": Param("int", 0),
                                  "num_args": Param("int", None, True)},
         inputs=("arg",), key_var_num_args="num_args")


def _concat(attrs, octx, *xs):
    return _t(jnp.concatenate(xs, axis=attrs["dim"]))

def _concat_infer(attrs, in_shapes):
    known = [s for s in in_shapes if s is not None]
    if not known:
        return in_shapes, [None]
    dim = attrs["dim"]
    proto = list(known[0])
    filled = [list(proto) if s is None else list(s) for s in in_shapes]
    total = sum(s[dim] for s in filled)
    out = list(filled[0]); out[dim] = total
    return [tuple(s) for s in filled], [tuple(out)]

register("Concat", _concat,
         params={"dim": Param("int", 1), "num_args": Param("int", None, True)},
         inputs=("arg",), key_var_num_args="num_args",
         aliases=("concat",), infer_shape=_concat_infer)


def _split(attrs, octx, x):
    n = attrs["num_outputs"]
    ax = attrs["axis"]
    parts = jnp.split(x, n, axis=ax)
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=ax) for p in parts]
    return tuple(parts)

def _split_noutputs(attrs):
    return attrs["num_outputs"]

_split_schema = register(
    "SliceChannel", _split,
    params={"num_outputs": Param("int", None, True),
            "axis": Param("int", 1),
            "squeeze_axis": Param("bool", False)},
    aliases=("split",))
_split_schema.num_outputs = _split_noutputs  # dynamic output count


def _where(attrs, octx, cond, x, y):
    return _t(jnp.where(cond != 0, x, y))

register("where", _where, inputs=("condition", "x", "y"))


def _pad(attrs, octx, x):
    pw = attrs["pad_width"]
    mode = attrs["mode"]
    pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    if mode == "constant":
        return _t(jnp.pad(x, pads, constant_values=attrs["constant_value"]))
    if mode == "edge":
        return _t(jnp.pad(x, pads, mode="edge"))
    if mode == "reflect":
        return _t(jnp.pad(x, pads, mode="reflect"))
    raise MXNetError(f"Pad: unknown mode {mode}")

register("Pad", _pad,
         params={"mode": Param("str", "constant"),
                 "pad_width": Param("shape", None, True),
                 "constant_value": Param("float", 0.0)},
         aliases=("pad",))


def _broadcast_to(attrs, octx, x):
    tgt = list(attrs["shape"])
    for i, d in enumerate(tgt):
        if d == 0:
            tgt[i] = x.shape[i]
    return _t(jnp.broadcast_to(x, tuple(tgt)))

register("broadcast_to", _broadcast_to,
         params={"shape": Param("shape", None, True)})


def _broadcast_axis(attrs, octx, x):
    axes = attrs["axis"]
    sizes = attrs["size"]
    if isinstance(axes, int):
        axes, sizes = (axes,), (sizes,)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return _t(jnp.broadcast_to(x, tuple(tgt)))

register("broadcast_axis", _broadcast_axis,
         params={"axis": Param("shape", None, True),
                 "size": Param("shape", None, True)},
         aliases=("broadcast_axes",))


def _broadcast_like(attrs, octx, x, like):
    return _t(jnp.broadcast_to(x, like.shape))

register("broadcast_like", _broadcast_like, inputs=("lhs", "rhs"))

# ---------------------------------------------------------------------------
# indexing (indexing_op.h)
# ---------------------------------------------------------------------------

def _take(attrs, octx, data, indices):
    ax = attrs["axis"]
    mode = attrs["mode"]
    idx = indices.astype(jnp.int32)
    n = data.shape[ax]
    if mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, n)
    return _t(jnp.take(data, idx, axis=ax))

register("take", _take,
         params={"axis": Param("int", 0), "mode": Param("str", "clip")},
         inputs=("a", "indices"))


def _batch_take(attrs, octx, data, indices):
    idx = indices.astype(jnp.int32)
    return _t(jnp.take_along_axis(data, idx[:, None], axis=1)[:, 0])

register("batch_take", _batch_take, inputs=("a", "indices"))


def _pick(attrs, octx, data, index):
    ax = attrs["axis"]
    idx = index.astype(jnp.int32)
    if ax is None:
        flat = data.reshape(-1)
        return _t(jnp.take(flat, idx.reshape(-1)).reshape(index.shape))
    ax = ax % data.ndim
    idx_exp = jnp.expand_dims(idx, ax) if idx.ndim < data.ndim else idx
    n = data.shape[ax]
    idx_exp = jnp.clip(idx_exp, 0, n - 1)
    out = jnp.take_along_axis(data, idx_exp, axis=ax)
    if attrs["keepdims"]:
        return _t(out)
    return _t(jnp.squeeze(out, axis=ax))

register("pick", _pick,
         params={"axis": Param("int", -1), "keepdims": Param("bool", False)},
         inputs=("data", "index"), aliases=("choose_element_0index",))


def _gather_nd(attrs, octx, data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return _t(data[tuple(idx[i] for i in range(m))])

register("gather_nd", _gather_nd, inputs=("data", "indices"))


def _scatter_nd(attrs, octx, data, indices):
    shape = attrs["shape"]
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return _t(out.at[tuple(idx[i] for i in range(m))].set(data))

register("scatter_nd", _scatter_nd,
         params={"shape": Param("shape", None, True)},
         inputs=("data", "indices"))


def _one_hot(attrs, octx, indices):
    from ..base import np_dtype
    depth = attrs["depth"]
    on, off = attrs["on_value"], attrs["off_value"]
    idx = indices.astype(jnp.int32)
    oh = jax.nn.one_hot(idx, depth)
    out = oh * on + (1 - oh) * off
    return _t(out.astype(np_dtype(attrs["dtype"])))

register("one_hot", _one_hot,
         params={"depth": Param("int", None, True),
                 "on_value": Param("float", 1.0),
                 "off_value": Param("float", 0.0),
                 "dtype": Param("dtype", "float32")},
         inputs=("indices",))


def _diag(attrs, octx, x):
    k = attrs["k"]
    if x.ndim == 1:
        return _t(jnp.diag(x, k=k))
    return _t(jnp.diagonal(x, offset=k, axis1=-2, axis2=-1))

register("diag", _diag, params={"k": Param("int", 0)})

# ---------------------------------------------------------------------------
# init ops (init_op.cc) — nullary; created via attrs only
# ---------------------------------------------------------------------------

def _np_dt(attrs):
    from ..base import np_dtype
    return np_dtype(attrs.get("dtype") or "float32")


def _zeros(attrs, octx):
    return _t(jnp.zeros(attrs["shape"], dtype=_np_dt(attrs)))

register("_zeros", _zeros, params={"shape": Param("shape", (), True),
                                   "dtype": Param("dtype", "float32")},
         inputs=())


def _ones(attrs, octx):
    return _t(jnp.ones(attrs["shape"], dtype=_np_dt(attrs)))

register("_ones", _ones, params={"shape": Param("shape", (), True),
                                 "dtype": Param("dtype", "float32")},
         inputs=())


def _full(attrs, octx):
    return _t(jnp.full(attrs["shape"], attrs["value"], dtype=_np_dt(attrs)))

register("_full", _full, params={"shape": Param("shape", (), True),
                                 "value": Param("float", 0.0, True),
                                 "dtype": Param("dtype", "float32")},
         inputs=())


def _arange(attrs, octx):
    start, stop, step = attrs["start"], attrs["stop"], attrs["step"]
    a = jnp.arange(start, stop, step, dtype=_np_dt(attrs))
    if attrs["repeat"] > 1:
        a = jnp.repeat(a, attrs["repeat"])
    return _t(a)

register("_arange", _arange,
         params={"start": Param("float", 0.0), "stop": Param("float", None),
                 "step": Param("float", 1.0), "repeat": Param("int", 1),
                 "dtype": Param("dtype", "float32")},
         inputs=())


def _eye(attrs, octx):
    return _t(jnp.eye(attrs["N"], attrs["M"] or None, k=attrs["k"],
                      dtype=_np_dt(attrs)))

register("_eye", _eye, params={"N": Param("int", None, True),
                               "M": Param("int", 0), "k": Param("int", 0),
                               "dtype": Param("dtype", "float32")},
         inputs=())


def _zeros_like(attrs, octx, x):
    return _t(jnp.zeros_like(x))

register("zeros_like", _zeros_like, infer_shape=_same_shape_infer(1))


def _ones_like(attrs, octx, x):
    return _t(jnp.ones_like(x))

register("ones_like", _ones_like, infer_shape=_same_shape_infer(1))

# ---------------------------------------------------------------------------
# ordering (ordering_op)
# ---------------------------------------------------------------------------

def _sort(attrs, octx, x):
    ax = attrs["axis"]
    y = jnp.sort(x, axis=ax)
    if not attrs["is_ascend"]:
        y = jnp.flip(y, axis=ax if ax is not None else tuple(range(x.ndim)))
    return _t(y)

register("sort", _sort, params={"axis": Param("int", -1),
                                "is_ascend": Param("bool", True)})


def _argsort(attrs, octx, x):
    ax = attrs["axis"]
    y = jnp.argsort(x, axis=ax)
    if not attrs["is_ascend"]:
        y = jnp.flip(y, axis=ax if ax is not None else tuple(range(x.ndim)))
    return _t(y.astype(_np_dt(attrs)))

register("argsort", _argsort, params={"axis": Param("int", -1),
                                      "is_ascend": Param("bool", True),
                                      "dtype": Param("dtype", "float32")})


def _topk_compute(attrs, octx, x):
    ax = attrs["axis"]
    k = attrs["k"]
    ret = attrs["ret_typ"]
    asc = attrs["is_ascend"]
    if ax is None:
        x2 = x.reshape(-1)
        ax2 = 0
    else:
        x2 = x
        ax2 = ax % x.ndim
    xm = jnp.moveaxis(x2, ax2, -1)
    vals, idxs = jax.lax.top_k(jnp.negative(xm) if asc else xm, k)
    if asc:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, ax2)
    idxs = jnp.moveaxis(idxs, -1, ax2)
    if ret == "value":
        return _t(vals)
    if ret == "both":
        return (vals, idxs.astype(_np_dt(attrs)))
    if ret == "mask":
        oh = jnp.sum(jax.nn.one_hot(idxs, xm.shape[-1], dtype=x.dtype), axis=-2)
        return _t(jnp.moveaxis(oh, -1, ax2) if ax is not None else oh)
    return _t(idxs.astype(_np_dt(attrs)))


def _topk_noutputs(attrs):
    return 2 if attrs["ret_typ"] == "both" else 1

_topk_schema = register("topk", _topk_compute,
                        params={"axis": Param("int", -1),
                                "k": Param("int", 1),
                                "ret_typ": Param("str", "indices"),
                                "is_ascend": Param("bool", False),
                                "dtype": Param("dtype", "float32")})
_topk_schema.num_outputs = _topk_noutputs

# shape-only ops (reference dtype is int64; under jax's default x64-off
# mode that maps to int32 — request it directly instead of triggering the
# truncation warning on every call)
def _shape_dtype():
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def _shape_array(attrs, octx, x):
    return _t(jnp.asarray(x.shape, dtype=_shape_dtype()))

register("shape_array", _shape_array)


def _size_array(attrs, octx, x):
    return _t(jnp.asarray([x.size], dtype=_shape_dtype()))

register("size_array", _size_array)


def _contrib_div_sqrt_dim(attrs, octx, x):
    # transformer helper (src/operator/contrib/transformer.cc:34)
    return _t(x / jnp.sqrt(jnp.asarray(x.shape[-1], dtype=x.dtype)))

register("_contrib_div_sqrt_dim", _contrib_div_sqrt_dim)


def _cumsum(attrs, octx, x):
    axis = attrs["axis"]
    dtype = attrs["dtype"]
    if axis is None:
        return _t(jnp.cumsum(x.ravel(), dtype=dtype))
    return _t(jnp.cumsum(x, axis=axis, dtype=dtype))


def _cumsum_infer(attrs, in_shapes):
    s = in_shapes[0]
    if s is None:
        return in_shapes, [None]
    if attrs["axis"] is None:
        n = 1
        for d in s:
            n *= d
        return in_shapes, [(n,)]
    return in_shapes, [tuple(s)]


register("cumsum", _cumsum, params={"axis": Param("int", None),
                                    "dtype": Param("dtype", None)},
         infer_shape=_cumsum_infer, aliases=("_np_cumsum",))
