"""Operator library. Importing this package registers all operators."""
from . import registry
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import contrib  # noqa: F401
from . import quantization  # noqa: F401
from . import extra  # noqa: F401
from . import attention  # noqa: F401

from .registry import get_op, list_ops  # noqa: F401
