"""Random sampling operators (parity: src/operator/random/, SURVEY.md §2.2).

The reference uses per-device curand/mt19937 resources; here each sampler is a
pure function of an explicit jax PRNG key supplied by the global key chain
(mxnet_tpu.random), so results are reproducible under mx.random.seed while
every invocation stays a compiled pure computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import Param, register, register_alias


def _t(*o):
    return tuple(o)


def _dt(attrs):
    from ..base import np_dtype
    return np_dtype(attrs.get("dtype") or "float32")


_SHAPE_PARAMS = {"shape": Param("shape", (1,)),
                 "dtype": Param("dtype", "float32"),
                 "ctx": Param("str", None)}


def _reg_random(name, fn, extra):
    params = dict(_SHAPE_PARAMS)
    params.update(extra)

    def fcompute(attrs, octx, *_):
        return _t(fn(octx.rng, attrs).astype(_dt(attrs)))

    register(name, fcompute, params=params, inputs=(), needs_rng=True)


_reg_random("_random_uniform",
            lambda k, a: jax.random.uniform(k, a["shape"], minval=a["low"],
                                            maxval=a["high"]),
            {"low": Param("float", 0.0), "high": Param("float", 1.0)})
_reg_random("_random_normal",
            lambda k, a: a["loc"] + a["scale"] * jax.random.normal(k, a["shape"]),
            {"loc": Param("float", 0.0), "scale": Param("float", 1.0)})
_reg_random("_random_gamma",
            lambda k, a: jax.random.gamma(k, a["alpha"], a["shape"]) * a["beta"],
            {"alpha": Param("float", 1.0), "beta": Param("float", 1.0)})
_reg_random("_random_exponential",
            lambda k, a: jax.random.exponential(k, a["shape"]) / a["lam"],
            {"lam": Param("float", 1.0)})
_reg_random("_random_poisson",
            lambda k, a: jax.random.poisson(k, a["lam"], a["shape"]).astype(
                jnp.float32),
            {"lam": Param("float", 1.0)})
_reg_random("_random_negative_binomial",
            lambda k, a: _neg_binomial(k, a["k"], a["p"], a["shape"]),
            {"k": Param("int", 1), "p": Param("float", 1.0)})
_reg_random("_random_generalized_negative_binomial",
            lambda k, a: _gen_neg_binomial(k, a["mu"], a["alpha"], a["shape"]),
            {"mu": Param("float", 1.0), "alpha": Param("float", 1.0)})
_reg_random("_random_randint",
            lambda k, a: jax.random.randint(k, a["shape"], int(a["low"]),
                                            int(a["high"])),
            {"low": Param("float", 0.0), "high": Param("float", 1.0)})


def _neg_binomial(key, r, p, shape):
    """Gamma-Poisson mixture; scalar or array r/p (broadcast to shape)."""
    k1, k2 = jax.random.split(key)
    r = jnp.broadcast_to(jnp.asarray(r, jnp.float32), shape)
    lam = jax.random.gamma(k1, r) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(jnp.float32)


def _gen_neg_binomial(key, mu, alpha, shape):
    r = 1.0 / alpha
    p = r / (r + mu)
    return _neg_binomial(key, r, p, shape)


# sample_* family: distribution params given as arrays; one sample (or `shape`
# samples) drawn per parameter element.

def _reg_sample(name, fn, n_params):
    def fcompute(attrs, octx, *inputs):
        extra = attrs["shape"] or ()
        out = fn(octx.rng, *inputs, extra)
        return _t(out)

    inputs = ("low", "high")[:n_params] if "uniform" in name else \
        tuple(f"p{i}" for i in range(n_params))
    register(name, fcompute,
             params={"shape": Param("shape", None),
                     "dtype": Param("dtype", "float32")},
             inputs=inputs, needs_rng=True)


def _samp_shape(param, extra):
    return tuple(param.shape) + tuple(extra)


def _bcast(p, extra):
    return p.reshape(p.shape + (1,) * len(tuple(extra)))


_reg_sample("_sample_uniform",
            lambda k, lo, hi, e: jax.random.uniform(
                k, _samp_shape(lo, e)) * (_bcast(hi - lo, e)) + _bcast(lo, e),
            2)
_reg_sample("_sample_normal",
            lambda k, mu, sig, e: _bcast(mu, e) + _bcast(sig, e) *
            jax.random.normal(k, _samp_shape(mu, e)), 2)
_reg_sample("_sample_gamma",
            lambda k, a, b, e: jax.random.gamma(
                k, _bcast(a, e), _samp_shape(a, e)) * _bcast(b, e), 2)
_reg_sample("_sample_exponential",
            lambda k, lam, e: jax.random.exponential(
                k, _samp_shape(lam, e)) / _bcast(lam, e), 1)
_reg_sample("_sample_poisson",
            lambda k, lam, e: jax.random.poisson(
                k, _bcast(lam, e), _samp_shape(lam, e)).astype(jnp.float32), 1)


def _sample_multinomial(attrs, octx, data):
    shape = attrs["shape"] or ()
    n = 1
    for s in shape:
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        draws = jax.random.categorical(octx.rng, logits, shape=(n,))
        out = draws.reshape(shape) if shape else draws[0]
    else:
        draws = jax.random.categorical(octx.rng, logits[:, None, :],
                                       axis=-1, shape=(data.shape[0], n))
        out = draws.reshape((data.shape[0],) + tuple(shape)) if shape \
            else draws[:, 0]
    outs = [out.astype(_dt(attrs))]
    if attrs["get_prob"]:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1).reshape(-1, data.shape[-1]),
            out.reshape(-1, 1).astype(jnp.int32), axis=1)
        outs.append(lp.reshape(out.shape))
    return tuple(outs)


_mult_schema = register("_sample_multinomial", _sample_multinomial,
                        params={"shape": Param("shape", None),
                                "get_prob": Param("bool", False),
                                "dtype": Param("dtype", "int32")},
                        inputs=("data",), needs_rng=True)
_mult_schema.num_outputs = lambda a: 2 if a["get_prob"] else 1  # type: ignore


def _shuffle(attrs, octx, data):
    return _t(jax.random.permutation(octx.rng, data, axis=0))

register("_shuffle", _shuffle, needs_rng=True, aliases=("shuffle",))
_reg_sample("_sample_negative_binomial",
            lambda k, r, p, e: _neg_binomial(k, _bcast(r, e), _bcast(p, e),
                                             _samp_shape(r, e)), 2)
_reg_sample("_sample_generalized_negative_binomial",
            lambda k, mu, al, e: _gen_neg_binomial(
                k, _bcast(mu, e), _bcast(al, e), _samp_shape(mu, e)), 2)


# ---------------------------------------------------------------------------
# frontend alias names (reference registers these via add_alias on the
# _random_* / _sample_* ops, src/operator/random/sample_op.cc)
# ---------------------------------------------------------------------------

for _a, _t_name in [
        ("uniform", "_random_uniform"),
        ("random_uniform", "_random_uniform"),
        ("normal", "_random_normal"),
        ("random_normal", "_random_normal"),
        ("random_gamma", "_random_gamma"),
        ("random_exponential", "_random_exponential"),
        ("random_poisson", "_random_poisson"),
        ("random_negative_binomial", "_random_negative_binomial"),
        ("random_generalized_negative_binomial",
         "_random_generalized_negative_binomial"),
        ("sample_multinomial", "_sample_multinomial"),
]:
    register_alias(_a, _t_name)
