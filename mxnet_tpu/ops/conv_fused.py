"""Pallas conv+BN(+ReLU) megakernels for the ResNet hot path.

Role: built to test round 3's hypothesis (docs/perf_analysis_r03.md §6)
that XLA would not fuse a reduction epilogue (BN statistics) into a
convolution's output nor keep the normalize/mask chain in VMEM between
a conv and its consumer — which, if true, would have made every
BatchNorm cost a full extra read pass. THE HYPOTHESIS WAS REFUTED BY
MEASUREMENT (docs/megakernel_r04.md): XLA already performs both
fusions. The kernels implement, for the 1x1 convolutions (2/3 of
ResNet-50's convs, touching its largest tensors):

  - `conv1x1(want_stats=True)`: y = w @ x with the per-channel sum /
                       sum-of-squares accumulated in VMEM while the
                       output tile is still resident — the BN stats pass
                       disappears.
  - prologues:         the same kernel optionally applies BN-apply+ReLU
                       (and a residual add) to its INPUT tile on the fly,
                       so the producer's raw conv output is the only
                       materialized tensor between two convolutions.

Layout: NCHW activations are viewed as (N, C, P=H*W) — the GEMM is
batched over N with C on the sublane axis and the spatial dim on lanes,
so no physical transpose is needed (the reference's 1x1 Convolution via
im2col, src/operator/nn/convolution-inl.h, pays the same GEMM but through
cuDNN). Weights (Co, Ci) live whole in VMEM (<=2 MB for every ResNet
shape).

All kernels are shape-specialized at trace time. These kernels are a
MEASURED ARTIFACT, not the default conv path: on the real v5e they tie
XLA's fused chain at best (XLA already output-fuses the BN statistics
into conv fusions and runs flat chains at the HBM roofline) — see
docs/megakernel_r04.md for the device-trace evidence. They remain
importable and tested for direct use and future layout-regime work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK_P = 512          # lanes per grid cell (multiple of 128)


def _pick_block_p(p, ci, co, has_residual=False):
    """Lane-block size. ResNet spatial dims (56^2=3136, 28^2, ...) are
    not 128-divisible, so fall back to a full-P block (legal via the
    equal-dimension escape) when the whole (Ci+Co, P) working set fits
    VMEM comfortably."""
    if p % 128 == 0:
        for b in (_BLOCK_P, 256, 128):
            if p % b == 0:
                return b
    # full-P block: bf16 in+out tiles + fp32 accumulator, plus the
    # optional residual input tile (another Ci x P in bf16)
    vmem = (ci * p + co * p) * 2 + co * p * 4
    if has_residual:
        vmem += ci * p * 2
    return p if vmem <= 8 * 1024 * 1024 else None


def eligible(ci, co, p, has_residual=False):
    """Shapes the megakernel path accepts: both channel dims tile the
    8x128 register grid and the spatial dim blocks into lanes."""
    return (ci % 8 == 0 and co % 8 == 0 and
            _pick_block_p(p, ci, co, has_residual) is not None)


def _c1x1_kernel(x_ref, w_ref, scale_ref, shift_ref, res_ref,
                 y_ref, part_ref, *, prologue, relu_in, want_stats):
    """One (n, p-block) cell: y[n, :, pb] = w @ f(x[n, :, pb]).

    f is the input prologue: identity, or BN-apply (+ReLU) with the
    per-channel scale/shift vectors resident in VMEM, optionally adding a
    residual tile first. Epilogue accumulates per-channel sum / sumsq of
    the fp32 output tile into `part_ref` before the tile leaves VMEM.
    """
    x = x_ref[:]                                   # (Ci, Bp)
    if prologue:
        xf = x.astype(jnp.float32)
        xf = xf * scale_ref[:] + shift_ref[:]      # (Ci,1) broadcast
        if res_ref is not None:
            xf = xf + res_ref[:].astype(jnp.float32)
        if relu_in:
            xf = jnp.maximum(xf, 0.0)
        x = xf.astype(x_ref.dtype)
    y = jax.lax.dot_general(
        w_ref[:], x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # (Co, Bp)
    yc = y.astype(y_ref.dtype)
    y_ref[:] = yc
    if want_stats:
        # stats of the STORED values (post bf16 round-trip) so the fused
        # path normalizes exactly what a separate stats pass would see
        y32 = yc.astype(jnp.float32)
        s1 = jnp.sum(y32, axis=1)                  # (Co,)
        s2 = jnp.sum(y32 * y32, axis=1)
        part_ref[:] = jnp.stack([s1, s2], axis=0)  # (2, Co)


def conv1x1(x, w, *, bn_in=None, residual=None, relu_in=False,
            want_stats=True, interpret=False):
    """Fused 1x1 convolution.

    x         (N, Ci, P)  activations (P = H*W, NCHW view)
    w         (Co, Ci)    weights
    bn_in     optional (scale, shift) fp32 (Ci,) vectors applied to the
              input tile in VMEM (BN-apply folded from the producer)
    residual  optional (N, Ci, P) added before relu_in
    relu_in   apply ReLU after the input BN (the usual BN+ReLU prologue)
    want_stats  also return (sum, sumsq) per output channel, computed
              while the fp32 tile is in VMEM (the fused BN-stats pass)

    Returns y (N, Co, P) [, (sum (Co,), sumsq (Co,)) fp32].
    """
    import jax.experimental.pallas as pl

    n, ci, p = x.shape
    co = w.shape[0]
    bp = _pick_block_p(p, ci, co, has_residual=residual is not None)
    if bp is None:
        raise ValueError(f"spatial dim {p} not blockable")
    prologue = bn_in is not None
    if bn_in is None:
        scale = jnp.ones((ci, 1), jnp.float32)
        shift = jnp.zeros((ci, 1), jnp.float32)
    else:
        scale = bn_in[0].reshape(ci, 1).astype(jnp.float32)
        shift = bn_in[1].reshape(ci, 1).astype(jnp.float32)

    kernel = functools.partial(
        _c1x1_kernel, prologue=prologue, relu_in=relu_in,
        want_stats=want_stats)
    if residual is None:
        kernel = functools.partial(
            lambda xr, wr, sr, hr, yr, pr, k: k(xr, wr, sr, hr, None,
                                                yr, pr),
            k=kernel)

    pt = p // bp
    in_specs = [
        pl.BlockSpec((None, ci, bp), lambda ni, pi: (ni, 0, pi)),
        pl.BlockSpec((co, ci), lambda ni, pi: (0, 0)),
        pl.BlockSpec((ci, 1), lambda ni, pi: (0, 0)),
        pl.BlockSpec((ci, 1), lambda ni, pi: (0, 0)),
    ]
    args = [x, w, scale, shift]
    if residual is not None:
        in_specs.append(pl.BlockSpec((None, ci, bp),
                                     lambda ni, pi: (ni, 0, pi)))
        args.append(residual)

    out_specs = [pl.BlockSpec((None, co, bp), lambda ni, pi: (ni, 0, pi))]
    out_shape = [jax.ShapeDtypeStruct((n, co, p), x.dtype)]
    if want_stats:
        out_specs.append(pl.BlockSpec((None, None, 2, co),
                                      lambda ni, pi: (ni, pi, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((n, pt, 2, co), jnp.float32))
    else:
        # no stats output at all — the kernel receives part_ref=None
        kernel = functools.partial(
            lambda *refs, k: k(*refs, None), k=kernel)
    out = pl.pallas_call(
        kernel,
        grid=(n, pt),
        in_specs=in_specs,
        out_specs=out_specs if want_stats else out_specs[0],
        out_shape=out_shape if want_stats else out_shape[0],
        interpret=interpret,
    )(*args)
    if not want_stats:
        return out
    y, parts = out
    sums = parts.sum(axis=(0, 1))                  # (2, Co)
    return y, (sums[0], sums[1])


def finalize_stats(s1, s2, count, eps):
    """mean/var (biased, matching BN) and the folded apply vectors:
    normalize(x) = x * scale + shift with scale = gamma*rstd,
    shift = beta - mean*scale."""
    mean = s1 / count
    var = jnp.maximum(s2 / count - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, var, rstd


def bn_fold(gamma, beta, mean, rstd):
    scale = gamma * rstd
    return scale, beta - mean * scale
