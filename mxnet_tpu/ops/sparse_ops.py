"""Gather/scatter sparse compute — TPU-native row_sparse/CSR kernels.

Role of the reference's sparse kernels (dot(csr,dense)
src/operator/tensor/dot-inl.h; sparse optimizer kernels
src/operator/optimizer_op.cc). TPU/XLA has no native sparse formats, so
the TPU-first realization is the ELL (padded-row) layout: a CSR matrix
(R, F) with at most K nonzeros per row becomes `val (R, K)` + `idx
(R, K)` device arrays (rows padded with idx=0/val=0). All kernels are
static-shaped gathers/scatters XLA lowers to its native dynamic-gather/
scatter HLOs — compute and memory scale with nnz (R*K), not with the
dense (R, F) / (F, M) sizes. NDArray-level dispatch lives in
ndarray/sparse.py; the measured dense-vs-sparse crossover on the real
chip is recorded in tools/sparse_bench.py + PARITY.md.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp


def ell_from_csr(data, indices, indptr, pad_to_multiple=8,
                 num_features=None):
    """Host-side CSR -> ELL conversion, vectorized (no per-row python
    loop — construction must scale to million-row matrices). Returns
    (val (R, K), idx (R, K), counts (R,)) with K = max row nnz rounded
    up for lane friendliness; counts preserves the exact nnz structure
    (pad entries are indistinguishable from an explicit zero at column
    0 without it). `num_features` (when known) bounds-checks the column
    indices here on the host — the device gathers/scatters downstream
    CLIP out-of-range indices instead of erroring, which would turn a
    malformed triplet into silently wrong values."""
    data = _np.asarray(data)
    indices = _np.asarray(indices, dtype=_np.int32)
    indptr = _np.asarray(indptr, dtype=_np.int64)
    if len(indices) and (int(indices.min()) < 0 or (
            num_features is not None
            and int(indices.max()) >= num_features)):
        raise ValueError(
            f"ell_from_csr: column index out of range [0, {num_features}) "
            f"(got min {int(indices.min())}, max {int(indices.max())})")
    rows = len(indptr) - 1
    counts = _np.diff(indptr).astype(_np.int32)
    k = int(counts.max()) if rows else 0
    k = max(1, -(-k // pad_to_multiple) * pad_to_multiple)
    val = _np.zeros((rows, k), dtype=data.dtype)
    idx = _np.zeros((rows, k), dtype=_np.int32)
    nnz = len(data)
    if nnz:
        row_of = _np.repeat(_np.arange(rows), counts)
        slot = _np.arange(nnz) - _np.repeat(indptr[:-1], counts)
        val[row_of, slot] = data
        idx[row_of, slot] = indices
    return val, idx, counts


def ell_dot(val, idx, weight):
    """dot(csr, dense): out[r] = sum_j val[r,j] * weight[idx[r,j]].
    Padded entries contribute val=0. out (R, M)."""
    if isinstance(idx, _np.ndarray) and idx.size and \
            int(idx.max()) >= weight.shape[0]:
        raise ValueError(f"ell_dot: column index {int(idx.max())} out of "
                         f"range for weight rows {weight.shape[0]}")
    gathered = jnp.take(weight, idx, axis=0)          # (R, K, M)
    return jnp.einsum("rk,rkm->rm", val.astype(weight.dtype), gathered)


def ell_dot_t(val, idx, dense, num_features):
    """dot(csr.T, dense): out[f] += sum over (r,j) with idx[r,j]==f of
    val[r,j] * dense[r]. The backward/transpose pattern (dW of a linear
    layer over sparse inputs). out (F, M) via XLA scatter-add."""
    if isinstance(idx, _np.ndarray) and idx.size and \
            int(idx.max()) >= num_features:
        raise ValueError(f"ell_dot_t: column index {int(idx.max())} out of "
                         f"range for num_features {num_features}")
    r, k = val.shape
    m = dense.shape[1]
    contrib = (val.astype(dense.dtype)[..., None]
               * dense[:, None, :])                   # (R, K, M)
    out = jnp.zeros((num_features, m), dense.dtype)
    return out.at[idx.reshape(-1)].add(contrib.reshape(r * k, m))


def unique_rows(ids, size, fill):
    """jit-safe static-shape dedup of a flat int row-id vector:
    ``(uniq (size,), inv (len(ids),), count)`` where ``uniq`` is sorted,
    padded with ``fill`` (pick one past the valid row range — a value
    that can never collide with a real id), ``inv`` maps each input
    position to its slot in ``uniq``, and ``count`` is the number of
    live (non-fill) uniques. The building block of the row-sparse
    gradient exchange: dedup happens BEFORE any wire movement, so
    per-step collective payloads scale with touched rows."""
    ids = jnp.asarray(ids).reshape(-1).astype(jnp.int32)
    uniq, inv = jnp.unique(ids, size=size, fill_value=fill,
                           return_inverse=True)
    count = jnp.sum(uniq != fill).astype(jnp.int32)
    return uniq, inv.reshape(-1).astype(jnp.int32), count


def segment_sum_rows(vals, inv, num_segments):
    """Sum value rows that dedup'd to the same unique slot:
    ``out[inv[i]] += vals[i]`` via a single XLA scatter-add (the vector
    form of np.add.at). Pair of unique_rows: (uniq, segment_sum) turns
    per-occurrence gradients into canonical row_sparse (rows, vals)."""
    vals = jnp.asarray(vals)
    out = jnp.zeros((num_segments,) + vals.shape[1:], vals.dtype)
    return out.at[jnp.asarray(inv).reshape(-1)].add(vals)


# The rows_* kernels gather with mode="clip" and scatter with
# mode="drop": an out-of-range row index (>= weight rows) reads row 0's
# values during the update math (harmless — the result is discarded)
# and its write is dropped entirely. This is what lets the sharded
# embedding exchange hand every device the full deduped global row list
# and mask non-owned/padding slots by mapping them to one-past-the-shard
# instead of compacting to a dynamic shape XLA can't compile. In-bounds
# behavior is unchanged (the modes only bind out of range). Negative
# indices must not be used for masking — they wrap before the mode
# applies.

def rows_sgd_update(weight, rows, grad_rows, lr, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """Row-sparse SGD: touch ONLY the listed rows (reference lazy_update
    sparse kernel semantics — untouched rows skip weight decay too).
    `rows` must be unique among in-bounds entries, the row_sparse format
    invariant (the reference's kernels iterate indices assuming the
    same); out-of-bounds entries are dropped."""
    weight = jnp.asarray(weight)
    g = jnp.asarray(grad_rows).astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w_rows = jnp.take(weight, rows, axis=0, mode="clip")\
        .astype(jnp.float32)
    upd = -lr * (g + wd * w_rows)
    return weight.at[rows].add(upd.astype(weight.dtype), mode="drop")


def rows_sgd_mom_update(weight, mom, rows, grad_rows, lr, momentum,
                        wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Row-sparse SGD+momentum: momentum decays ONLY on touched rows
    (reference sgd_mom sparse kernel)."""
    weight, mom = jnp.asarray(weight), jnp.asarray(mom)
    g = jnp.asarray(grad_rows).astype(jnp.float32) * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w_rows = jnp.take(weight, rows, axis=0, mode="clip")\
        .astype(jnp.float32)
    m_rows = jnp.take(mom, rows, axis=0, mode="clip").astype(jnp.float32)
    m_new = momentum * m_rows - lr * (g + wd * w_rows)
    return (weight.at[rows].add(m_new.astype(weight.dtype), mode="drop"),
            mom.at[rows].set(m_new.astype(mom.dtype), mode="drop"))


def rows_adam_update(weight, mean, var, rows, grad_rows, lr, beta1, beta2,
                     epsilon, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Row-sparse (lazy) Adam: moments decay ONLY on touched rows
    (reference adam_update sparse kernel, optimizer_op.cc). Adam-family
    prep order: rescale -> +wd*w -> clip (ops/optimizer_ops.py
    _prep_wd_first — decay folds into the grad BEFORE clipping, unlike
    the SGD family)."""
    weight = jnp.asarray(weight)
    mean, var = jnp.asarray(mean), jnp.asarray(var)
    w_rows = jnp.take(weight, rows, axis=0, mode="clip")\
        .astype(jnp.float32)
    g = jnp.asarray(grad_rows).astype(jnp.float32) * rescale_grad \
        + wd * w_rows
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m_rows = jnp.take(mean, rows, axis=0, mode="clip").astype(jnp.float32)
    v_rows = jnp.take(var, rows, axis=0, mode="clip").astype(jnp.float32)
    m_new = beta1 * m_rows + (1 - beta1) * g
    v_new = beta2 * v_rows + (1 - beta2) * g * g
    step = -lr * m_new / (jnp.sqrt(v_new) + epsilon)
    return (weight.at[rows].add(step.astype(weight.dtype), mode="drop"),
            mean.at[rows].set(m_new.astype(mean.dtype), mode="drop"),
            var.at[rows].set(v_new.astype(var.dtype), mode="drop"))
