"""Contrib + legacy vision operators — detection, sampling, signal ops.

Parity targets (SURVEY.md §2.2 "contrib ops" + "legacy top-level ops"):
  - SSD family: MultiBoxPrior/Target/Detection
    (src/operator/contrib/multibox_{prior,target,detection}.cc)
  - box_nms / box_iou / bipartite_matching (src/operator/contrib/bounding_box.cc)
  - ROIPooling (src/operator/roi_pooling.cc)
  - SpatialTransformer / BilinearSampler / GridGenerator
    (src/operator/{spatial_transformer,bilinear_sampler,grid_generator}.cc)
  - Correlation (src/operator/correlation.cc)
  - CTCLoss (src/operator/contrib/ctc_loss.cc)
  - AdaptiveAvgPooling2D / BilinearResize2D
    (src/operator/contrib/{adaptive_avg_pooling,bilinear_resize}.cc)
  - fft/ifft, count_sketch, khatri_rao, quadratic
    (src/operator/contrib/{fft,ifft,count_sketch,krprod,quadratic_op}.cc)

TPU-first design notes. The reference implements these with sequential CPU
loops / handwritten CUDA; none of that survives here. Everything below is
fixed-shape XLA: greedy matching and NMS become `lax.fori_loop`s over masked
argmax/top-k (O(k) compiled steps, each a vectorized reduction on-device),
compaction becomes stable-argsort gathers (differentiable — jax's vjp of
`take` is the scatter the reference hand-writes as nms_backward), bin pooling
(ROI/adaptive) becomes separable masked reductions, and CTC's alpha recursion
is a `lax.scan` in log space whose autodiff *is* the beta pass. No dynamic
shapes anywhere: suppressed/invalid rows are encoded as -1, as the reference
does, which keeps every output shape static for jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .registry import Param, register

_NEG = -1e30


def _t(*outs):
    return tuple(outs)


def _flat_batch(x, keep_last):
    """Collapse leading dims, keeping the last `keep_last` dims."""
    lead = x.shape[:-keep_last] if keep_last else x.shape
    flat = 1
    for d in lead:
        flat *= d
    return x.reshape((flat,) + x.shape[len(lead):]), lead


def _corner_wh(boxes):
    """(…,4) corner boxes -> width, height (clamped at 0 for area)."""
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    return w, h


def _box_area(boxes, fmt="corner"):
    if fmt == "corner":
        w, h = _corner_wh(boxes)
    else:
        w, h = boxes[..., 2], boxes[..., 3]
    return jnp.where((w < 0) | (h < 0), 0.0, w * h)


def _to_corner(boxes):
    x, y, w, h = (boxes[..., 0], boxes[..., 1],
                  boxes[..., 2] / 2, boxes[..., 3] / 2)
    return jnp.stack([x - w, y - h, x + w, y + h], axis=-1)


def _round_half_away(v):
    """C round(): half away from zero — NOT numpy/jax banker's rounding
    (roi_pooling.cc:69, psroi_pooling.cu:72)."""
    return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)


def _pair_iou(a, b, fmt="corner"):
    """IoU of every a-box against every b-box: a (…,A,4), b (…,B,4) ->
    (…,A,B). Matches CalculateOverlap (multibox_detection.cc:75): u<=0 -> 0."""
    if fmt == "center":
        a, b = _to_corner(a), _to_corner(b)
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = _box_area(a)[..., :, None]
    area_b = _box_area(b)[..., None, :]
    union = area_a + area_b - inter
    return jnp.where(union <= 0, 0.0, inter / union)


# ---------------------------------------------------------------------------
# MultiBoxPrior (src/operator/contrib/multibox_prior.cc:43-71)
# ---------------------------------------------------------------------------

def _multibox_prior(attrs, octx, data):
    h, w = int(data.shape[2]), int(data.shape[3])
    sizes, ratios = attrs["sizes"], attrs["ratios"]
    step_y, step_x = attrs["steps"]
    off_y, off_x = attrs["offsets"]
    if step_y <= 0 or step_x <= 0:
        step_y, step_x = 1.0 / h, 1.0 / w
    # Anchor half-extents per location: every size at ratio[0]=1, then every
    # extra ratio at size[0]; widths aspect-corrected by h/w (caffe-SSD
    # convention the reference keeps, multibox_prior.cc:50).
    half_w = [s * h / w / 2 for s in sizes]
    half_h = [s / 2 for s in sizes]
    for r in ratios[1:]:
        sr = math.sqrt(r)
        half_w.append(sizes[0] * h / w * sr / 2)
        half_h.append(sizes[0] / sr / 2)
    hw = jnp.asarray(half_w, data.dtype)          # (A,)
    hh = jnp.asarray(half_h, data.dtype)
    a = hw.shape[0]
    cy = (jnp.arange(h, dtype=data.dtype) + off_y) * step_y
    cx = (jnp.arange(w, dtype=data.dtype) + off_x) * step_x
    cyg = jnp.broadcast_to(cy[:, None, None], (h, w, a))
    cxg = jnp.broadcast_to(cx[None, :, None], (h, w, a))
    out = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh], axis=-1)
    out = out.reshape(1, h * w * a, 4)
    if attrs["clip"]:
        out = jnp.clip(out, 0.0, 1.0)
    return _t(out)


def _multibox_prior_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    a = len(attrs["sizes"]) + len(attrs["ratios"]) - 1
    return in_shapes, [(1, ds[2] * ds[3] * a, 4)]


register("_contrib_MultiBoxPrior", _multibox_prior,
         params={"sizes": Param("floats", (1.0,)),
                 "ratios": Param("floats", (1.0,)),
                 "clip": Param("bool", False),
                 "steps": Param("floats", (-1.0, -1.0)),
                 "offsets": Param("floats", (0.5, 0.5))},
         inputs=("data",), infer_shape=_multibox_prior_infer)


# ---------------------------------------------------------------------------
# MultiBoxTarget (src/operator/contrib/multibox_target.cc:70-280)
# ---------------------------------------------------------------------------

def _encode_loc(anchors, gt):
    """SSD box encoding (multibox_target.cc AssignLocTargets :32-55); the
    variance division is applied by the caller."""
    aw, ah = _corner_wh(anchors)
    ax = (anchors[..., 0] + anchors[..., 2]) * 0.5
    ay = (anchors[..., 1] + anchors[..., 3]) * 0.5
    gw, gh = _corner_wh(gt)
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(jnp.maximum(gw, 1e-12) / aw),
                      jnp.log(jnp.maximum(gh, 1e-12) / ah)], axis=-1)


def _multibox_target(attrs, octx, anchor, label, cls_pred):
    anchors = anchor.reshape(-1, 4)                       # (A,4)
    na = anchors.shape[0]
    nl = label.shape[1]
    thresh = attrs["overlap_threshold"]
    ignore = attrs["ignore_label"]
    vx, vy, vw, vh = attrs["variances"]
    mine_ratio = attrs["negative_mining_ratio"]
    mine_thresh = attrs["negative_mining_thresh"]

    def one_sample(lab, cpred):
        # valid gts: reference stops at the first class-id == -1 row
        not_pad = lab[:, 0] != -1.0
        valid = jnp.cumprod(not_pad.astype(jnp.int32)).astype(bool)   # (L,)
        has_gt = valid[0]
        gt_boxes = lab[:, 1:5]
        ious = _pair_iou(anchors, gt_boxes)                # (A, L)
        ious = jnp.where(valid[None, :], ious, -1.0)

        # stage 1 — greedy bipartite matching: repeatedly take the global
        # best (anchor, gt) pair among the unmatched, one gt per iteration.
        def bi_body(_, st):
            a_matched, g_matched, m_gt, m_iou = st
            m = jnp.where(a_matched[:, None] | g_matched[None, :], _NEG, ious)
            flat = jnp.argmax(m)
            bi, bk = flat // nl, flat % nl
            ok = m[bi, bk] > 1e-6
            a_matched = a_matched.at[bi].set(a_matched[bi] | ok)
            g_matched = g_matched.at[bk].set(g_matched[bk] | ok)
            m_gt = m_gt.at[bi].set(jnp.where(ok, bk, m_gt[bi]))
            m_iou = m_iou.at[bi].set(jnp.where(ok, m[bi, bk], m_iou[bi]))
            return a_matched, g_matched, m_gt, m_iou

        a_matched, _, m_gt, m_iou = jax.lax.fori_loop(
            0, nl, bi_body,
            (jnp.zeros(na, bool), jnp.zeros(nl, bool),
             jnp.full(na, -1), jnp.full(na, -1.0)))

        # stage 2 — threshold matching for anchors the bipartite pass missed
        best_gt = jnp.argmax(ious, axis=1)
        best_iou = jnp.max(ious, axis=1)
        thr_pos = (~a_matched) & (best_iou > thresh) if thresh > 0 else \
            jnp.zeros(na, bool)
        positive = a_matched | thr_pos
        m_gt = jnp.where(a_matched, m_gt, best_gt)
        m_iou = jnp.where(a_matched, m_iou, best_iou)

        if mine_ratio > 0:
            # hard-negative mining: among non-positive anchors whose best
            # IoU < mining threshold, keep the num_pos*ratio with the
            # highest background-class probability deficit
            num_pos = jnp.sum(positive)
            num_neg = jnp.maximum((num_pos * mine_ratio).astype(jnp.int32),
                                  attrs["minimum_negative_samples"])
            num_neg = jnp.minimum(num_neg, na - num_pos)
            cand = (~positive) & (m_iou < mine_thresh)
            bg_prob = jax.nn.softmax(cpred, axis=0)[0]     # (A,)
            score = jnp.where(cand, -bg_prob, _NEG)
            order = jnp.argsort(-score, stable=True)
            rank = jnp.argsort(order, stable=True)
            negative = cand & (rank < num_neg)
        else:
            negative = ~positive

        cls_t = jnp.where(positive, lab[m_gt, 0] + 1.0,
                          jnp.where(negative, 0.0, ignore))
        loc = _encode_loc(anchors, gt_boxes[m_gt]) / jnp.asarray(
            [vx, vy, vw, vh], anchors.dtype)
        mask4 = jnp.broadcast_to(positive[:, None], (na, 4)).astype(
            anchors.dtype)
        loc_t = jnp.where(positive[:, None], loc, 0.0) * mask4
        # a batch item with zero ground truths keeps the init values
        # (loc 0 / mask 0 / cls ignore_label — multibox_target-inl.h:122-124)
        cls_t = jnp.where(has_gt, cls_t, ignore)
        loc_t = jnp.where(has_gt, loc_t, 0.0)
        mask4 = jnp.where(has_gt, mask4, 0.0)
        return loc_t.reshape(-1), mask4.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one_sample)(label, cls_pred)
    return _t(loc_t, loc_m, cls_t)


def _multibox_target_infer(attrs, in_shapes):
    ash, lsh, csh = in_shapes
    if ash is None or lsh is None:
        return in_shapes, [None, None, None]
    na, nb = ash[1], lsh[0]
    return in_shapes, [(nb, na * 4), (nb, na * 4), (nb, na)]


register("_contrib_MultiBoxTarget", _multibox_target,
         params={"overlap_threshold": Param("float", 0.5),
                 "ignore_label": Param("float", -1.0),
                 "negative_mining_ratio": Param("float", -1.0),
                 "negative_mining_thresh": Param("float", 0.5),
                 "minimum_negative_samples": Param("int", 0),
                 "variances": Param("floats", (0.1, 0.1, 0.2, 0.2))},
         inputs=("anchor", "label", "cls_pred"), num_outputs=3,
         infer_shape=_multibox_target_infer)


# ---------------------------------------------------------------------------
# MultiBoxDetection (src/operator/contrib/multibox_detection.cc:46-170)
# ---------------------------------------------------------------------------

def _decode_loc(anchors, loc, variances, clip):
    vx, vy, vw, vh = variances
    aw, ah = _corner_wh(anchors)
    ax = (anchors[..., 0] + anchors[..., 2]) * 0.5
    ay = (anchors[..., 1] + anchors[..., 3]) * 0.5
    ox = loc[..., 0] * vx * aw + ax
    oy = loc[..., 1] * vy * ah + ay
    ow = jnp.exp(loc[..., 2] * vw) * aw / 2
    oh = jnp.exp(loc[..., 3] * vh) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _nms_keep(boxes, ids, valid, k, thresh, force):
    """Greedy NMS over the first k (sorted) rows. Returns the kept mask.
    Sequential in refs, O(k) fori_loop of vectorized suppressions here —
    the same wavefront scheme as the reference GPU kernel
    (bounding_box-inl.h nms_impl :259-286)."""
    n = boxes.shape[0]
    idx = jnp.arange(n)

    def body(ref, keep):
        ref_ok = keep[ref] & valid[ref]
        ious = _pair_iou(boxes[ref][None, :], boxes)[0]    # (n,)
        same = jnp.full(n, True) if force else (ids == ids[ref])
        sup = (idx > ref) & (idx < k) & ref_ok & keep & same & \
            (ious >= thresh)
        return keep & ~sup

    return jax.lax.fori_loop(0, n, body, valid & (idx < k))


def _multibox_detection(attrs, octx, cls_prob, loc_pred, anchor):
    if attrs["background_id"] != 0:
        # the reference kernel also hardcodes class 0 as background
        # (multibox_detection.cc:107 loops j=1..C); error instead of
        # silently mislabeling
        raise MXNetError("MultiBoxDetection: only background_id=0 is "
                         "supported")
    anchors = anchor.reshape(-1, 4)
    variances = attrs["variances"]
    threshold = attrs["threshold"]
    nms_thresh = attrs["nms_threshold"]
    topk = attrs["nms_topk"]
    na = anchors.shape[0]

    def one_sample(cprob, lpred):
        fg = cprob[1:, :]                                   # (C-1, A)
        score = jnp.max(fg, axis=0)
        cid = jnp.argmax(fg, axis=0).astype(cprob.dtype)    # 0-based fg class
        valid = score >= threshold
        boxes = _decode_loc(anchors, lpred.reshape(na, 4), variances,
                            attrs["clip"])
        # pack valid rows first, ordered by descending score (stable)
        key = jnp.where(valid, score, _NEG)
        order = jnp.argsort(-key, stable=True)
        s_score, s_cid = score[order], cid[order]
        s_boxes, s_valid = boxes[order], valid[order]
        nvalid = jnp.sum(valid)
        k = jnp.minimum(nvalid, topk) if topk > 0 else nvalid
        keep = _nms_keep(s_boxes, s_cid, s_valid, k, nms_thresh,
                         attrs["force_suppress"])
        if not (0 < nms_thresh <= 1):
            keep = s_valid
        out_id = jnp.where(keep, s_cid, -1.0)
        row = jnp.concatenate([out_id[:, None], s_score[:, None], s_boxes],
                              axis=1)
        return jnp.where(s_valid[:, None], row,
                         jnp.full((na, 6), -1.0, cprob.dtype))

    return _t(jax.vmap(one_sample)(cls_prob, loc_pred))


def _multibox_detection_infer(attrs, in_shapes):
    csh = in_shapes[0]
    if csh is None:
        return in_shapes, [None]
    return in_shapes, [(csh[0], csh[2], 6)]


register("_contrib_MultiBoxDetection", _multibox_detection,
         params={"clip": Param("bool", True),
                 "threshold": Param("float", 0.01),
                 "background_id": Param("int", 0),
                 "nms_threshold": Param("float", 0.5),
                 "force_suppress": Param("bool", False),
                 "variances": Param("floats", (0.1, 0.1, 0.2, 0.2)),
                 "nms_topk": Param("int", -1)},
         inputs=("cls_prob", "loc_pred", "anchor"),
         infer_shape=_multibox_detection_infer)


# ---------------------------------------------------------------------------
# box_nms / box_iou / bipartite_matching (src/operator/contrib/bounding_box.cc)
# ---------------------------------------------------------------------------

def _box_nms(attrs, octx, data):
    thresh = attrs["overlap_thresh"]
    valid_thresh = attrs["valid_thresh"]
    topk = attrs["topk"]
    cs, si, ii = attrs["coord_start"], attrs["score_index"], attrs["id_index"]
    force = attrs["force_suppress"]
    in_fmt, out_fmt = attrs["in_format"], attrs["out_format"]

    flat, lead = _flat_batch(data, 2)
    n = flat.shape[1]
    k = n if topk < 0 else min(n, topk)
    if k < 1:
        return _t(data)

    def one(rows):
        scores = rows[:, si]
        valid = scores > valid_thresh
        # invalid rows sort to the back and never enter the candidate set
        order = jnp.argsort(jnp.where(valid, -scores, _NEG * -1),
                            stable=True)
        srows = rows[order]
        boxes = srows[:, cs:cs + 4]
        if in_fmt == "center":
            boxes = _to_corner(boxes)
        ids = srows[:, ii] if ii >= 0 else jnp.zeros(n, rows.dtype)
        keep = _nms_keep_strict(boxes, ids, k, thresh, force)
        keep = keep & valid[order]
        # pack survivors to the front (score order preserved), -1 elsewhere
        pack = jnp.argsort(~keep, stable=True)
        out = srows[pack]
        kept_row = jnp.arange(n) < jnp.sum(keep)
        if in_fmt != out_fmt:
            conv = _to_corner(out[:, cs:cs + 4]) if out_fmt == "corner" \
                else _from_corner(out[:, cs:cs + 4])
            # rebuild the row (an aliased .at[].set of a slice computed from
            # itself miscompiles on the CPU backend under jit)
            out = jnp.concatenate([out[:, :cs], conv, out[:, cs + 4:]],
                                  axis=1)
        return jnp.where(kept_row[:, None], out, -1.0)

    out = jax.vmap(one)(flat)
    return _t(out.reshape(data.shape))


def _from_corner(boxes):
    l, t, r, b = (boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3])
    return jnp.stack([(l + r) / 2, (t + b) / 2, r - l, b - t], axis=-1)


def _nms_keep_strict(boxes, ids, k, thresh, force):
    """box_nms variant: all rows are candidates, suppression is iou > thresh
    (strictly greater, unlike MultiBoxDetection's >=)."""
    n = boxes.shape[0]
    idx = jnp.arange(n)

    def body(ref, keep):
        ious = _pair_iou(boxes[ref][None, :], boxes)[0]
        same = jnp.full(n, True) if force else (ids == ids[ref])
        sup = (idx > ref) & (idx < k) & keep[ref] & keep & same & \
            (ious > thresh)
        return keep & ~sup

    return jax.lax.fori_loop(0, n, body, idx < k)


register("_contrib_box_nms", _box_nms,
         params={"overlap_thresh": Param("float", 0.5),
                 "valid_thresh": Param("float", 0.0),
                 "topk": Param("int", -1),
                 "coord_start": Param("int", 2),
                 "score_index": Param("int", 1),
                 "id_index": Param("int", -1),
                 "force_suppress": Param("bool", False),
                 "in_format": Param("str", "corner"),
                 "out_format": Param("str", "corner")},
         inputs=("data",),
         aliases=("_contrib_box_non_maximum_suppression",))


def _box_iou(attrs, octx, lhs, rhs):
    fmt = attrs["format"]
    a, alead = _flat_batch(lhs, 1)     # (A,4) after collapsing leading dims
    b, blead = _flat_batch(rhs, 1)
    iou = _pair_iou(a, b, fmt)
    return _t(iou.reshape(alead + blead))


register("_contrib_box_iou", _box_iou,
         params={"format": Param("str", "corner")},
         inputs=("lhs", "rhs"))


def _bipartite_matching(attrs, octx, data):
    thresh = attrs["threshold"]
    is_ascend = attrs["is_ascend"]
    topk = attrs["topk"]
    flat, lead = _flat_batch(data, 2)
    nr, nc = flat.shape[1], flat.shape[2]

    def one(score):
        s = -score if is_ascend else score
        bound = -thresh if is_ascend else thresh

        def body(_, st):
            rmark, cmark, count = st
            m = jnp.where((rmark[:, None] == -1) & (cmark[None, :] == -1),
                          s, _NEG)
            flat_i = jnp.argmax(m)
            r, c = flat_i // nc, flat_i % nc
            ok = m[r, c] > bound
            if topk > 0:
                ok = ok & (count < topk)
            rmark = rmark.at[r].set(jnp.where(ok, c, rmark[r]))
            cmark = cmark.at[c].set(jnp.where(ok, r, cmark[c]))
            return rmark, cmark, count + ok.astype(jnp.int32)

        rmark, cmark, _ = jax.lax.fori_loop(
            0, min(nr, nc), body,
            (jnp.full(nr, -1.0, score.dtype),
             jnp.full(nc, -1.0, score.dtype), jnp.asarray(0)))
        return rmark, cmark

    rm, cm = jax.vmap(one)(flat)
    return _t(rm.reshape(lead + (nr,)), cm.reshape(lead + (nc,)))


def _bipartite_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None, None]
    return in_shapes, [tuple(ds[:-1]), tuple(ds[:-2]) + (ds[-1],)]


register("_contrib_bipartite_matching", _bipartite_matching,
         params={"is_ascend": Param("bool", False),
                 "threshold": Param("float", None, True),
                 "topk": Param("int", -1)},
         inputs=("data",), num_outputs=2, infer_shape=_bipartite_infer)

# ---------------------------------------------------------------------------
# ROIPooling (src/operator/roi_pooling.cc:44-120)
# ---------------------------------------------------------------------------

def _bin_masks(length, nbins, start, size, dtype=jnp.float32):
    """Membership masks of `nbins` ROI bins over a `length` axis.

    Bin i covers [start + floor(i*size/nbins), start + ceil((i+1)*size/nbins))
    clipped to [0, length) — the reference's per-bin loop bounds
    (roi_pooling.cc:96-104) expressed as a (nbins, length) mask so pooling
    becomes a separable masked reduction instead of dynamic slicing.
    """
    i = jnp.arange(nbins, dtype=dtype)
    lo = start + jnp.floor(i * size / nbins)
    hi = start + jnp.ceil((i + 1) * size / nbins)
    pos = jnp.arange(length, dtype=dtype)[None, :]
    return (pos >= jnp.clip(lo, 0, length)[:, None]) & \
           (pos < jnp.clip(hi, 0, length)[:, None])


def _roi_pooling(attrs, octx, data, rois):
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    n, c, h, w = data.shape

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = _round_half_away(roi[1] * scale)
        y1 = _round_half_away(roi[2] * scale)
        x2 = _round_half_away(roi[3] * scale)
        y2 = _round_half_away(roi[4] * scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        img = data[jnp.clip(bidx, 0, n - 1)]               # (C,H,W)
        mh = _bin_masks(h, ph, y1, rh, data.dtype)          # (ph,H)
        mw = _bin_masks(w, pw, x1, rw, data.dtype)          # (pw,W)
        # separable masked max: over W first, then H
        t = jnp.where(mw[None, :, None, :], img[:, None, :, :], _NEG)
        # (C,pw,H,W)
        t = jnp.max(t, axis=3)                              # (C,pw,H)
        o = jnp.where(mh[None, :, None, :], t[:, None, :, :], _NEG)
        o = jnp.max(o, axis=3)                              # (C,ph,pw)
        return jnp.where(o <= _NEG / 2, 0.0, o)             # empty bins -> 0

    return _t(jax.vmap(one_roi)(rois))


def _roi_pooling_infer(attrs, in_shapes):
    ds, rs = in_shapes
    if ds is None or rs is None:
        return in_shapes, [None]
    ph, pw = attrs["pooled_size"]
    return in_shapes, [(rs[0], ds[1], ph, pw)]


register("ROIPooling", _roi_pooling,
         params={"pooled_size": Param("shape", None, True),
                 "spatial_scale": Param("float", None, True)},
         inputs=("data", "rois"), infer_shape=_roi_pooling_infer)


# ---------------------------------------------------------------------------
# BilinearSampler / GridGenerator / SpatialTransformer
# (src/operator/bilinear_sampler.cc, grid_generator.cc, spatial_transformer.cc)
# ---------------------------------------------------------------------------

def _bilinear_sample(img, gx, gy):
    """Sample img (C,H,W) at real coords gx,gy (Ho,Wo); zero outside.
    between-the-grid behavior of BilinearSamplerForward
    (src/operator/bilinear_sampler.cc:40-80)."""
    c, h, w = img.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def at(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = img[:, yc, xc]                                  # (C,Ho,Wo)
        return jnp.where(inb[None], v, 0.0)

    tl = at(y0, x0)
    tr = at(y0, x0 + 1)
    bl = at(y0 + 1, x0)
    br = at(y0 + 1, x0 + 1)
    top = tl * (1 - wx) + tr * wx
    bot = bl * (1 - wx) + br * wx
    return top * (1 - wy) + bot * wy


def _bilinear_sampler(attrs, octx, data, grid):
    def one(img, g):
        # grid in [-1,1]: x_src = (x+1)*(W-1)/2 (bilinear_sampler-inl.h)
        gx = (g[0] + 1.0) * (img.shape[2] - 1) / 2.0
        gy = (g[1] + 1.0) * (img.shape[1] - 1) / 2.0
        return _bilinear_sample(img, gx, gy)

    return _t(jax.vmap(one)(data, grid))


def _bilinear_sampler_infer(attrs, in_shapes):
    ds, gs = in_shapes
    if ds is None or gs is None:
        return in_shapes, [None]
    return in_shapes, [(ds[0], ds[1], gs[2], gs[3])]


register("BilinearSampler", _bilinear_sampler,
         inputs=("data", "grid"), infer_shape=_bilinear_sampler_infer)


def _normalized_meshgrid(h, w, dtype):
    """Target-grid coords in [-1,1], row-major (y, x)."""
    ys = jnp.linspace(-1.0, 1.0, h, dtype=dtype) if h > 1 else \
        jnp.zeros(1, dtype)
    xs = jnp.linspace(-1.0, 1.0, w, dtype=dtype) if w > 1 else \
        jnp.zeros(1, dtype)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    return gx, gy


def _grid_generator(attrs, octx, data):
    tt = attrs["transform_type"]
    if tt == "affine":
        h, w = attrs["target_shape"]
        gx, gy = _normalized_meshgrid(h, w, data.dtype)
        ones = jnp.ones_like(gx)
        tgt = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                         ones.reshape(-1)])                 # (3, H*W)

        def one(theta):
            src = theta.reshape(2, 3) @ tgt                 # (2, H*W)
            return src.reshape(2, h, w)

        return _t(jax.vmap(one)(data))
    elif tt == "warp":
        n, _, h, w = data.shape
        yy, xx = jnp.meshgrid(jnp.arange(h, dtype=data.dtype),
                              jnp.arange(w, dtype=data.dtype), indexing="ij")
        # flow-field displacement, renormalized to [-1,1]
        # (grid_generator-inl.h warp path)
        gx = (data[:, 0] + xx) * 2.0 / jnp.maximum(w - 1, 1) - 1.0
        gy = (data[:, 1] + yy) * 2.0 / jnp.maximum(h - 1, 1) - 1.0
        return _t(jnp.stack([gx, gy], axis=1))
    raise MXNetError(f"GridGenerator: unknown transform_type {tt!r}")


def _grid_generator_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    if attrs["transform_type"] == "affine":
        h, w = attrs["target_shape"]
        return in_shapes, [(ds[0], 2, h, w)]
    return in_shapes, [tuple(ds)]


register("GridGenerator", _grid_generator,
         params={"transform_type": Param("str", None, True),
                 "target_shape": Param("shape", (0, 0))},
         inputs=("data",), infer_shape=_grid_generator_infer)


def _spatial_transformer(attrs, octx, data, loc):
    h, w = attrs["target_shape"]
    gx, gy = _normalized_meshgrid(h, w, data.dtype)
    tgt = jnp.stack([gx.reshape(-1), gy.reshape(-1),
                     jnp.ones(h * w, data.dtype)])

    def one(img, theta):
        src = theta.reshape(2, 3) @ tgt
        sx = (src[0].reshape(h, w) + 1.0) * (img.shape[2] - 1) / 2.0
        sy = (src[1].reshape(h, w) + 1.0) * (img.shape[1] - 1) / 2.0
        return _bilinear_sample(img, sx, sy)

    return _t(jax.vmap(one)(data, loc))


def _spatial_transformer_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is not None and in_shapes[1] is None:
        in_shapes = [ds, (ds[0], 6)]
    if ds is None:
        return in_shapes, [None]
    h, w = attrs["target_shape"]
    return in_shapes, [(ds[0], ds[1], h, w)]


register("SpatialTransformer", _spatial_transformer,
         params={"target_shape": Param("shape", (0, 0)),
                 "transform_type": Param("str", "affine"),
                 "sampler_type": Param("str", "bilinear")},
         inputs=("data", "loc"), infer_shape=_spatial_transformer_infer)


# ---------------------------------------------------------------------------
# AdaptiveAvgPooling2D / BilinearResize2D (src/operator/contrib/)
# ---------------------------------------------------------------------------

def _adaptive_avg_pool(attrs, octx, data):
    osz = attrs["output_size"]
    n, c, h, w = data.shape
    if not osz:
        oh, ow = 1, 1
    elif len(osz) == 1:
        oh = ow = osz[0]
    else:
        oh, ow = osz
    mh = _bin_masks(h, oh, jnp.asarray(0.0), jnp.asarray(float(h)),
                    data.dtype).astype(data.dtype)           # (oh,H)
    mw = _bin_masks(w, ow, jnp.asarray(0.0), jnp.asarray(float(w)),
                    data.dtype).astype(data.dtype)           # (ow,W)
    mh = mh / jnp.sum(mh, axis=1, keepdims=True)
    mw = mw / jnp.sum(mw, axis=1, keepdims=True)
    # separable weighted average -> two small matmuls (MXU-friendly)
    out = jnp.einsum("nchw,oh->ncow", data, mh)
    out = jnp.einsum("ncow,pw->ncop", out, mw)
    return _t(out)


def _adaptive_avg_pool_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    osz = attrs["output_size"]
    if not osz:
        oh = ow = 1
    elif len(osz) == 1:
        oh = ow = osz[0]
    else:
        oh, ow = osz
    return in_shapes, [(ds[0], ds[1], oh, ow)]


register("_contrib_AdaptiveAvgPooling2D", _adaptive_avg_pool,
         params={"output_size": Param("shape", ())},
         inputs=("data",), infer_shape=_adaptive_avg_pool_infer)


def _bilinear_resize(attrs, octx, data):
    oh, ow = attrs["height"], attrs["width"]
    n, c, h, w = data.shape
    # align-corners interpolation: src = dst*(in-1)/(out-1)
    # (bilinear_resize-inl.h rheight/rwidth)
    gy = jnp.arange(oh, dtype=data.dtype) * \
        ((h - 1) / (oh - 1) if oh > 1 else 0.0)
    gx = jnp.arange(ow, dtype=data.dtype) * \
        ((w - 1) / (ow - 1) if ow > 1 else 0.0)
    gyy = jnp.broadcast_to(gy[:, None], (oh, ow))
    gxx = jnp.broadcast_to(gx[None, :], (oh, ow))
    out = jax.vmap(lambda img: _bilinear_sample(img, gxx, gyy))(data)
    return _t(out)


def _bilinear_resize_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [(ds[0], ds[1], attrs["height"], attrs["width"])]


register("_contrib_BilinearResize2D", _bilinear_resize,
         params={"height": Param("int", None, True),
                 "width": Param("int", None, True)},
         inputs=("data",), infer_shape=_bilinear_resize_infer)


# ---------------------------------------------------------------------------
# Correlation (src/operator/correlation.cc — FlowNet cost volume)
# ---------------------------------------------------------------------------

def _correlation(attrs, octx, data1, data2):
    k = attrs["kernel_size"]
    if k % 2 == 0:
        raise MXNetError("Correlation: kernel_size must be odd")
    md = attrs["max_displacement"]
    s1, s2 = attrs["stride1"], attrs["stride2"]
    pad = attrs["pad_size"]
    mul = attrs["is_multiply"]
    n, c, h, w = data1.shape
    kr = (k - 1) // 2
    border = md + kr
    ph, pw = h + 2 * pad, w + 2 * pad
    oh = int(math.ceil((ph - border * 2) / s1))
    ow = int(math.ceil((pw - border * 2) / s1))
    r = md // s2
    d = 2 * r + 1

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    # window sums via avg over kernel: reduce_window on the product volume.
    # Patch 1 window top-left for output (y,x): (y*s1 + md, x*s1 + md);
    # patch 2 is offset by the displacement (dy*s2, dx*s2).
    span_h = (oh - 1) * s1 + k
    span_w = (ow - 1) * s1 + k
    base1 = jax.lax.slice(p1, (0, 0, md, md),
                          (n, c, md + span_h, md + span_w))
    chans = []
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            oy, ox = md + dy * s2, md + dx * s2
            shifted = jax.lax.slice(p2, (0, 0, oy, ox),
                                    (n, c, oy + span_h, ox + span_w))
            prod = base1 * shifted if mul else jnp.abs(base1 - shifted)
            summed = jax.lax.reduce_window(
                prod, 0.0, jax.lax.add, (1, 1, k, k), (1, 1, s1, s1),
                "valid")
            chans.append(jnp.sum(summed, axis=1) / (k * k * c))
    return _t(jnp.stack(chans, axis=1))


def _correlation_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    k, md = attrs["kernel_size"], attrs["max_displacement"]
    s1, s2, pad = attrs["stride1"], attrs["stride2"], attrs["pad_size"]
    border = md + (k - 1) // 2
    oh = int(math.ceil((ds[2] + 2 * pad - border * 2) / s1))
    ow = int(math.ceil((ds[3] + 2 * pad - border * 2) / s1))
    d = 2 * (md // s2) + 1
    return in_shapes, [(ds[0], d * d, oh, ow)]


register("Correlation", _correlation,
         params={"kernel_size": Param("int", 1),
                 "max_displacement": Param("int", 1),
                 "stride1": Param("int", 1),
                 "stride2": Param("int", 1),
                 "pad_size": Param("int", 0),
                 "is_multiply": Param("bool", True)},
         inputs=("data1", "data2"), infer_shape=_correlation_infer)


# ---------------------------------------------------------------------------
# CTCLoss (src/operator/contrib/ctc_loss.cc) — log-space alpha recursion;
# jax autodiff of the scan replaces the handwritten beta/grad pass.
# ---------------------------------------------------------------------------

def _ctc_one(logp, lab, dlen, llen, blank):
    """Negative log likelihood for one sequence.
    logp (T, A) log-softmax scores; lab (L,) int labels; dlen/llen scalars."""
    t_max, _ = logp.shape
    l_max = lab.shape[0]
    s = 2 * l_max + 1
    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full(s, blank, lab.dtype)
    ext = ext.at[1::2].set(lab)
    pos = jnp.arange(s)
    valid_s = pos < 2 * llen + 1
    # transition-allowed-from-s-2: only for label positions with
    # ext[s] != ext[s-2] (standard CTC skip rule)
    ext_m2 = jnp.concatenate([jnp.full(2, -1, lab.dtype), ext[:-2]])
    can_skip = (pos % 2 == 1) & (ext != ext_m2)

    init = jnp.full(s, _NEG)
    init = init.at[0].set(logp[0, ext[0]])
    init = init.at[1].set(jnp.where(llen > 0, logp[0, ext[1]], _NEG))

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full(1, _NEG), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full(2, _NEG), alpha[:-2]])
        prev2 = jnp.where(can_skip, prev2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        new = merged + logp[t, ext]
        new = jnp.where(valid_s, new, _NEG)
        new = jnp.where(t < dlen, new, alpha)   # freeze past data length
        return new, None

    alpha, _ = jax.lax.scan(step, init, jnp.arange(1, t_max))
    end1 = alpha[2 * llen]
    end2 = jnp.where(llen > 0, alpha[2 * llen - 1], _NEG)
    return -jnp.logaddexp(end1, end2)


def _ctc_loss(attrs, octx, data, label, *rest):
    # optional length inputs arrive positionally — dispatch on the flags,
    # not on argument position (use_label_lengths alone must NOT bind the
    # lengths array to data_lengths)
    data_lengths = label_lengths = None
    i = 0
    if attrs["use_data_lengths"]:
        data_lengths = rest[i]
        i += 1
    if attrs["use_label_lengths"]:
        label_lengths = rest[i]
        i += 1
    t_max, b, a = data.shape
    blank_first = attrs["blank_label"] == "first"
    blank = 0 if blank_first else a - 1
    pad = 0 if blank_first else -1
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    if label_lengths is not None:
        llen = label_lengths.astype(jnp.int32)
    else:
        llen = jnp.sum((lab != pad).astype(jnp.int32), axis=-1)
    dlen = data_lengths.astype(jnp.int32) if data_lengths is not None \
        else jnp.full(b, t_max, jnp.int32)
    loss = jax.vmap(_ctc_one, in_axes=(1, 0, 0, 0, None))(
        logp, lab, dlen, llen, blank)
    return _t(loss)


def _ctc_inputs(attrs):
    names = ["data", "label"]
    if attrs["use_data_lengths"]:
        names.append("data_lengths")
    if attrs["use_label_lengths"]:
        names.append("label_lengths")
    return names


def _ctc_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [(ds[1],)]


_ctc_schema = register(
    "CTCLoss", _ctc_loss,
    params={"use_data_lengths": Param("bool", False),
            "use_label_lengths": Param("bool", False),
            "blank_label": Param("str", "first")},
    inputs=("data", "label", "data_lengths", "label_lengths"),
    infer_shape=_ctc_infer,
    aliases=("ctc_loss", "_contrib_ctc_loss", "_contrib_CTCLoss"))
_ctc_schema.list_inputs = _ctc_inputs  # type: ignore[method-assign]
_ctc_schema.num_inputs = lambda attrs: len(_ctc_inputs(attrs))  # type: ignore


# ---------------------------------------------------------------------------
# fft / ifft (src/operator/contrib/fft.cc, ifft.cc — cuFFT role -> jnp.fft)
# ---------------------------------------------------------------------------

def _contrib_fft(attrs, octx, data):
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    # cufftComplex layout: interleaved (re, im) pairs, last dim doubled
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return _t(out.reshape(data.shape[:-1] + (2 * data.shape[-1],))
              .astype(data.dtype))


def _contrib_fft_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [tuple(ds[:-1]) + (2 * ds[-1],)]


register("_contrib_fft", _contrib_fft,
         params={"compute_size": Param("int", 128)},
         inputs=("data",), infer_shape=_contrib_fft_infer)


def _contrib_ifft(attrs, octx, data):
    d = data.shape[-1] // 2
    pairs = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    spec = jax.lax.complex(pairs[..., 0], pairs[..., 1])
    # cuFFT CUFFT_INVERSE is unnormalized: multiply the 1/N back out
    out = jnp.fft.ifft(spec, axis=-1).real * d
    return _t(out.astype(data.dtype))


def _contrib_ifft_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [tuple(ds[:-1]) + (ds[-1] // 2,)]


register("_contrib_ifft", _contrib_ifft,
         params={"compute_size": Param("int", 128)},
         inputs=("data",), infer_shape=_contrib_ifft_infer)


# ---------------------------------------------------------------------------
# count_sketch (src/operator/contrib/count_sketch.cc) + khatri_rao (krprod.cc)
# + quadratic (quadratic_op.cc — the "write your own op" tutorial op)
# ---------------------------------------------------------------------------

def _count_sketch(attrs, octx, data, h, s):
    out_dim = attrs["out_dim"]
    hh = h.reshape(-1).astype(jnp.int32)                   # (in_dim,)
    ss = s.reshape(-1).astype(data.dtype)
    signed = data * ss[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return _t(out.at[..., hh].add(signed))


def _count_sketch_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [tuple(ds[:-1]) + (attrs["out_dim"],)]


register("_contrib_count_sketch", _count_sketch,
         params={"out_dim": Param("int", None, True),
                 "processing_batch_size": Param("int", 32)},
         inputs=("data", "h", "s"), infer_shape=_count_sketch_infer)


def _khatri_rao(attrs, octx, *mats):
    # column-wise Khatri-Rao: all matrices share the column count; rows
    # Kronecker-multiply (krprod.cc khatri_rao)
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[1])
    return _t(out)


def _khatri_rao_infer(attrs, in_shapes):
    if any(s is None for s in in_shapes):
        return in_shapes, [None]
    rows = 1
    for s in in_shapes:
        rows *= s[0]
    return in_shapes, [(rows, in_shapes[0][1])]


register("khatri_rao", _khatri_rao,
         params={"num_args": Param("int", None, True)},
         inputs=("args",), key_var_num_args="num_args",
         infer_shape=_khatri_rao_infer)


def _quadratic(attrs, octx, data):
    return _t(attrs["a"] * data * data + attrs["b"] * data + attrs["c"])


register("_contrib_quadratic", _quadratic,
         params={"a": Param("float", 0.0), "b": Param("float", 0.0),
                 "c": Param("float", 0.0)},
         inputs=("data",))


# ---------------------------------------------------------------------------
# R-CNN family: Proposal / MultiProposal (src/operator/contrib/proposal.cc,
# multi_proposal.cc), PSROIPooling (psroi_pooling.cu), DeformableConvolution
# (deformable_convolution.cc), DeformablePSROIPooling
# (deformable_psroi_pooling.cu)
# ---------------------------------------------------------------------------

def _rpn_base_anchors(feature_stride, ratios, scales):
    """py-faster-rcnn anchor table (proposal-inl.h GenerateAnchors :214,
    _Transform :196): ratios outer, scales inner; +1-width conventions."""
    # feature_stride comes from the op's static attrs (python number),
    # never a tracer  # analysis: allow=trace-host-cast
    fs = float(feature_stride)
    w = h = fs
    x_ctr = y_ctr = (fs - 1.0) / 2.0
    size = w * h
    rows = []
    for ratio in ratios:
        size_ratio = math.floor(size / ratio)
        new_w = math.floor(math.sqrt(size_ratio) + 0.5)
        new_h = math.floor(new_w * ratio + 0.5)
        for scale in scales:
            sw, sh = new_w * scale, new_h * scale
            rows.append([x_ctr - 0.5 * (sw - 1), y_ctr - 0.5 * (sh - 1),
                         x_ctr + 0.5 * (sw - 1), y_ctr + 0.5 * (sh - 1)])
    return _np.asarray(rows, _np.float32)


def _proposal_one(fg_scores, deltas, iminfo, attrs):
    """RPN proposal generation for a single image.
    fg_scores (A,H,W), deltas (4A,H,W), iminfo (3,)."""
    a, h, w = fg_scores.shape
    fs = attrs["feature_stride"]
    if a != len(attrs["ratios"]) * len(attrs["scales"]):
        # proposal.cc:341 CHECK_EQ(num_anchors, ratios * scales)
        raise MXNetError(
            f"Proposal: cls_prob has {a} anchors per position but "
            f"ratios x scales = "
            f"{len(attrs['ratios']) * len(attrs['scales'])}")
    base = jnp.asarray(_rpn_base_anchors(fs, attrs["ratios"],
                                         attrs["scales"]))
    sx = jnp.arange(w, dtype=fg_scores.dtype) * fs
    sy = jnp.arange(h, dtype=fg_scores.dtype) * fs
    shift = jnp.stack(
        [jnp.broadcast_to(sx[None, :], (h, w)),
         jnp.broadcast_to(sy[:, None], (h, w)),
         jnp.broadcast_to(sx[None, :], (h, w)),
         jnp.broadcast_to(sy[:, None], (h, w))], axis=-1)    # (H,W,4)
    anchors = shift[:, :, None, :] + base[None, None]        # (H,W,A,4)

    d = deltas.reshape(a, 4, h, w).transpose(2, 3, 0, 1)     # (H,W,A,4)
    im_h, im_w, im_scale = iminfo[0], iminfo[1], iminfo[2]
    if attrs["iou_loss"]:
        pred = anchors + d
    else:
        # +1-width box decode (proposal.cc BBoxTransformInv :37-90)
        aw = anchors[..., 2] - anchors[..., 0] + 1.0
        ah = anchors[..., 3] - anchors[..., 1] + 1.0
        ax = anchors[..., 0] + 0.5 * (aw - 1.0)
        ay = anchors[..., 1] + 0.5 * (ah - 1.0)
        cx = d[..., 0] * aw + ax
        cy = d[..., 1] * ah + ay
        pw = jnp.exp(d[..., 2]) * aw
        phh = jnp.exp(d[..., 3]) * ah
        pred = jnp.stack([cx - 0.5 * (pw - 1), cy - 0.5 * (phh - 1),
                          cx + 0.5 * (pw - 1), cy + 0.5 * (phh - 1)],
                         axis=-1)
    lo = jnp.zeros(4, pred.dtype)
    hi = jnp.stack([im_w - 1, im_h - 1, im_w - 1, im_h - 1])
    pred = jnp.clip(pred, lo, hi)

    score = jnp.transpose(fg_scores, (1, 2, 0))              # (H,W,A)
    # drop anchors in the padded region beyond the true image extent
    real_h = jnp.floor(im_h / fs)
    real_w = jnp.floor(im_w / fs)
    inside = (jnp.arange(h)[:, None, None] < real_h) & \
             (jnp.arange(w)[None, :, None] < real_w)
    score = jnp.where(inside, score, -1.0)
    # drop boxes smaller than rpn_min_size (scaled to input image)
    min_size = attrs["rpn_min_size"] * im_scale
    iw = pred[..., 2] - pred[..., 0] + 1.0
    ih = pred[..., 3] - pred[..., 1] + 1.0
    score = jnp.where((iw < min_size) | (ih < min_size), -1.0, score)

    boxes = pred.reshape(-1, 4)
    score = score.reshape(-1)
    count = boxes.shape[0]
    pre_n = attrs["rpn_pre_nms_top_n"]
    pre_n = count if pre_n <= 0 else min(pre_n, count)
    post_n = min(attrs["rpn_post_nms_top_n"], pre_n)

    top_scores, top_idx = jax.lax.top_k(score, pre_n)
    top_boxes = boxes[top_idx]
    # +1-width pixel IoU (proposal.cc NonMaximumSuppression computes areas
    # as (x2-x1+1)*(y2-y1+1)): shift the far corners by one before the
    # standard corner IoU
    nms_boxes = top_boxes + jnp.asarray([0.0, 0.0, 1.0, 1.0],
                                        top_boxes.dtype)
    keep = _nms_keep(nms_boxes, jnp.zeros(pre_n), jnp.full(pre_n, True),
                     pre_n, attrs["threshold"], True)
    pack = jnp.argsort(~keep, stable=True)
    nkept = jnp.maximum(jnp.sum(keep), 1)
    # pad to post_n by cycling kept proposals (proposal.cc :405-420)
    slots = jnp.mod(jnp.arange(post_n), nkept)
    sel = pack[slots]
    return top_boxes[sel], top_scores[sel]


def _proposal(attrs, octx, cls_prob, bbox_pred, im_info):
    if cls_prob.shape[0] != 1:
        # reference CHECKs batch==1 (proposal.cc:292); use MultiProposal
        raise MXNetError("Proposal supports batch size 1 only; use "
                         "_contrib_MultiProposal for batched inputs")
    a2 = cls_prob.shape[1]
    rois, scores = _proposal_one(cls_prob[0, a2 // 2:], bbox_pred[0],
                                 im_info[0], attrs)
    post_n = rois.shape[0]
    out = jnp.concatenate([jnp.zeros((post_n, 1), rois.dtype), rois], axis=1)
    return _t(out, scores[:, None])


_PROPOSAL_PARAMS = {
    "rpn_pre_nms_top_n": Param("int", 6000),
    "rpn_post_nms_top_n": Param("int", 300),
    "threshold": Param("float", 0.7),
    "rpn_min_size": Param("int", 16),
    "scales": Param("floats", (4.0, 8.0, 16.0, 32.0)),
    "ratios": Param("floats", (0.5, 1.0, 2.0)),
    "feature_stride": Param("int", 16),
    "output_score": Param("bool", False),
    "iou_loss": Param("bool", False),
}


def _proposal_infer(attrs, in_shapes):
    cs = in_shapes[0]
    if cs is None:
        return in_shapes, [None, None]
    count = (cs[1] // 2) * cs[2] * cs[3]
    pre = attrs["rpn_pre_nms_top_n"]
    pre = count if pre <= 0 else min(pre, count)
    post = min(attrs["rpn_post_nms_top_n"], pre)
    n = cs[0]
    return in_shapes, [(n * post, 5), (n * post, 1)]


register("_contrib_Proposal", _proposal, params=dict(_PROPOSAL_PARAMS),
         inputs=("cls_prob", "bbox_pred", "im_info"), num_outputs=2,
         infer_shape=_proposal_infer)


def _multi_proposal(attrs, octx, cls_prob, bbox_pred, im_info):
    a2 = cls_prob.shape[1]
    rois, scores = jax.vmap(
        lambda c, b, i: _proposal_one(c[a2 // 2:], b, i, attrs))(
        cls_prob, bbox_pred, im_info)
    n, post_n = rois.shape[:2]
    bidx = jnp.broadcast_to(
        jnp.arange(n, dtype=rois.dtype)[:, None, None], (n, post_n, 1))
    out = jnp.concatenate([bidx, rois], axis=2).reshape(n * post_n, 5)
    return _t(out, scores.reshape(n * post_n, 1))


register("_contrib_MultiProposal", _multi_proposal,
         params=dict(_PROPOSAL_PARAMS),
         inputs=("cls_prob", "bbox_pred", "im_info"), num_outputs=2,
         infer_shape=_proposal_infer)


def _psroi_channel_maps(pooled, group):
    """gh/gw index per bin (psroi_pooling.cu:100-103)."""
    g = _np.clip((_np.arange(pooled) * group) // pooled, 0, group - 1)
    return jnp.asarray(g, jnp.int32)


def _psroi_pooling(attrs, octx, data, rois):
    scale = attrs["spatial_scale"]
    od = attrs["output_dim"]
    p = attrs["pooled_size"]
    g = attrs["group_size"] or p
    n, channels, h, w = data.shape
    if channels != od * g * g:
        raise MXNetError(f"PSROIPooling: data channels {channels} != "
                         f"output_dim*group_size^2 = {od * g * g}")
    ghi = gwi = _psroi_channel_maps(p, g)

    def one_roi(roi):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, n - 1)
        x1 = _round_half_away(roi[1]) * scale
        y1 = _round_half_away(roi[2]) * scale
        x2 = (_round_half_away(roi[3]) + 1.0) * scale
        y2 = (_round_half_away(roi[4]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p
        i = jnp.arange(p, dtype=data.dtype)
        hs = jnp.clip(jnp.floor(i * bh + y1), 0, h)
        he = jnp.clip(jnp.ceil((i + 1) * bh + y1), 0, h)
        ws = jnp.clip(jnp.floor(i * bw + x1), 0, w)
        we = jnp.clip(jnp.ceil((i + 1) * bw + x1), 0, w)
        posh = jnp.arange(h, dtype=data.dtype)[None, :]
        posw = jnp.arange(w, dtype=data.dtype)[None, :]
        mh = ((posh >= hs[:, None]) & (posh < he[:, None])).astype(data.dtype)
        mw = ((posw >= ws[:, None]) & (posw < we[:, None])).astype(data.dtype)
        img = data[bidx].reshape(od, g, g, h, w)
        sel = img[:, ghi][:, :, gwi]                        # (od,p,p,H,W)
        tot = jnp.einsum("ocdhw,ch,dw->ocd", sel, mh, mw)
        area = mh.sum(1)[:, None] * mw.sum(1)[None, :]
        return jnp.where(area > 0, tot / jnp.maximum(area, 1.0), 0.0)

    return _t(jax.vmap(one_roi)(rois))


def _psroi_infer(attrs, in_shapes):
    ds, rs = in_shapes
    if ds is None or rs is None:
        return in_shapes, [None]
    p = attrs["pooled_size"]
    return in_shapes, [(rs[0], attrs["output_dim"], p, p)]


register("_contrib_PSROIPooling", _psroi_pooling,
         params={"spatial_scale": Param("float", None, True),
                 "output_dim": Param("int", None, True),
                 "pooled_size": Param("int", None, True),
                 "group_size": Param("int", 0)},
         inputs=("data", "rois"), infer_shape=_psroi_infer)


def _clamped_bilinear(img, gx, gy):
    """Bilinear sample with clamped coords + ±0.5-border zero mask
    (deformable_psroi_pooling.cu:40-68,146-152). img (C,H,W)."""
    c, h, w = img.shape
    ok = (gx >= -0.5) & (gx <= w - 0.5) & (gy >= -0.5) & (gy <= h - 0.5)
    gx = jnp.clip(gx, 0.0, w - 1.0)
    gy = jnp.clip(gy, 0.0, h - 1.0)
    x1 = jnp.floor(gx)
    y1 = jnp.floor(gy)
    dx = gx - x1
    dy = gy - y1
    x1i = x1.astype(jnp.int32)
    y1i = y1.astype(jnp.int32)
    x2i = jnp.minimum(x1i + 1, w - 1)
    y2i = jnp.minimum(y1i + 1, h - 1)
    v11 = img[:, y1i, x1i]
    v12 = img[:, y2i, x1i]
    v21 = img[:, y1i, x2i]
    v22 = img[:, y2i, x2i]
    val = ((1 - dx) * (1 - dy) * v11 + (1 - dx) * dy * v12 +
           dx * (1 - dy) * v21 + dx * dy * v22)
    return val, ok


def _deformable_conv(attrs, octx, data, offset, weight, bias=None):
    kh, kw = attrs["kernel"]
    sh, sw = attrs["stride"] or (1, 1)
    dh, dw = attrs["dilate"] or (1, 1)
    ph, pw = attrs["pad"] or (0, 0)
    ng = attrs["num_group"]
    ndg = attrs["num_deformable_group"]
    n, cin, h, w = data.shape
    nf = attrs["num_filter"]
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cpg = cin // ndg                      # channels per deformable group

    oy = jnp.arange(oh, dtype=data.dtype) * sh - ph
    ox = jnp.arange(ow, dtype=data.dtype) * sw - pw

    def one(img, off):
        # off: (ndg*2*kh*kw, oh, ow); per kernel tap (i,j): (dy, dx) pair
        cols = []
        for i in range(kh):
            for j in range(kw):
                tap = 2 * (i * kw + j)
                vals = []
                for gidx in range(ndg):
                    dy = off[gidx * 2 * kh * kw + tap]
                    dx = off[gidx * 2 * kh * kw + tap + 1]
                    gy = oy[:, None] + i * dh + dy
                    gx = ox[None, :] + j * dw + dx
                    v, ok = _clamped_bilinear(
                        img[gidx * cpg:(gidx + 1) * cpg], gx, gy)
                    # zero padding outside (im2col semantics)
                    vals.append(jnp.where(ok[None], v, 0.0))
                cols.append(jnp.concatenate(vals, axis=0))  # (cin,oh,ow)
        return jnp.stack(cols, axis=1)                      # (cin,kh*kw,oh,ow)

    cols = jax.vmap(one)(data, offset)                      # (N,cin,K2,oh,ow)
    wmat = weight.reshape(ng, nf // ng, (cin // ng) * kh * kw)
    cols = cols.reshape(n, ng, (cin // ng) * kh * kw, oh * ow)
    out = jnp.einsum("gfk,ngko->ngfo", wmat, cols).reshape(n, nf, oh, ow)
    if not attrs["no_bias"]:
        out = out + bias.reshape(1, -1, 1, 1)
    return _t(out)


def _deformable_conv_infer(attrs, in_shapes):
    ds = in_shapes[0]
    nf = attrs["num_filter"]
    kh, kw = attrs["kernel"]
    if ds is not None:
        in_shapes = list(in_shapes)
        ng = attrs["num_group"]
        if in_shapes[2] is None:
            in_shapes[2] = (nf, ds[1] // ng, kh, kw)
        if len(in_shapes) > 3 and in_shapes[3] is None:
            in_shapes[3] = (nf,)
    if ds is None:
        return in_shapes, [None]
    sh, sw = attrs["stride"] or (1, 1)
    dh, dw = attrs["dilate"] or (1, 1)
    ph, pw = attrs["pad"] or (0, 0)
    oh = (ds[2] + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (ds[3] + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    return in_shapes, [(ds[0], nf, oh, ow)]


def _deform_conv_inputs(attrs):
    base = ["data", "offset", "weight"]
    return base if attrs["no_bias"] else base + ["bias"]


_dconv_schema = register(
    "_contrib_DeformableConvolution", _deformable_conv,
    params={"kernel": Param("shape", None, True),
            "stride": Param("shape", None),
            "dilate": Param("shape", None),
            "pad": Param("shape", None),
            "num_filter": Param("int", None, True),
            "num_group": Param("int", 1),
            "num_deformable_group": Param("int", 1),
            "workspace": Param("int", 1024),
            "no_bias": Param("bool", False),
            "layout": Param("str", None)},
    inputs=("data", "offset", "weight", "bias"),
    infer_shape=_deformable_conv_infer)
_dconv_schema.list_inputs = _deform_conv_inputs  # type: ignore
_dconv_schema.num_inputs = lambda attrs: len(_deform_conv_inputs(attrs))  # type: ignore


def _deformable_psroi_pooling(attrs, octx, data, rois, trans=None):
    scale = attrs["spatial_scale"]
    od = attrs["output_dim"]
    p = attrs["pooled_size"]
    g = attrs["group_size"]
    part = attrs["part_size"] or p
    sp = attrs["sample_per_part"]
    tstd = attrs["trans_std"]
    no_trans = attrs["no_trans"] or trans is None
    n, channels, h, w = data.shape
    if not no_trans:
        num_cls = trans.shape[1] // 2
    else:
        num_cls = 1
    cpc = od // num_cls                     # channels_each_class
    ghi = gwi = _psroi_channel_maps(p, g)
    parth = _np.floor(_np.arange(p) / p * part).astype(_np.int32)

    def one_roi(roi, tr):
        bidx = jnp.clip(roi[0].astype(jnp.int32), 0, n - 1)
        x1 = _round_half_away(roi[1]) * scale - 0.5
        y1 = _round_half_away(roi[2]) * scale - 0.5
        x2 = (_round_half_away(roi[3]) + 1.0) * scale - 0.5
        y2 = (_round_half_away(roi[4]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / p, rw / p
        sbh, sbw = bh / sp, bw / sp
        img = data[bidx].reshape(od, g, g, h, w)

        def one_bin(ph_i, pw_i):
            gh, gw = ghi[ph_i], gwi[pw_i]
            chans = img[:, gh, gw]                          # (od,H,W)
            if no_trans:
                tx = ty = jnp.asarray(0.0, data.dtype)
                tx = jnp.broadcast_to(tx, (od,))
                ty = jnp.broadcast_to(ty, (od,))
            else:
                cls_id = jnp.arange(od) // cpc              # (od,)
                pth, ptw = parth[ph_i], parth[pw_i]
                tx = tr[cls_id * 2, pth, ptw] * tstd
                ty = tr[cls_id * 2 + 1, pth, ptw] * tstd
            hstart = ph_i * bh + y1 + ty * rh               # (od,)
            wstart = pw_i * bw + x1 + tx * rw
            ih = jnp.arange(sp, dtype=data.dtype)
            gy = hstart[:, None, None] + ih[:, None] * sbh  # (od,sp,1)
            gx = wstart[:, None, None] + ih[None, :] * sbw  # (od,1,sp)
            gy = jnp.broadcast_to(gy, (od, sp, sp))
            gx = jnp.broadcast_to(gx, (od, sp, sp))
            vals, ok = jax.vmap(
                lambda c, yy, xx: _clamped_bilinear(c[None], xx, yy))(
                chans, gy, gx)
            vals = vals[:, 0]                               # (od,sp,sp)
            cnt = jnp.sum(ok, axis=(1, 2))
            tot = jnp.sum(jnp.where(ok, vals, 0.0), axis=(1, 2))
            return jnp.where(cnt > 0, tot / jnp.maximum(cnt, 1), 0.0)

        rows = [jnp.stack([one_bin(i, j) for j in range(p)], axis=-1)
                for i in range(p)]
        return jnp.stack(rows, axis=-2)                     # (od,p,p)

    if no_trans:
        tr_dummy = jnp.zeros((rois.shape[0], 2, part, part), data.dtype)
        out = jax.vmap(one_roi)(rois, tr_dummy)
    else:
        # trans is per-roi (R, 2*num_cls, part, part) in the reference's
        # R-FCN usage (one trans map per roi)
        out = jax.vmap(one_roi)(rois, trans)
    return _t(out)


def _deform_psroi_inputs(attrs):
    return ["data", "rois"] if attrs["no_trans"] else \
        ["data", "rois", "trans"]


def _deform_psroi_infer(attrs, in_shapes):
    ds, rs = in_shapes[0], in_shapes[1]
    if ds is None or rs is None:
        return in_shapes, [None]
    p = attrs["pooled_size"]
    return in_shapes, [(rs[0], attrs["output_dim"], p, p)]


_dpsroi_schema = register(
    "_contrib_DeformablePSROIPooling", _deformable_psroi_pooling,
    params={"spatial_scale": Param("float", None, True),
            "output_dim": Param("int", None, True),
            "group_size": Param("int", None, True),
            "pooled_size": Param("int", None, True),
            "part_size": Param("int", 0),
            "sample_per_part": Param("int", 1),
            "trans_std": Param("float", 0.0),
            "no_trans": Param("bool", False)},
    inputs=("data", "rois", "trans"), infer_shape=_deform_psroi_infer)
_dpsroi_schema.list_inputs = _deform_psroi_inputs  # type: ignore
_dpsroi_schema.num_inputs = lambda attrs: len(_deform_psroi_inputs(attrs))  # type: ignore


# ---------------------------------------------------------------------------
# legacy Crop (src/operator/crop-inl.h) — crop spatial dims to h_w or to a
# reference input's size, from offset or center
# ---------------------------------------------------------------------------

def _crop_op(attrs, octx, data, crop_like=None):
    n, c, h, w = data.shape
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = attrs["h_w"]
    if th <= 0 or tw <= 0:
        raise MXNetError("Crop: need h_w or a second (crop_like) input")
    if attrs["center_crop"]:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = attrs["offset"]
    if oy + th > h or ox + tw > w:
        raise MXNetError(f"Crop: crop window ({oy}:{oy+th},{ox}:{ox+tw}) "
                         f"exceeds input ({h},{w})")
    return _t(jax.lax.slice(data, (0, 0, oy, ox), (n, c, oy + th, ox + tw)))


def _crop_op_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    if len(in_shapes) > 1 and in_shapes[1] is not None:
        th, tw = in_shapes[1][2], in_shapes[1][3]
    else:
        th, tw = attrs["h_w"]
    return in_shapes, [(ds[0], ds[1], th, tw)]


_crop_schema = register(
    "Crop", _crop_op,
    params={"num_args": Param("int", 1),
            "offset": Param("shape", (0, 0)),
            "h_w": Param("shape", (0, 0)),
            "center_crop": Param("bool", False)},
    inputs=("data", "crop_like"), infer_shape=_crop_op_infer)
_crop_schema.list_inputs = lambda attrs: (
    ["data", "crop_like"] if attrs["num_args"] == 2 else ["data"])
_crop_schema.num_inputs = lambda attrs: attrs["num_args"]
