"""Optimizer update operators.

Parity target: src/operator/optimizer_op.{cc,-inl.h} (SURVEY.md §2.2) — the
reference registers parameter updates as *ops* so they run on-device (and on
kvstore servers). Here each update is a fused jax function compiled once per
hyperparameter set: the whole update (rescale, clip, state update, weight
update) is one XLA executable, so state never round-trips to host and XLA
fuses it into a couple of HBM passes.

Calling convention (MXNet parity): `mx.nd.sgd_mom_update(w, g, mom, out=w,
lr=..)` — state inputs are declared aux with `aux_always=True`, so their
updated values are written back to the passed NDArrays; the new weight is
output 0 (rebound onto `w` via `out=`).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import Param, register

__all__ = []


def _prep(attrs, grad, weight):
    """rescale → clip → + wd*weight (SGD-family order: the reference clips
    the rescaled grad, then applies decay separately)."""
    g = grad * jnp.asarray(attrs.rescale_grad, grad.dtype)
    if attrs.clip_gradient is not None and attrs.clip_gradient > 0:
        c = jnp.asarray(attrs.clip_gradient, g.dtype)
        g = jnp.clip(g, -c, c)
    return g + jnp.asarray(attrs.wd, weight.dtype) * weight


def _prep_wd_first(attrs, grad, weight):
    """rescale → + wd*weight → clip (Adam/RMSProp/FTML-family order: the
    reference folds decay into the grad before clipping)."""
    g = grad * jnp.asarray(attrs.rescale_grad, grad.dtype) + \
        jnp.asarray(attrs.wd, weight.dtype) * weight
    if attrs.clip_gradient is not None and attrs.clip_gradient > 0:
        c = jnp.asarray(attrs.clip_gradient, g.dtype)
        g = jnp.clip(g, -c, c)
    return g


_COMMON = {
    "lr": Param("float", required=True),
    "wd": Param("float", 0.0),
    "rescale_grad": Param("float", 1.0),
    "clip_gradient": Param("float", -1.0),
}


def _p(**extra):
    d = dict(_COMMON)
    for k, v in extra.items():
        d[k] = Param("float", v)
    return d


# -- SGD ---------------------------------------------------------------------

def _row_mask(grad):
    """Rows "touched" by a row_sparse gradient, dense-backed: any nonzero
    in the row (matches RowSparseNDArray.indices). Broadcastable mask.

    Documented divergence from the reference's index-based lazy kernels
    (src/operator/optimizer_op.cc): a row explicitly listed in
    grad.indices whose values happen to be EXACTLY zero (e.g. in-batch
    updates canceling) is treated as untouched here, so it also skips
    wd/momentum/moment decay for that step. The dense-backed NDArray has
    no index list to consult; value-inferred occupancy is the honest
    equivalent (see also ndarray/sparse.py stance note)."""
    axes = tuple(range(1, grad.ndim))
    touched = jnp.any(grad != 0, axis=axes) if axes else (grad != 0)
    return touched.reshape((-1,) + (1,) * (grad.ndim - 1))


def _lazy(attrs, grad, new, old):
    """reference lazy_update semantics (src/operator/optimizer_op.cc sparse
    sgd/adam kernels): with a row_sparse grad and lazy_update=True, ONLY
    rows present in grad.indices are updated — untouched rows skip weight
    decay, momentum decay and moment updates entirely. The optimizer
    frontend sets the attr only when grad.stype == 'row_sparse'."""
    if not attrs.get("lazy_update"):
        return new
    m = _row_mask(grad)
    return tuple(jnp.where(m, n, o) for n, o in zip(new, old))


def _sgd_update(attrs, octx, weight, grad):
    g = _prep(attrs, grad, weight)
    new_w = weight - jnp.asarray(attrs.lr, weight.dtype) * g
    return _lazy(attrs, grad, (new_w,), (weight,))


register("sgd_update", _sgd_update,
         params=dict(_p(), lazy_update=Param("bool", False)),
         inputs=("weight", "grad"), aliases=())


def _sgd_mom_update(attrs, octx, weight, grad, mom):
    g = _prep(attrs, grad, weight)
    lr = jnp.asarray(attrs.lr, weight.dtype)
    new_mom = jnp.asarray(attrs.momentum, mom.dtype) * mom - lr * g
    return _lazy(attrs, grad, (weight + new_mom, new_mom), (weight, mom))


register("sgd_mom_update", _sgd_mom_update,
         params=dict(_p(momentum=0.0), lazy_update=Param("bool", False)),
         inputs=("weight", "grad", "mom"), aux=("mom",),
         mutates_aux=True, aux_always=True)


def _mp_sgd_update(attrs, octx, weight, grad, weight32):
    g32 = _prep(attrs, grad.astype(jnp.float32), weight32)
    new_w32 = weight32 - jnp.float32(attrs.lr) * g32
    return (new_w32.astype(weight.dtype), new_w32)


register("mp_sgd_update", _mp_sgd_update, params=_p(),
         inputs=("weight", "grad", "weight32"), aux=("weight32",),
         mutates_aux=True, aux_always=True)


def _mp_sgd_mom_update(attrs, octx, weight, grad, mom, weight32):
    g32 = _prep(attrs, grad.astype(jnp.float32), weight32)
    new_mom = jnp.float32(attrs.momentum) * mom - jnp.float32(attrs.lr) * g32
    new_w32 = weight32 + new_mom
    return (new_w32.astype(weight.dtype), new_mom, new_w32)


register("mp_sgd_mom_update", _mp_sgd_mom_update, params=_p(momentum=0.0),
         inputs=("weight", "grad", "mom", "weight32"),
         aux=("mom", "weight32"), mutates_aux=True, aux_always=True)


# -- Adam --------------------------------------------------------------------

def _adam_update(attrs, octx, weight, grad, mean, var):
    g = _prep_wd_first(attrs, grad, weight)
    b1 = jnp.asarray(attrs.beta1, mean.dtype)
    b2 = jnp.asarray(attrs.beta2, var.dtype)
    new_mean = b1 * mean + (1 - b1) * g
    new_var = b2 * var + (1 - b2) * jnp.square(g)
    step = jnp.asarray(attrs.lr, weight.dtype) * new_mean / (
        jnp.sqrt(new_var) + jnp.asarray(attrs.epsilon, weight.dtype))
    return _lazy(attrs, grad, (weight - step, new_mean, new_var),
                 (weight, mean, var))


register("adam_update", _adam_update,
         params=dict(_p(beta1=0.9, beta2=0.999, epsilon=1e-8),
                     lazy_update=Param("bool", False)),
         inputs=("weight", "grad", "mean", "var"), aux=("mean", "var"),
         mutates_aux=True, aux_always=True)


# -- RMSProp -----------------------------------------------------------------

def _rmsprop_update(attrs, octx, weight, grad, n):
    g = _prep_wd_first(attrs, grad, weight)
    g1 = jnp.asarray(attrs.gamma1, n.dtype)
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    step = jnp.asarray(attrs.lr, weight.dtype) * g / jnp.sqrt(
        new_n + jnp.asarray(attrs.epsilon, weight.dtype))
    new_w = weight - step
    if attrs.clip_weights is not None and attrs.clip_weights > 0:
        cw = jnp.asarray(attrs.clip_weights, weight.dtype)
        new_w = jnp.clip(new_w, -cw, cw)
    return (new_w, new_n)


register("rmsprop_update", _rmsprop_update,
         params=_p(gamma1=0.95, epsilon=1e-8, clip_weights=-1.0),
         inputs=("weight", "grad", "n"), aux=("n",),
         mutates_aux=True, aux_always=True)


def _rmspropalex_update(attrs, octx, weight, grad, n, g_avg, delta):
    g = _prep_wd_first(attrs, grad, weight)
    g1 = jnp.asarray(attrs.gamma1, n.dtype)
    g2 = jnp.asarray(attrs.gamma2, delta.dtype)
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_g = (1 - g1) * g + g1 * g_avg
    new_delta = g2 * delta - jnp.asarray(attrs.lr, weight.dtype) * g / jnp.sqrt(
        new_n - jnp.square(new_g) + jnp.asarray(attrs.epsilon, weight.dtype))
    new_w = weight + new_delta
    if attrs.clip_weights is not None and attrs.clip_weights > 0:
        cw = jnp.asarray(attrs.clip_weights, weight.dtype)
        new_w = jnp.clip(new_w, -cw, cw)
    return (new_w, new_n, new_g, new_delta)


register("rmspropalex_update", _rmspropalex_update,
         params=_p(gamma1=0.95, gamma2=0.9, epsilon=1e-8, clip_weights=-1.0),
         inputs=("weight", "grad", "n", "g", "delta"),
         aux=("n", "g", "delta"), mutates_aux=True, aux_always=True)


# -- Ftrl --------------------------------------------------------------------

def _ftrl_update(attrs, octx, weight, grad, z, n):
    g = grad * jnp.asarray(attrs.rescale_grad, grad.dtype)
    if attrs.clip_gradient is not None and attrs.clip_gradient > 0:
        c = jnp.asarray(attrs.clip_gradient, g.dtype)
        g = jnp.clip(g, -c, c)
    lr = jnp.asarray(attrs.lr, weight.dtype)
    new_z = z + g - (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr * weight
    new_n = n + jnp.square(g)
    l1 = jnp.asarray(attrs.lamda1, weight.dtype)
    beta = jnp.asarray(attrs.beta, weight.dtype)
    wd = jnp.asarray(attrs.wd, weight.dtype)
    new_w = jnp.where(
        jnp.abs(new_z) > l1,
        (jnp.sign(new_z) * l1 - new_z) / ((beta + jnp.sqrt(new_n)) / lr + wd),
        jnp.zeros_like(weight))
    return (new_w, new_z, new_n)


register("ftrl_update", _ftrl_update, params=_p(lamda1=0.01, beta=1.0),
         inputs=("weight", "grad", "z", "n"), aux=("z", "n"),
         mutates_aux=True, aux_always=True)


# -- SignSGD / Signum --------------------------------------------------------

def _signsgd_update(attrs, octx, weight, grad):
    g = grad * jnp.asarray(attrs.rescale_grad, grad.dtype)
    if attrs.clip_gradient is not None and attrs.clip_gradient > 0:
        c = jnp.asarray(attrs.clip_gradient, g.dtype)
        g = jnp.clip(g, -c, c)
    lr = jnp.asarray(attrs.lr, weight.dtype)
    wd = jnp.asarray(attrs.wd, weight.dtype)
    return ((1 - lr * wd) * weight - lr * jnp.sign(g),)


register("signsgd_update", _signsgd_update, params=_p(),
         inputs=("weight", "grad"))


def _signum_update(attrs, octx, weight, grad, mom):
    g = grad * jnp.asarray(attrs.rescale_grad, grad.dtype)
    if attrs.clip_gradient is not None and attrs.clip_gradient > 0:
        c = jnp.asarray(attrs.clip_gradient, g.dtype)
        g = jnp.clip(g, -c, c)
    lr = jnp.asarray(attrs.lr, weight.dtype)
    m = jnp.asarray(attrs.momentum, mom.dtype)
    wd = jnp.asarray(attrs.wd, weight.dtype)
    new_mom = m * mom - (1 - m) * (g + wd * weight)
    wd_lh = jnp.asarray(attrs.wd_lh, weight.dtype)
    new_w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return (new_w, new_mom)


register("signum_update", _signum_update, params=_p(momentum=0.0, wd_lh=0.0),
         inputs=("weight", "grad", "mom"), aux=("mom",),
         mutates_aux=True, aux_always=True)


# -- FTML --------------------------------------------------------------------

def _ftml_update(attrs, octx, weight, grad, d, v, z):
    g = _prep_wd_first(attrs, grad, weight)
    t = attrs.t
    b1 = jnp.asarray(attrs.beta1, v.dtype)
    b2 = jnp.asarray(attrs.beta2, v.dtype)
    eps = jnp.asarray(attrs.epsilon, v.dtype)
    lr = jnp.asarray(attrs.lr, weight.dtype)
    new_v = b2 * v + (1 - b2) * jnp.square(g)
    corr2 = 1 - attrs.beta2 ** t
    corr1 = 1 - attrs.beta1 ** t
    d_t = jnp.asarray(corr1, v.dtype) / lr * (
        jnp.sqrt(new_v / jnp.asarray(corr2, v.dtype)) + eps)
    sigma = d_t - b1 * d
    new_z = b1 * z + (1 - b1) * g - sigma * weight
    new_w = -new_z / d_t
    return (new_w, d_t, new_v, new_z)


register("ftml_update", _ftml_update,
         params={**_p(beta1=0.6, beta2=0.999, epsilon=1e-8),
                 "t": Param("int", required=True)},
         inputs=("weight", "grad", "d", "v", "z"), aux=("d", "v", "z"),
         mutates_aux=True, aux_always=True)
