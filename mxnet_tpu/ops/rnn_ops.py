"""Fused RNN operator.

Parity target: src/operator/rnn-inl.h (SURVEY.md §2.2 — the reference's
cuDNN-backed fused multi-layer RNN; CPU path is LSTM-only, rnn-inl.h:333,
while this TPU op supports all four modes). The whole stack — layers ×
directions × time — lowers into nested `lax.scan`s, so XLA pipelines the
per-step matmuls on the MXU instead of launching one kernel per timestep.

Flat parameter layout matches cuDNN/MXNet: for each layer, each direction:
input weights W (gates*H, in), recurrent weights R (gates*H, H); then for
each layer/direction: input bias bW (gates*H), recurrent bias bR (gates*H).
Gate order: LSTM i,f,g,o — GRU r,z,n.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import Param, register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total flat parameter count (matches cuDNN GetParamSize)."""
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * g * state_size * (in_sz + state_size)  # W + R
        size += dirs * g * state_size * 2                      # bW + bR
    return size


def _unpack_params(params, num_layers, input_size, state_size, dirs, gates):
    """Split the flat vector into per-(layer, dir) (W, R, bW, bR)."""
    ptr = 0
    mats = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        layer_mats = []
        for d in range(dirs):
            w = params[ptr:ptr + gates * state_size * in_sz].reshape(
                gates * state_size, in_sz)
            ptr += gates * state_size * in_sz
            r = params[ptr:ptr + gates * state_size * state_size].reshape(
                gates * state_size, state_size)
            ptr += gates * state_size * state_size
            layer_mats.append([w, r, None, None])
        mats.append(layer_mats)
    for layer in range(num_layers):
        for d in range(dirs):
            mats[layer][d][2] = params[ptr:ptr + gates * state_size]
            ptr += gates * state_size
            mats[layer][d][3] = params[ptr:ptr + gates * state_size]
            ptr += gates * state_size
    return mats


def _cell_step(mode, state_size):
    if mode == "lstm":
        def step(carry, gin):
            h, c = carry
            i, f, g, o = jnp.split(gin, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return (new_h, new_c), new_h
        return step
    if mode == "gru":
        # gru needs the recurrent projection split by gate: handled inline
        return None
    act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

    def step(carry, gin):
        (h,) = carry
        new_h = act(gin)
        return (new_h,), new_h
    return step


def _run_layer(x, h0, c0, w, r, bw, br, mode, state_size, reverse):
    """One direction of one layer over time. x: (T, N, in)."""
    T = x.shape[0]
    if reverse:
        x = x[::-1]
    # precompute input projections for the whole sequence: one big matmul
    # (T*N, in) @ (in, gates*H) — MXU-shaped
    xw = jnp.einsum("tni,gi->tng", x, w) + bw

    if mode == "gru":
        def step(carry, xw_t):
            (h,) = carry
            rh = h @ r.T + br
            xr, xz, xn = jnp.split(xw_t, 3, axis=-1)
            hr, hz, hn = jnp.split(rh, 3, axis=-1)
            rg = jax.nn.sigmoid(xr + hr)
            zg = jax.nn.sigmoid(xz + hz)
            ng = jnp.tanh(xn + rg * hn)
            new_h = (1 - zg) * ng + zg * h
            return (new_h,), new_h
        carry = (h0,)
        carry, ys = jax.lax.scan(step, carry, xw)
        hT, cT = carry[0], None
    elif mode == "lstm":
        cell = _cell_step(mode, state_size)

        def step(carry, xw_t):
            h = carry[0]
            gin = xw_t + h @ r.T + br
            return cell(carry, gin)
        carry = (h0, c0)
        carry, ys = jax.lax.scan(step, carry, xw)
        hT, cT = carry
    else:
        cell = _cell_step(mode, state_size)

        def step(carry, xw_t):
            h = carry[0]
            gin = xw_t + h @ r.T + br
            return cell(carry, gin)
        carry = (h0,)
        carry, ys = jax.lax.scan(step, carry, xw)
        hT, cT = carry[0], None
    if reverse:
        ys = ys[::-1]
    return ys, hT, cT


def _rnn(attrs, octx, data, params, state, *rest):
    mode = attrs["mode"]
    if mode not in _GATES:
        raise MXNetError(f"RNN: unknown mode {mode}")
    state_size = attrs["state_size"]
    num_layers = attrs["num_layers"]
    dirs = 2 if attrs["bidirectional"] else 1
    gates = _GATES[mode]
    state_cell = rest[0] if (mode == "lstm" and rest) else None

    T, N, input_size = data.shape
    mats = _unpack_params(params, num_layers, input_size, state_size, dirs,
                          gates)

    p = attrs["p"]
    x = data
    h_states, c_states = [], []
    rng = octx.rng
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            idx = layer * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if state_cell is not None else None
            w, r, bw, br = mats[layer][d]
            ys, hT, cT = _run_layer(x, h0, c0, w, r, bw, br, mode,
                                    state_size, reverse=(d == 1))
            outs.append(ys)
            h_states.append(hT)
            if cT is not None:
                c_states.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and octx.is_train and layer < num_layers - 1 and \
                rng is not None:
            rng, sub = jax.random.split(rng)
            keep = jax.random.bernoulli(sub, 1 - p, x.shape)
            x = jnp.where(keep, x / (1 - p), 0)

    outputs = [x, jnp.stack(h_states)]
    if mode == "lstm":
        outputs.append(jnp.stack(c_states))
    return tuple(outputs)


def _rnn_infer(attrs, in_shapes):
    ds = in_shapes[0]
    mode = attrs["mode"]
    state_size = attrs["state_size"]
    num_layers = attrs["num_layers"]
    dirs = 2 if attrs["bidirectional"] else 1
    in_shapes = list(in_shapes)
    if ds is not None:
        T, N, input_size = ds
        if in_shapes[1] is None:
            in_shapes[1] = (rnn_param_size(num_layers, input_size,
                                           state_size,
                                           attrs["bidirectional"], mode),)
        if in_shapes[2] is None:
            in_shapes[2] = (num_layers * dirs, N, state_size)
        if mode == "lstm" and len(in_shapes) > 3 and in_shapes[3] is None:
            in_shapes[3] = (num_layers * dirs, N, state_size)
        out = [(T, N, state_size * dirs),
               (num_layers * dirs, N, state_size)]
        if mode == "lstm":
            out.append((num_layers * dirs, N, state_size))
        return in_shapes, out
    return in_shapes, [None] * (3 if mode == "lstm" else 2)


def _rnn_num_outputs(attrs):
    # output + state_h (+ state_c for lstm); when state_outputs=False the
    # caller just ignores the extra outputs (parity: reference returns them
    # only if state_outputs, but constant output count keeps jit caching
    # simple — Symbol consumers index [0])
    return 3 if attrs["mode"] == "lstm" else 2


_rnn_schema = register(
    "RNN", _rnn,
    params={"state_size": Param("int", None, True),
            "num_layers": Param("int", None, True),
            "bidirectional": Param("bool", False),
            "mode": Param("str", None, True),
            "p": Param("float", 0.0),
            "state_outputs": Param("bool", False),
            "lstm_state_clip_min": Param("float", None),
            "lstm_state_clip_max": Param("float", None),
            "lstm_state_clip_nan": Param("bool", False)},
    inputs=("data", "parameters", "state", "state_cell"),
    num_outputs=_rnn_num_outputs, needs_rng=True,
    infer_shape=_rnn_infer)


def _state_zeros(attrs, octx, data):
    # begin-state helper: zeros (num, N, dim) with N taken from the data
    # symbol — lets hybridized RNN layers trace without concrete states
    return (jnp.zeros((attrs["num"], data.shape[1], attrs["dim"]),
                      data.dtype),)


def _state_zeros_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [(attrs["num"], ds[1], attrs["dim"])]


register("_rnn_state_zeros", _state_zeros,
         params={"num": Param("int", None, True),
                 "dim": Param("int", None, True)},
         inputs=("data",), infer_shape=_state_zeros_infer)


def _cell_state_zeros(attrs, octx, data):
    # per-step cell state: zeros (N, dim) with N from the (N, ...) input —
    # the reference's 0-means-unknown begin_state shape contract realized
    # with static shapes
    return (jnp.zeros((data.shape[0], attrs["dim"]), data.dtype),)


def _cell_state_zeros_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [(ds[0], attrs["dim"])]


register("_cell_state_zeros", _cell_state_zeros,
         params={"dim": Param("int", None, True)},
         inputs=("data",), infer_shape=_cell_state_zeros_infer)


def _rnn_inputs(attrs):
    if attrs["mode"] == "lstm":
        return ["data", "parameters", "state", "state_cell"]
    return ["data", "parameters", "state"]


_rnn_schema.list_inputs = _rnn_inputs  # type: ignore
_rnn_schema.num_inputs = lambda attrs: 4 if attrs["mode"] == "lstm" else 3  # type: ignore
