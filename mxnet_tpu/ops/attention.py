"""Attention operators — Pallas flash-attention kernel + XLA fallback.

The reference has no attention op (its transformer support is the helper
`_contrib_div_sqrt_dim`, src/operator/contrib/transformer.cc:34); this is
TPU-first new surface: a blockwise online-softmax kernel written in Pallas
(per /opt/skills/guides/pallas_guide.md) that keeps the (S, S) score
matrix out of HBM, gridded over (batch*heads, q-blocks) with the K/V
stream resident in VMEM. Dispatch picks the kernel on TPU for
tile-friendly shapes and falls back to a fused XLA implementation
elsewhere (including the CPU test mesh). The sequence-parallel versions
live in mxnet_tpu.parallel.sp.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import Param, register

_BLOCK_Q = 128    # floor tile; _auto_block picks larger when S allows
_BLOCK_K = 128
_LSE_LANES = 8    # minor replication of the per-row lse (TPU block tiling)


def _auto_block(s):
    """Default block size: the LARGEST of 512/256/128 dividing S. The r5
    sweep (tools/attention_sweep.py, docs/ROUND5.md) measured 512-blocks
    at ~1.9x the r4 default 128 on v5e (seq 4096 causal fwd+bwd: 984k vs
    527k tok/s) — bigger tiles amortize the per-block softmax bookkeeping
    and keep the MXU busier. Sequences not divisible by 128 fall back to
    a single block (small-S case)."""
    for blk in (512, 256, 128):
        if s % blk == 0:
            return blk
    return min(_BLOCK_Q, s)


def _t(*o):
    return tuple(o)


def reference_attention(q, k, v, causal=False, scale=None):
    """Dense oracle. One implementation shared with the with-lse variant
    below — the score/mask/softmax math must not fork."""
    return reference_attention_with_lse(q, k, v, causal, scale)[0]


def reference_attention_with_lse(q, k, v, causal=False, scale=None):
    """Dense oracle returning (out, lse (B,H,S) f32) — the merge
    statistic blockwise/ring combiners need. Rows with NO valid key get
    out=0 and lse=-inf (the logsumexp of an empty set), so such a block
    contributes exactly nothing to a logaddexp merge. GQA (k/v with
    fewer heads) is handled by repeating kv across each query group."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if k.shape[1] != q.shape[1]:
        group = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)
    safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(scores - safe[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l == 0, 1.0, l)
    out = jnp.einsum("bhqk,bhkd->bhqd", p,
                     v.astype(jnp.float32)) / l_safe[..., None]
    lse = jnp.where(l == 0, -jnp.inf, safe + jnp.log(l_safe))
    return out.astype(q.dtype), lse


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, seq_len,
                  causal, scale):
    """One (bh, q-block) grid cell: stream K/V blocks with online softmax.
    Also writes the per-row logsumexp — the backward's saved statistic."""
    import jax.experimental.pallas as pl

    q_block = q_ref.shape[0]
    # keep q in its storage dtype: the MXU runs bf16 matmuls at full rate
    # while an fp32 upcast would halve+ throughput; accumulation happens
    # in fp32 via preferred_element_type, and the scale is applied to the
    # fp32 scores (numerically at least as good as scaling q)
    q = q_ref[:]                                        # (Bq, D)
    q_start = pl.program_id(1) * q_block
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (q_block, 1), 0)

    acc0 = jnp.zeros((q_block, q.shape[1]), jnp.float32)
    m0 = jnp.full((q_block, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q_block, 1), jnp.float32)
    n_blocks = seq_len // block_k
    if causal:
        # flash-attention causal skip: blocks fully above the diagonal
        # contribute nothing — bound the scan at the q-block's last row
        n_blocks = jnp.minimum(
            n_blocks, (q_start + q_block + block_k - 1) // block_k)

    def body(i, carry):
        acc, m, l = carry
        start = i * block_k
        k_blk = k_ref[pl.dslice(start, block_k), :]
        v_blk = v_ref[pl.dslice(start, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            k_pos = start + jax.lax.broadcasted_iota(jnp.int32,
                                                     (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe))
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    # rows with no valid key (UNREACHABLE for kernel-eligible shapes:
    # self-attention with s_q == s_k always has the diagonal key): the
    # +inf sentinel makes every backward p = exp(s - lse) collapse to 0,
    # matching the zero forward output. NOTE the dense with-lse oracle
    # uses -inf for empty rows (the merge-correct logsumexp-of-empty
    # convention) — the two only disagree on rows that cannot exist here.
    # The row statistic is replicated across a minor dim of 8 — the
    # smallest lane count the TPU lowering accepts for a blocked store
    lse = jnp.where(l == 0, jnp.inf, m + jnp.log(l_safe))
    lse_ref[:] = jnp.broadcast_to(lse, (q_block, _LSE_LANES))


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                         glse_ref, dq_ref, *, block_k, seq_len, causal,
                         scale):
    """dQ for one (bh, q-block): stream K/V. With the saved lse the
    softmax re-materializes blockwise (p = exp(s - lse)) — no (S, S)
    tensor ever exists; delta = rowsum(dO * O) is recomputed in-VMEM from
    the O/dO blocks (cheaper than a third saved row array). glse is the
    lse OUTPUT's cotangent (ring/blockwise merging differentiates
    through lse): dlse_i/ds_ij = p_ij, so it simply subtracts from the
    row term — zeros when lse is not a differentiated output."""
    import jax.experimental.pallas as pl

    q_block = q_ref.shape[0]
    q = q_ref[:]
    do = do_ref[:].astype(jnp.float32)                  # (Bq, D)
    lse = lse_ref[:, 0:1]                               # (Bq, 1)
    delta = jnp.sum(do * o_ref[:].astype(jnp.float32), axis=1,
                    keepdims=True)                      # (Bq, 1)
    if glse_ref is not None:
        delta = delta - glse_ref[:, 0:1]
    q_start = pl.program_id(1) * q_block
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (q_block, 1), 0)

    n_blocks = seq_len // block_k
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, (q_start + q_block + block_k - 1) // block_k)

    def body(i, dq_acc):
        start = i * block_k
        k_blk = k_ref[pl.dslice(start, block_k), :]
        v_blk = v_ref[pl.dslice(start, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            k_pos = start + jax.lax.broadcasted_iota(jnp.int32,
                                                     (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse)                             # masked rows -> 0
        dp = jax.lax.dot_general(
            do, v_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (Bq, Bk)
        ds = p * (dp - delta) * scale
        return dq_acc + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (Bq, D)

    dq = jax.lax.fori_loop(0, n_blocks,
                           body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          glse_ref, dk_ref, dv_ref, *, block_q, seq_len,
                          causal, scale):
    """dK/dV for one (bh, k-block): stream Q/dO/O blocks. Causal skip from
    the other side — q-blocks strictly above this k-block see none of it
    (fori_loop lower bound derived from the grid position)."""
    import jax.experimental.pallas as pl

    block_k = k_ref.shape[0]
    k = k_ref[:]                                        # (Bk, D)
    v = v_ref[:]
    k_start = pl.program_id(1) * block_k
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

    first_block = k_start // block_q if causal else 0
    n_blocks = seq_len // block_q

    def body(i, carry):
        dk_acc, dv_acc = carry
        start = i * block_q
        q_blk = q_ref[pl.dslice(start, block_q), :]      # (Bq, D)
        do_blk = do_ref[pl.dslice(start, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.dslice(start, block_q), 0:1]    # (Bq, 1)
        delta = jnp.sum(
            do_blk * o_ref[pl.dslice(start, block_q), :].astype(
                jnp.float32), axis=1, keepdims=True)     # (Bq, 1)
        if glse_ref is not None:
            delta = delta - glse_ref[pl.dslice(start, block_q), 0:1]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            q_pos = start + jax.lax.broadcasted_iota(jnp.int32,
                                                     (block_q, 1), 0)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse)
        # dV += P^T dO  (contract over the q rows)
        dv_acc = dv_acc + jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (Bk, D)
        dp = jax.lax.dot_general(
            do_blk, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (Bq, Bk)
        ds = p * (dp - delta) * scale
        # dK += dS^T Q
        dk_acc = dk_acc + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (Bk, D)
        return dk_acc, dv_acc

    z = jnp.zeros(k.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(first_block, n_blocks, body, (z, z))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the input's varying-mesh-axes set, so
    pallas_call outputs typecheck under shard_map's vma analysis (the
    kernels are purely shard-local: outputs vary exactly as q does)."""
    try:
        vma = jax.typeof(like).vma
    except Exception:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _vmem_params(s, d, n_full_streams, interpret, itemsize=2):
    """Mosaic compiler params for long sequences: the kernels keep
    full-length (S, D) K/V (and, in the backward, Q/dO/O) refs resident
    in VMEM with double buffering across grid cells; past ~8k tokens
    that legitimately exceeds the default 16MB scoped-vmem budget
    (measured on v5e: s=12288 wants 16.7M). Raise the per-kernel limit
    toward the physical VMEM when the estimate calls for it — the
    budget is a compiler default, not the hardware bound."""
    if interpret:
        return {}
    need = n_full_streams * s * d * itemsize * 2   # x2 double buffering
    if need <= 8 * 2 ** 20:
        # q/out blocks + lse + scratch ride within the default budget
        return {}
    from jax.experimental.pallas import tpu as pltpu
    # s/d/need are static python shape ints even at trace time, not
    # tracers — the cast never syncs  # analysis: allow=trace-host-cast
    limit = min(110 * 2 ** 20, int(need * 1.5) + 16 * 2 ** 20)
    return {"compiler_params": pltpu.CompilerParams(
        vmem_limit_bytes=limit)}


def _kv_index_map(h, h_kv):
    """Grid-index map for K/V refs under GQA: q-head `bh % h` reads kv
    head `(bh % h) // group` — the kernels stream the SHARED kv block
    straight from HBM, no repeated copy is ever materialized."""
    if h == h_kv:
        return lambda bh, i: (bh, 0, 0)
    group = h // h_kv
    return lambda bh, i: ((bh // h) * h_kv + (bh % h) // group, 0, 0)


def _flash_pallas(q, k, v, causal, scale, interpret=False, block_q=None,
                  block_k=None):
    """Forward kernel. q (B, H, S, D), k/v (B, H_kv, S, D) with
    H % H_kv == 0 (GQA/MQA share kv blocks in-kernel), S % block == 0 and
    D % 128 == 0 (or 64). Returns (out (B,H,S,D), lse (B*H, S, 8) f32 —
    the row statistic lane-replicated for TPU block tiling)."""
    import jax.experimental.pallas as pl

    b, h, s, d = q.shape
    h_kv = k.shape[1]
    block_q = min(block_q or _auto_block(s), s)
    block_k = min(block_k or _auto_block(s), s)
    if s % block_q or s % block_k:
        # forced/explicit blocks that don't tile S would silently leave
        # grid-truncated output rows unwritten
        raise ValueError(f"flash attention: seq {s} is not divisible by "
                         f"blocks ({block_q}, {block_k})")
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h_kv, s, d)
    vf = v.reshape(b * h_kv, s, d)
    kv_map = _kv_index_map(h, h_kv)
    kernel = functools.partial(_flash_kernel, block_k=block_k, seq_len=s,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, s, d), kv_map),
            pl.BlockSpec((None, s, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q, _LSE_LANES),
                         lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            _sds((b * h, s, d), q.dtype, q),
            _sds((b * h, s, _LSE_LANES), jnp.float32, q),
        ],
        interpret=interpret,
        **_vmem_params(s, d, 2, interpret, q.dtype.itemsize),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d), lse


def _flash_pallas_bwd(q, k, v, o, lse, g, causal, scale, interpret=False,
                      g_lse=None, block_q=None, block_k=None):
    """Recompute-based flash backward: two single-HBM-pass kernels (dQ
    gridded over q-blocks; dK/dV over k-blocks) re-derive the softmax
    from the saved lse — O(S) extra memory, never an (S, S) tensor.
    g_lse (B, H, S) is the lse output's cotangent when lse is itself a
    differentiated output (blockwise/ring merging); None means zeros.
    GQA: kv blocks stream shared via the index map (like the forward);
    the dK/dV kernel still produces PER-Q-HEAD partials, reduced over
    each group outside the kernel (one cheap XLA sum — the simple,
    correct realization; an in-kernel cross-head accumulation would
    need grid-order-dependent output aliasing)."""
    import jax.experimental.pallas as pl

    b, h, s, d = q.shape
    h_kv = k.shape[1]
    kv_map = _kv_index_map(h, h_kv)
    block_q = min(block_q or _auto_block(s), s)
    block_k = min(block_k or _auto_block(s), s)
    if s % block_q or s % block_k:
        raise ValueError(f"flash attention bwd: seq {s} is not divisible "
                         f"by blocks ({block_q}, {block_k})")
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h_kv, s, d)
    vf = v.reshape(b * h_kv, s, d)
    dof = g.reshape(b * h, s, d)
    of = o.reshape(b * h, s, d)
    have_glse = g_lse is not None
    if have_glse:
        # the masked-row lse can be +/-inf sentinels; 0*inf would NaN, so
        # derive the vma-carrying zero from a finitized lse
        glse_args = (jnp.broadcast_to(
            g_lse.astype(jnp.float32).reshape(b * h, s, 1),
            (b * h, s, _LSE_LANES))
            + 0.0 * jnp.where(jnp.isfinite(lse), lse, 0.0),)
    else:
        glse_args = ()

    def _with_optional_glse(kernel, n_lead):
        """The hot no-glse path passes glse_ref=None statically — no
        extra HBM stream for the common training backward."""
        if have_glse:
            return kernel
        return functools.partial(
            lambda *refs, k: k(*refs[:n_lead], None, *refs[n_lead:]),
            k=kernel)

    full_spec = pl.BlockSpec((None, s, d), lambda bh, i: (bh, 0, 0))
    kv_full = pl.BlockSpec((None, s, d), kv_map)
    lse_full = pl.BlockSpec((None, s, _LSE_LANES), lambda bh, i: (bh, 0, 0))
    lse_blk = pl.BlockSpec((None, block_q, _LSE_LANES),
                           lambda bh, qi: (bh, qi, 0))

    dq_kernel = _with_optional_glse(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          seq_len=s, causal=causal, scale=scale), 6)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            kv_full, kv_full,
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, block_q, d), lambda bh, qi: (bh, qi, 0)),
            lse_blk,
        ] + ([lse_blk] if have_glse else []),
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, qi: (bh, qi, 0)),
        out_shape=_sds((b * h, s, d), q.dtype, q),
        interpret=interpret,
        **_vmem_params(s, d, 2, interpret, q.dtype.itemsize),
    )(qf, kf, vf, dof, of, lse, *glse_args)

    if h == h_kv:
        kv_blk = pl.BlockSpec((None, block_k, d),
                              lambda bh, ki: (bh, ki, 0))
    else:
        group = h // h_kv
        kv_blk = pl.BlockSpec(
            (None, block_k, d),
            lambda bh, ki: ((bh // h) * h_kv + (bh % h) // group, ki, 0))
    dkv_kernel = _with_optional_glse(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          seq_len=s, causal=causal, scale=scale), 6)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, s // block_k),
        in_specs=[
            full_spec, kv_blk, kv_blk,
            full_spec, full_spec, lse_full,
        ] + ([lse_full] if have_glse else []),
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            _sds((b * h, s, d), k.dtype, q),
            _sds((b * h, s, d), v.dtype, q),
        ],
        interpret=interpret,
        **_vmem_params(s, d, 3, interpret, q.dtype.itemsize),
    )(qf, kf, vf, dof, of, lse, *glse_args)

    dq = dq.reshape(b, h, s, d)
    dk = dk.reshape(b, h, s, d)
    dv = dv.reshape(b, h, s, d)
    if h != h_kv:
        group = h // h_kv
        dk = dk.reshape(b, h_kv, group, s, d).sum(2).astype(k.dtype)
        dv = dv.reshape(b, h_kv, group, s, d).sum(2).astype(v.dtype)
    return dq, dk, dv


def _pallas_eligible(q, k, platform=None, block_q=None, block_k=None):
    b, h, s, d = q.shape
    if k.shape != q.shape:
        # GQA/MQA (fewer kv heads, same seq) stays kernel-eligible; true
        # cross-attention (s_q != s_k) goes to the XLA path
        if k.shape[0] != b or k.shape[2] != s or k.shape[3] != d \
                or k.shape[1] == 0 or h % k.shape[1] != 0:
            return False
    if d % 128 != 0 and d not in (64,):
        return False
    if s % min(block_q or _auto_block(s), s) != 0 or \
            s % min(block_k or _auto_block(s), s) != 0:
        return False
    if s < 8:
        return False
    # TPU-only auto-pick: the kernels' lse layout and block tiling are
    # TPU-tuned — a GPU backend falls back to the XLA path unless the
    # caller forces pallas explicitly
    if platform is not None:
        return platform == "tpu"
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def flash_attention_with_lse(q, k, v, causal=False, scale=None,
                             force=None, platform=None):
    """(out, lse) variant of flash_attention for blockwise/ring
    combiners. BOTH outputs are differentiable: the Pallas backward
    folds the lse cotangent into its row term (glse in the kernels)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    use_pallas = (force in ("pallas", "interpret") or
                  (force is None and _pallas_eligible(q, k, platform)))
    if not use_pallas:
        return reference_attention_with_lse(q, k, v, causal, scale)
    interpret = force == "interpret"
    b, h, s, _ = q.shape

    @jax.custom_vjp
    def fn(q, k, v):
        out, lse = _flash_pallas(q, k, v, causal, scale,
                                 interpret=interpret)
        return out, lse[:, :, 0].reshape(b, h, s)

    def fwd(q, k, v):
        out, lse = _flash_pallas(q, k, v, causal, scale,
                                 interpret=interpret)
        return ((out, lse[:, :, 0].reshape(b, h, s)),
                (q, k, v, out, lse))

    def bwd(res, cotangents):
        g_o, g_lse = cotangents
        q, k, v, out, lse = res
        return _flash_pallas_bwd(q, k, v, out, lse, g_o, causal, scale,
                                 interpret=interpret, g_lse=g_lse)

    fn.defvjp(fwd, bwd)
    return fn(q, k, v)


def _flash_pallas_trainable(q, k, v, causal, scale, interpret=False,
                            block_q=None, block_k=None):
    """Pallas forward + Pallas recompute-based backward (FlashAttention-2
    style): the forward saves only O and the per-row logsumexp; the
    backward re-materializes softmax blocks from them in VMEM. Activation
    memory is O(B*H*S*D + B*H*S), never O(S^2) — the long-context
    training path."""

    @jax.custom_vjp
    def fn(q, k, v):
        out, _ = _flash_pallas(q, k, v, causal, scale, interpret=interpret,
                               block_q=block_q, block_k=block_k)
        return out

    def fwd(q, k, v):
        out, lse = _flash_pallas(q, k, v, causal, scale,
                                 interpret=interpret, block_q=block_q,
                                 block_k=block_k)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, out, lse = res
        return _flash_pallas_bwd(q, k, v, out, lse, g, causal, scale,
                                 interpret=interpret, block_q=block_q,
                                 block_k=block_k)

    fn.defvjp(fwd, bwd)
    return fn(q, k, v)


def flash_attention(q, k, v, causal=False, scale=None, force=None,
                    platform=None, block_q=None, block_k=None):
    """Blockwise attention: Pallas kernel on TPU, fused XLA otherwise.

    force: None (auto) | 'pallas' | 'xla' | 'interpret' (kernel under the
    Pallas interpreter — CPU-testable). `platform` is the jit target's
    platform when the caller compiles for a specific device (the executor
    plumbs it via OpCtx); auto mode must not pick the pallas path for a
    cpu-targeted program just because the DEFAULT backend is a TPU.

    GQA/MQA: k/v may carry fewer heads than q (H % H_kv == 0) — the
    kernels stream the SHARED kv blocks (no repeated copy; dK/dV group
    partials reduce outside the kernel). block_q/block_k override the
    default 128 tiling (tools/attention_sweep.py measures the curve).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if force == "xla":
        return reference_attention(q, k, v, causal, scale)
    if force == "interpret":
        return _flash_pallas_trainable(q, k, v, causal, scale,
                                       interpret=True, block_q=block_q,
                                       block_k=block_k)
    if force == "pallas" or (force is None and
                             _pallas_eligible(q, k, platform, block_q,
                                              block_k)):
        return _flash_pallas_trainable(q, k, v, causal, scale,
                                       block_q=block_q, block_k=block_k)
    return reference_attention(q, k, v, causal, scale)


# -- decode mode (q_len = 1 against a KV cache) -----------------------------

def reference_decode_attention(q, k, v, lengths, scale=None):
    """Dense decode-step oracle. q (B, H, D) is the current token's
    query; k/v (B, H_kv, S, D) are KV caches of which only the first
    ``lengths[b]`` positions are valid (the rest is stale pool memory and
    MUST NOT leak into the softmax). Returns (B, H, D). Rows with
    lengths == 0 produce zeros (the empty-softmax convention shared with
    reference_attention_with_lse)."""
    b, h, d = q.shape
    h_kv, s = k.shape[1], k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if h_kv != h:
        group = h // h_kv
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    scores = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s), 2)
    valid = pos < jnp.asarray(lengths, jnp.int32).reshape(b, 1, 1)
    scores = jnp.where(valid, scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)
    safe = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(scores - safe[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l == 0, 1.0, l)
    out = jnp.einsum("bhs,bhsd->bhd", p,
                     v.astype(jnp.float32)) / l_safe[..., None]
    return out.astype(q.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, *, block_k,
                   seq_len, scale):
    """One kv-head grid cell of the decode step: the q "rows" are the
    GQA group sharing this kv head (the q_len=1 realization of the
    forward kernel's (q-block, kv-stream) structure — the group axis
    stands in for the q-block so the MXU still sees a matmul). K/V
    stream in blocks with the online softmax; positions >= the session's
    length are masked (stale pool memory beyond the write cursor)."""
    import jax.experimental.pallas as pl

    q = q_ref[:]                                        # (G, D)
    l = len_ref[0, 0]                                   # valid kv length
    g = q.shape[0]
    acc0 = jnp.zeros((g, q.shape[1]), jnp.float32)
    m0 = jnp.full((g, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    # dynamic block bound: blocks wholly past the write cursor contribute
    # nothing — the decode cost scales with the session's length, not the
    # pool's max_len
    n_blocks = jnp.minimum(seq_len // block_k,
                           (l + block_k - 1) // block_k)

    def body(i, carry):
        acc, m, lsum = carry
        start = i * block_k
        k_blk = k_ref[pl.dslice(start, block_k), :]
        v_blk = v_ref[pl.dslice(start, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (G, Bk)
        k_pos = start + jax.lax.broadcasted_iota(jnp.int32,
                                                 (1, block_k), 1)
        s = jnp.where(k_pos < l, s, -jnp.inf)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe))
        l_new = lsum * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, _, lsum = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    l_safe = jnp.where(lsum == 0, 1.0, lsum)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)


def _decode_pallas(q, k, v, lengths, scale, interpret=False):
    import jax.experimental.pallas as pl

    b, h, d = q.shape
    h_kv, s = k.shape[1], k.shape[2]
    group = h // h_kv
    block_k = min(_auto_block(s), s)
    qf = q.reshape(b * h_kv, group, d)
    kf = k.reshape(b * h_kv, s, d)
    vf = v.reshape(b * h_kv, s, d)
    lens = jnp.asarray(lengths, jnp.int32).reshape(b, 1, 1)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               seq_len=s, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h_kv,),
        in_specs=[
            pl.BlockSpec((None, group, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((None, s, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((None, s, d), lambda bh: (bh, 0, 0)),
            pl.BlockSpec((None, 1, 1), lambda bh: (bh // h_kv, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, group, d), lambda bh: (bh, 0, 0)),
        out_shape=_sds((b * h_kv, group, d), q.dtype, q),
        interpret=interpret,
        **_vmem_params(s, d, 2, interpret, q.dtype.itemsize),
    )(qf, kf, vf, lens)
    return out.reshape(b, h, d)


def _decode_eligible(q, k, platform=None):
    b, h, d = q.shape
    if k.shape[0] != b or k.shape[3] != d or k.shape[1] == 0 \
            or h % k.shape[1] != 0:
        return False
    s = k.shape[2]
    if d % 128 != 0 and d not in (64,):
        return False
    if s % min(_auto_block(s), s) != 0 or s < 8:
        return False
    if platform is not None:
        return platform == "tpu"
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def decode_attention(q, k, v, lengths, scale=None, force=None,
                     platform=None):
    """Single-token decode attention against a length-masked KV cache.

    q (B, H, D); k/v (B, H_kv, S, D) pool blocks; lengths (B,) int32
    valid-prefix lengths. GQA shares kv in-kernel exactly like
    flash_attention (the kv-head grid cell serves its whole q group).
    force: None (auto: Pallas on TPU-eligible shapes) | 'pallas' |
    'xla' | 'interpret'."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    if force == "xla":
        return reference_decode_attention(q, k, v, lengths, scale)
    if force in ("pallas", "interpret") or \
            (force is None and _decode_eligible(q, k, platform)):
        return _decode_pallas(q, k, v, lengths, scale,
                              interpret=force == "interpret")
    return reference_decode_attention(q, k, v, lengths, scale)


# -- registry surface -------------------------------------------------------

def _flash_attention_op(attrs, octx, q, k, v):
    return _t(flash_attention(q, k, v, causal=attrs["causal"],
                              scale=attrs["scale"],
                              platform=octx.platform))


register("_contrib_flash_attention", _flash_attention_op,
         params={"causal": Param("bool", False),
                 "scale": Param("float", None)},
         inputs=("query", "key", "value"),
         infer_shape=lambda attrs, s: (s, [s[0]]))

