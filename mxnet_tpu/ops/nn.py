"""Neural-network operators.

Parity target: src/operator/nn/ + legacy top-level ops (SURVEY.md §2.2 —
Convolution, Deconvolution, FullyConnected, BatchNorm, LayerNorm, LRN, Pooling,
Activation, softmax, Dropout, Embedding, UpSampling, SoftmaxOutput,
*RegressionOutput, MakeLoss, SequenceMask/Last/Reverse, InstanceNorm,
L2Normalization, LeakyReLU). All map onto XLA HLO (conv_general_dilated,
reduce_window, dot_general) so the MXU does the FLOPs; no cuDNN/mkldnn-style
per-backend kernels are needed. Ops whose reference *backward* differs from
the mathematical vjp of their forward (SoftmaxOutput & friends — their grad is
defined through the implied loss) use jax.custom_vjp.

Shape inference fills unknown weight shapes from data shapes, reproducing
FInferShape's bidirectional contract that `simple_bind` relies on.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .registry import Param, register


def _t(*outs):
    return tuple(outs)


def _prod(xs):
    n = 1
    for x in xs:
        n *= x
    return n


# ---------------------------------------------------------------------------
# FullyConnected (src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _bias_add_dead_grad(y, b):
    """y + b where d(b) is a structural zero.

    Applied by the executor's dead-bias pass (executor.py:_dead_bias_convs)
    when the op's only consumer is a batch-stats BatchNorm: the BN output is
    invariant to a per-channel shift, so the true bias gradient is exactly
    zero — this just stops XLA from spending a full pass over dy to compute
    that zero. Forward is bit-identical to a plain add.
    """
    return y + b


def _bias_add_dead_fwd(y, b):
    return y + b, b  # b is a (C,)-sized vector; kept only for zeros_like


def _bias_add_dead_bwd(b, dy):
    return dy, jnp.zeros_like(b)


_bias_add_dead_grad.defvjp(_bias_add_dead_fwd, _bias_add_dead_bwd)


def _add_bias(attrs, y, bias):
    if attrs.get("__bias_grad_dead__"):
        return _bias_add_dead_grad(y, bias.astype(y.dtype))
    return y + bias.astype(y.dtype)


def _fc(attrs, octx, data, weight, bias=None):
    x = data.reshape(data.shape[0], -1) if attrs["flatten"] else data
    y = jnp.matmul(x, weight.T)  # weight: (num_hidden, in_dim) — MXNet layout
    if not attrs["no_bias"]:
        y = _add_bias(attrs, y, bias)
    return _t(y)


def _fc_infer(attrs, in_shapes):
    ds = in_shapes[0]
    nh = attrs["num_hidden"]
    if ds is not None:
        in_dim = _prod(ds[1:]) if attrs["flatten"] else ds[-1]
        if in_shapes[1] is None:
            in_shapes = list(in_shapes)
            in_shapes[1] = (nh, in_dim)
    if not attrs["no_bias"] and len(in_shapes) > 2 and in_shapes[2] is None:
        in_shapes = list(in_shapes)
        in_shapes[2] = (nh,)
    if ds is None:
        return in_shapes, [None]
    out = (ds[0], nh) if attrs["flatten"] else tuple(ds[:-1]) + (nh,)
    return in_shapes, [out]


def _fc_inputs(attrs):
    return ["data", "weight"] if attrs["no_bias"] else ["data", "weight", "bias"]


_fc_schema = register(
    "FullyConnected", _fc,
    params={"num_hidden": Param("int", None, True),
            "no_bias": Param("bool", False),
            "flatten": Param("bool", True)},
    inputs=("data", "weight", "bias"), infer_shape=_fc_infer)
_fc_schema.list_inputs = _fc_inputs  # type: ignore[method-assign]
_fc_schema.num_inputs = lambda attrs: 2 if attrs["no_bias"] else 3  # type: ignore

# ---------------------------------------------------------------------------
# Convolution / Deconvolution (src/operator/nn/convolution.cc)
# ---------------------------------------------------------------------------

_CONV_SPECS = {1: ("NCW", "OIW", "NCW"),
               2: ("NCHW", "OIHW", "NCHW"),
               3: ("NCDHW", "OIDHW", "NCDHW")}


def _conv_attrs(attrs, nspatial):
    k = attrs["kernel"]
    stride = attrs["stride"] or (1,) * nspatial
    dilate = attrs["dilate"] or (1,) * nspatial
    pad = attrs["pad"] or (0,) * nspatial
    return k, tuple(stride), tuple(dilate), tuple(pad)


def _conv(attrs, octx, data, weight, bias=None):
    ns = len(attrs["kernel"])
    k, stride, dilate, pad = _conv_attrs(attrs, ns)
    # NOTE: no preferred_element_type=f32 for bf16 inputs — the MXU already
    # accumulates in fp32 internally, and a widened output dtype breaks the
    # conv transpose rule under reverse-mode (f32 cotangent x bf16 weight)
    y = jax.lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=_CONV_SPECS[ns],
        feature_group_count=attrs["num_group"])
    if y.dtype != data.dtype:
        y = y.astype(data.dtype)
    if not attrs["no_bias"]:
        # bias cast at the use site: a fp32 bias must not promote bf16
        # activations (mixed-precision discipline, same as _batch_norm)
        y = _add_bias(attrs, y, bias.reshape((1, -1) + (1,) * ns))
    return _t(y)


def _conv_out_dim(d, k, s, p, dil):
    return (d + 2 * p - (dil * (k - 1) + 1)) // s + 1


def _conv_infer(attrs, in_shapes):
    ds = in_shapes[0]
    nf = attrs["num_filter"]
    ns = len(attrs["kernel"])
    k, stride, dilate, pad = _conv_attrs(attrs, ns)
    in_shapes = list(in_shapes)
    if ds is not None and in_shapes[1] is None:
        in_shapes[1] = (nf, ds[1] // attrs["num_group"]) + tuple(k)
    if not attrs["no_bias"] and len(in_shapes) > 2 and in_shapes[2] is None:
        in_shapes[2] = (nf,)
    if ds is None:
        return in_shapes, [None]
    spatial = tuple(_conv_out_dim(ds[2 + i], k[i], stride[i], pad[i], dilate[i])
                    for i in range(ns))
    return in_shapes, [(ds[0], nf) + spatial]


_conv_params = {"kernel": Param("shape", None, True),
                "stride": Param("shape", None),
                "dilate": Param("shape", None),
                "pad": Param("shape", None),
                "num_filter": Param("int", None, True),
                "num_group": Param("int", 1),
                "no_bias": Param("bool", False),
                "workspace": Param("int", 1024),
                "cudnn_tune": Param("str", None),
                "cudnn_off": Param("bool", False),
                "layout": Param("str", None)}

_conv_schema = register("Convolution", _conv, params=dict(_conv_params),
                        inputs=("data", "weight", "bias"),
                        infer_shape=_conv_infer)
_conv_schema.list_inputs = _fc_inputs  # type: ignore
_conv_schema.num_inputs = lambda attrs: 2 if attrs["no_bias"] else 3  # type: ignore


def _deconv(attrs, octx, data, weight, bias=None):
    ns = len(attrs["kernel"])
    k, stride, dilate, pad = _conv_attrs(attrs, ns)
    adj = attrs["adj"] or (0,) * ns
    # Deconvolution == gradient of Convolution w.r.t. its input. Weight layout
    # is (in_channels, num_filter/num_group, *kernel) (deconvolution-inl.h).
    g = attrs["num_group"]
    # transposed conv via lhs dilation
    pads = []
    for i in range(ns):
        eff_k = dilate[i] * (k[i] - 1) + 1
        lo = eff_k - 1 - pad[i]
        hi = eff_k - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    # weight (Cin, Cout/g, *k) -> flip spatial, swap to (Cout, Cin/g, *k)
    w = jnp.flip(weight, axis=tuple(range(2, 2 + ns)))
    if g == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        cin = weight.shape[0]
        w = w.reshape((g, cin // g) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)
        w = w.reshape((-1, cin // g) + tuple(k))
    y = jax.lax.conv_general_dilated(
        data, w, window_strides=(1,) * ns, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate,
        dimension_numbers=_CONV_SPECS[ns], feature_group_count=g)
    if not attrs["no_bias"]:
        y = y + bias.reshape((1, -1) + (1,) * ns).astype(y.dtype)
    return _t(y)


def _deconv_infer(attrs, in_shapes):
    ds = in_shapes[0]
    nf = attrs["num_filter"]
    ns = len(attrs["kernel"])
    k, stride, dilate, pad = _conv_attrs(attrs, ns)
    adj = attrs["adj"] or (0,) * ns
    in_shapes = list(in_shapes)
    if ds is not None and in_shapes[1] is None:
        in_shapes[1] = (ds[1], nf // attrs["num_group"]) + tuple(k)
    if not attrs["no_bias"] and len(in_shapes) > 2 and in_shapes[2] is None:
        in_shapes[2] = (nf,)
    if ds is None:
        return in_shapes, [None]
    spatial = tuple(
        stride[i] * (ds[2 + i] - 1) + dilate[i] * (k[i] - 1) + 1
        - 2 * pad[i] + adj[i]
        for i in range(ns))
    return in_shapes, [(ds[0], nf) + spatial]


_deconv_params = dict(_conv_params)
_deconv_params["adj"] = Param("shape", None)
_deconv_params["target_shape"] = Param("shape", None)
_deconv_schema = register("Deconvolution", _deconv, params=_deconv_params,
                          inputs=("data", "weight", "bias"),
                          infer_shape=_deconv_infer)
_deconv_schema.list_inputs = _fc_inputs  # type: ignore
_deconv_schema.num_inputs = lambda attrs: 2 if attrs["no_bias"] else 3  # type: ignore

# ---------------------------------------------------------------------------
# Pooling (src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------

def _pooling(attrs, octx, data):
    ptype = attrs["pool_type"]
    ns = data.ndim - 2
    if attrs["global_pool"]:
        axes = tuple(range(2, data.ndim))
        red = {"max": jnp.max, "avg": jnp.mean, "sum": jnp.sum}[ptype]
        y = red(data, axis=axes, keepdims=True)
        return _t(y)
    k = attrs["kernel"]
    stride = tuple(attrs["stride"] or (1,) * ns)
    pad = tuple(attrs["pad"] or (0,) * ns)
    window = (1, 1) + tuple(k)
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if attrs["pooling_convention"] == "full":
        # ceil-mode output: widen right pad so the last partial window counts
        for i in range(ns):
            d = data.shape[2 + i]
            out_full = -(-(d + 2 * pad[i] - k[i]) // stride[i]) + 1
            span = (out_full - 1) * stride[i] + k[i]
            extra = max(0, span - (d + 2 * pad[i]))
            pads[2 + i] = (pad[i], pad[i] + extra)
    if ptype == "max":
        # init must stay a python scalar: a traced-array init defeats jax's
        # reduce_window monoid recognition and kills reverse-mode autodiff
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            int(jnp.iinfo(data.dtype).min)
        y = jax.lax.reduce_window(data, init,
                                  jax.lax.max, window, strides, pads)
    else:
        zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
        y = jax.lax.reduce_window(data, zero,
                                  jax.lax.add, window, strides, pads)
        if ptype == "avg":
            if attrs["count_include_pad"]:
                y = y / _prod(k)
            else:
                ones = jnp.ones(data.shape, dtype=data.dtype)
                cnt = jax.lax.reduce_window(ones, zero,
                                            jax.lax.add, window, strides, pads)
                y = y / cnt
    return _t(y)


def _pool_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    if attrs["global_pool"]:
        return in_shapes, [tuple(ds[:2]) + (1,) * (len(ds) - 2)]
    ns = len(ds) - 2
    k = attrs["kernel"]
    stride = tuple(attrs["stride"] or (1,) * ns)
    pad = tuple(attrs["pad"] or (0,) * ns)
    out = []
    for i in range(ns):
        if attrs["pooling_convention"] == "full":
            out.append(-(-(ds[2 + i] + 2 * pad[i] - k[i]) // stride[i]) + 1)
        else:
            out.append((ds[2 + i] + 2 * pad[i] - k[i]) // stride[i] + 1)
    return in_shapes, [tuple(ds[:2]) + tuple(out)]


register("Pooling", _pooling,
         params={"kernel": Param("shape", ()),
                 "pool_type": Param("str", "max"),
                 "global_pool": Param("bool", False),
                 "stride": Param("shape", None),
                 "pad": Param("shape", None),
                 "pooling_convention": Param("str", "valid"),
                 "count_include_pad": Param("bool", True),
                 "cudnn_off": Param("bool", False)},
         infer_shape=_pool_infer)

# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

def _activation(attrs, octx, x):
    t = attrs["act_type"]
    if t == "relu":
        return _t(jnp.maximum(x, 0))
    if t == "sigmoid":
        return _t(jax.nn.sigmoid(x))
    if t == "tanh":
        return _t(jnp.tanh(x))
    if t == "softrelu":
        return _t(jax.nn.softplus(x))
    if t == "softsign":
        return _t(x / (1 + jnp.abs(x)))
    raise MXNetError(f"Activation: unknown act_type {t}")


def _same1(attrs, in_shapes):
    return in_shapes, [in_shapes[0]]

register("Activation", _activation,
         params={"act_type": Param("str", None, True)}, infer_shape=_same1)


def _leaky_relu(attrs, octx, *inputs):
    t = attrs["act_type"]
    x = inputs[0]
    slope = attrs["slope"]
    if t == "leaky":
        return _t(jnp.where(x > 0, x, slope * x))
    if t == "elu":
        return _t(jnp.where(x > 0, x, slope * (jnp.exp(x) - 1)))
    if t == "prelu":
        gamma = inputs[1]
        g = gamma.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else gamma
        return _t(jnp.where(x > 0, x, g * x))
    if t == "rrelu":
        lo, hi = attrs["lower_bound"], attrs["upper_bound"]
        if octx.is_train and octx.rng is not None:
            a = jax.random.uniform(octx.rng, x.shape, dtype=x.dtype,
                                   minval=lo, maxval=hi)
        else:
            a = (lo + hi) / 2.0
        return _t(jnp.where(x > 0, x, a * x))
    if t == "gelu":
        return _t(jax.nn.gelu(x))
    raise MXNetError(f"LeakyReLU: unknown act_type {t}")


def _lrelu_infer(attrs, in_shapes):
    in_shapes = list(in_shapes)
    if attrs["act_type"] == "prelu" and len(in_shapes) > 1 and \
            in_shapes[1] is None and in_shapes[0] is not None:
        in_shapes[1] = (in_shapes[0][1],)
    return in_shapes, [in_shapes[0]]


_lrelu_schema = register(
    "LeakyReLU", _leaky_relu,
    params={"act_type": Param("str", "leaky"),
            "slope": Param("float", 0.25),
            "lower_bound": Param("float", 0.125),
            "upper_bound": Param("float", 0.334)},
    inputs=("data", "gamma"), needs_rng=True, infer_shape=_lrelu_infer)
_lrelu_schema.num_inputs = lambda a: 2 if a["act_type"] == "prelu" else 1  # type: ignore
_lrelu_schema.list_inputs = lambda a: (["data", "gamma"]  # type: ignore
                                       if a["act_type"] == "prelu" else ["data"])

# ---------------------------------------------------------------------------
# softmax family (src/operator/nn/softmax.cc)
# ---------------------------------------------------------------------------

def _softmax(attrs, octx, x):
    z = x / attrs["temperature"] if attrs["temperature"] != 1.0 else x
    return _t(jax.nn.softmax(z, axis=attrs["axis"]))

register("softmax", _softmax,
         params={"axis": Param("int", -1), "temperature": Param("float", 1.0)},
         infer_shape=_same1)


def _log_softmax(attrs, octx, x):
    z = x / attrs["temperature"] if attrs["temperature"] != 1.0 else x
    return _t(jax.nn.log_softmax(z, axis=attrs["axis"]))

register("log_softmax", _log_softmax,
         params={"axis": Param("int", -1), "temperature": Param("float", 1.0)},
         infer_shape=_same1)


def _softmax_activation(attrs, octx, x):
    if attrs["mode"] == "channel":
        return _t(jax.nn.softmax(x, axis=1))
    return _t(jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape))

register("SoftmaxActivation", _softmax_activation,
         params={"mode": Param("str", "instance")}, infer_shape=_same1)


# SoftmaxOutput: forward=softmax, backward=(p - onehot(label)) scaled — the
# reference defines the grad through the implied CE loss
# (src/operator/softmax_output-inl.h). custom_vjp reproduces that contract.

def _softmax_output(attrs, octx, data, label):
    grad_scale = attrs["grad_scale"]
    ignore_label = attrs["ignore_label"]
    use_ignore = attrs["use_ignore"]
    multi_output = attrs["multi_output"]
    preserve_shape = attrs["preserve_shape"]
    normalization = attrs["normalization"]
    smooth_alpha = attrs["smooth_alpha"]

    axis = 1 if multi_output else -1
    if not multi_output and not preserve_shape and data.ndim > 2:
        pass  # softmax over trailing axis of flattened rows == last axis

    @jax.custom_vjp
    def _fn(d, lbl):
        return jax.nn.softmax(d, axis=axis)

    def _fwd(d, lbl):
        out = jax.nn.softmax(d, axis=axis)
        return out, (out, lbl)

    def _bwd(res, g):
        out, lbl = res
        nclass = out.shape[axis]
        if lbl.shape == out.shape:
            tgt = lbl
            valid = jnp.ones(lbl.shape[:1], dtype=out.dtype)
        else:
            li = lbl.astype(jnp.int32)
            oh = jax.nn.one_hot(li, nclass, dtype=out.dtype)
            if multi_output:
                # label (n, d...) -> one_hot gives (n, d..., c); move c to axis 1
                oh = jnp.moveaxis(oh, -1, 1)
            tgt = oh
            if smooth_alpha:
                tgt = tgt * (1 - smooth_alpha) + smooth_alpha / (nclass - 1) * (1 - tgt)
            valid = jnp.ones(li.shape, dtype=out.dtype)
            if use_ignore:
                mask = (li != int(ignore_label)).astype(out.dtype)
                valid = mask
                if multi_output:
                    tgt = tgt * jnp.expand_dims(mask, 1)
                    out_m = out * jnp.expand_dims(mask, 1)
                else:
                    tgt = tgt * mask[..., None]
                    out_m = out * mask[..., None]
            else:
                out_m = out
        if not use_ignore:
            out_m = out
        grad = out_m - tgt
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            grad = grad / jnp.maximum(jnp.sum(valid), 1.0)
        grad = grad * grad_scale
        return grad.astype(out.dtype), jnp.zeros_like(lbl)

    _fn.defvjp(_fwd, _bwd)
    return _t(_fn(data, label))


def _softmax_output_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = list(in_shapes)
    if ds is not None and in_shapes[1] is None:
        if attrs["multi_output"]:
            in_shapes[1] = (ds[0],) + tuple(ds[2:])
        else:
            in_shapes[1] = tuple(ds[:-1])
    return in_shapes, [ds]


register("SoftmaxOutput", _softmax_output,
         params={"grad_scale": Param("float", 1.0),
                 "ignore_label": Param("float", -1.0),
                 "use_ignore": Param("bool", False),
                 "multi_output": Param("bool", False),
                 "preserve_shape": Param("bool", False),
                 "normalization": Param("str", "null"),
                 "out_grad": Param("bool", False),
                 "smooth_alpha": Param("float", 0.0)},
         inputs=("data", "label"), aliases=("Softmax",),
         infer_shape=_softmax_output_infer)

# ---------------------------------------------------------------------------
# Normalization layers
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _bn_train(data, gamma, beta, axis, eps, fix_gamma, relu):
    """Training-mode BN core: returns (out, batch_mean, batch_var).

    Hand-written vjp for HBM-roofline reasons (docs/perf_analysis_r03.md):
    the backward does the minimal two passes (one for the dgamma/dbeta
    sums, one for dx) instead of autodiff's mean->var dependency chain.
    Stats accumulate in fp32 regardless of the activation dtype (stable
    two-pass variance — see _bn_stats). `relu` folds a following
    Activation('relu') node into the kernel (executor BN+ReLU fusion pass):
    the backward masks dy inline instead of paying a separate full
    read+write pass over the activation tensor.
    """
    return _bn_train_fwd(data, gamma, beta, axis, eps, fix_gamma, relu)[0]


def _bn_stats(data, red_axes):
    # two-pass variance (mean first, then E[(x-mean)^2]) — the one-pass
    # E[x^2]-mean^2 form cancels catastrophically when |mean| >> std
    # (measured: fp32 data with mean 1e3/std 1e-2 yields var=-0.19 -> NaN
    # through rsqrt; the reference's CPU BN is two-pass for the same
    # reason). Costs ~4% ResNet-50 step time vs one-pass; correctness wins.
    m = jnp.mean(data, axis=red_axes, dtype=jnp.float32)
    bshape = tuple(1 if i in red_axes else s
                   for i, s in enumerate(data.shape))
    d = data.astype(jnp.float32) - m.reshape(bshape)
    return m, jnp.mean(jax.lax.square(d), axis=red_axes)


def _bn_train_fwd(data, gamma, beta, axis, eps, fix_gamma, relu):
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    mean, var = _bn_stats(data, red_axes)
    rstd = jax.lax.rsqrt(var + eps)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    scale = (g.astype(jnp.float32) * rstd).astype(data.dtype)
    shift = (beta.astype(jnp.float32)
             - mean * g.astype(jnp.float32) * rstd).astype(data.dtype)
    out = data * scale.reshape(bshape) + shift.reshape(bshape)
    if relu:
        out = jnp.maximum(out, 0)
    return (out, mean, var), (data, gamma, beta, mean, rstd)


def _bn_train_bwd(axis, eps, fix_gamma, relu, res, cts):
    # cotangents for the mean/var outputs are ignored: callers feed them
    # only into the stop-gradient EMA update, so they are exact zeros
    data, gamma, beta, mean, rstd = res
    dy = cts[0]
    red_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    n = _prod(data.shape[i] for i in red_axes)
    xhat = (data - mean.reshape(bshape).astype(data.dtype)) \
        * rstd.reshape(bshape).astype(data.dtype)
    if relu:
        # recompute the relu mask from xhat (cheaper than saving `out`:
        # out > 0 <=> g*xhat + beta > 0, all in-registers here).
        # Accepted tradeoff: g*xhat + beta is a different bf16 evaluation
        # order than the forward's data*scale + shift, so an element
        # landing EXACTLY on the relu boundary can round to a different
        # side and flip its mask bit — bounded by one ulp of gradient
        # noise on measure-zero inputs, in exchange for not saving `out`
        g_b = (jnp.ones_like(gamma) if fix_gamma else gamma) \
            .reshape(bshape).astype(data.dtype)
        pre = xhat * g_b + beta.reshape(bshape).astype(data.dtype)
        dy = jnp.where(pre > 0, dy, jnp.zeros((), dy.dtype))
    # pass 1: both channel reductions stream (dy, data) once
    dbeta = jnp.sum(dy, axis=red_axes, dtype=jnp.float32)
    dgamma = jnp.sum(dy * xhat, axis=red_axes, dtype=jnp.float32)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    coef = (g.astype(jnp.float32) * rstd).reshape(bshape).astype(data.dtype)
    # pass 2: dx from dy, data and the reduced sums
    dx = coef * (dy
                 - (dbeta / n).reshape(bshape).astype(data.dtype)
                 - xhat * (dgamma / n).reshape(bshape).astype(data.dtype))
    dgamma_out = jnp.zeros_like(gamma) if fix_gamma \
        else dgamma.astype(gamma.dtype)
    return dx, dgamma_out, dbeta.astype(gamma.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def _batch_norm(attrs, octx, data, gamma, beta, moving_mean, moving_var):
    eps = attrs["eps"]
    momentum = attrs["momentum"]
    axis = attrs["axis"] % data.ndim
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))

    fuse_relu = bool(attrs.get("__fuse_relu__", False))
    use_batch = octx.is_train and not attrs["use_global_stats"]
    if use_batch:
        out, mean, var = _bn_train(data, gamma, beta, axis, eps,
                                   bool(attrs["fix_gamma"]), fuse_relu)
        mean = jax.lax.stop_gradient(mean).astype(moving_mean.dtype)
        var = jax.lax.stop_gradient(var).astype(moving_var.dtype)
        new_mean = momentum * moving_mean + (1 - momentum) * mean
        new_var = momentum * moving_var + (1 - momentum) * var
        return (out, new_mean, new_var)
    g = jnp.ones_like(gamma) if attrs["fix_gamma"] else gamma
    mean, var = moving_mean, moving_var
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * \
        inv.reshape(bshape) * g.reshape(bshape).astype(data.dtype) + \
        beta.reshape(bshape).astype(data.dtype)
    if fuse_relu:
        out = jnp.maximum(out, 0)
    return (out, mean, var)


def _bn_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = list(in_shapes)
    if ds is not None:
        c = (ds[attrs["axis"] % len(ds)],)
        for i in range(1, 5):
            if in_shapes[i] is None:
                in_shapes[i] = c
    return in_shapes, [ds]


register("BatchNorm", _batch_norm,
         params={"eps": Param("float", 1e-3),
                 "momentum": Param("float", 0.9),
                 "fix_gamma": Param("bool", True),
                 "use_global_stats": Param("bool", False),
                 "output_mean_var": Param("bool", False),
                 "axis": Param("int", 1),
                 "cudnn_off": Param("bool", False)},
         inputs=("data", "gamma", "beta", "moving_mean", "moving_var"),
         aux=("moving_mean", "moving_var"), mutates_aux=True,
         infer_shape=_bn_infer, aliases=("BatchNorm_v1",))


def _layer_norm(attrs, octx, data, gamma, beta):
    axis = attrs["axis"] % data.ndim
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + attrs["eps"])
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    return _t(out * gamma.reshape(bshape) + beta.reshape(bshape))


def _ln_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = list(in_shapes)
    if ds is not None:
        c = (ds[attrs["axis"] % len(ds)],)
        for i in (1, 2):
            if in_shapes[i] is None:
                in_shapes[i] = c
    return in_shapes, [ds]


register("LayerNorm", _layer_norm,
         params={"axis": Param("int", -1), "eps": Param("float", 1e-5),
                 "output_mean_var": Param("bool", False)},
         inputs=("data", "gamma", "beta"), infer_shape=_ln_infer)


def _instance_norm(attrs, octx, data, gamma, beta):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + attrs["eps"])
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return _t(out * gamma.reshape(bshape) + beta.reshape(bshape))


def _in_infer(attrs, in_shapes):
    ds = in_shapes[0]
    in_shapes = list(in_shapes)
    if ds is not None:
        for i in (1, 2):
            if in_shapes[i] is None:
                in_shapes[i] = (ds[1],)
    return in_shapes, [ds]


register("InstanceNorm", _instance_norm,
         params={"eps": Param("float", 1e-3)},
         inputs=("data", "gamma", "beta"), infer_shape=_in_infer)


def _l2_normalization(attrs, octx, data):
    eps = attrs["eps"]
    mode = attrs["mode"]
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError(f"L2Normalization: unknown mode {mode}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return _t(data / norm)

register("L2Normalization", _l2_normalization,
         params={"eps": Param("float", 1e-10),
                 "mode": Param("str", "instance")}, infer_shape=_same1)


def _lrn(attrs, octx, data):
    n = attrs["nsize"]
    alpha, beta, knorm = attrs["alpha"], attrs["beta"], attrs["knorm"]
    sq = jnp.square(data)
    half = n // 2
    pads = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add,
                                (1, n) + (1,) * (data.ndim - 2),
                                (1,) * data.ndim, pads)
    return _t(data / jnp.power(knorm + (alpha / n) * acc, beta))

register("LRN", _lrn,
         params={"alpha": Param("float", 1e-4), "beta": Param("float", 0.75),
                 "knorm": Param("float", 2.0), "nsize": Param("int", None, True)},
         infer_shape=_same1)

# ---------------------------------------------------------------------------
# Dropout / Embedding / UpSampling
# ---------------------------------------------------------------------------

def _dropout(attrs, octx, x):
    p = attrs["p"]
    mode = attrs["mode"]
    apply_drop = (octx.is_train or mode == "always") and p > 0
    if not apply_drop or octx.rng is None:
        return _t(x)
    keep = 1.0 - p
    mask = jax.random.bernoulli(octx.rng, keep, x.shape)
    return _t(jnp.where(mask, x / keep, 0).astype(x.dtype))

register("Dropout", _dropout,
         params={"p": Param("float", 0.5), "mode": Param("str", "training"),
                 "axes": Param("shape", None)},
         needs_rng=True, infer_shape=_same1)


def _embedding(attrs, octx, data, weight):
    idx = jnp.clip(data.astype(jnp.int32), 0, attrs["input_dim"] - 1)
    return _t(jnp.take(weight, idx, axis=0))


def _embedding_infer(attrs, in_shapes):
    in_shapes = list(in_shapes)
    if in_shapes[1] is None:
        in_shapes[1] = (attrs["input_dim"], attrs["output_dim"])
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    return in_shapes, [tuple(ds) + (attrs["output_dim"],)]


register("Embedding", _embedding,
         params={"input_dim": Param("int", None, True),
                 "output_dim": Param("int", None, True),
                 "dtype": Param("dtype", "float32"),
                 "sparse_grad": Param("bool", False)},
         inputs=("data", "weight"), infer_shape=_embedding_infer)


def _upsampling(attrs, octx, *inputs):
    scale = attrs["scale"]
    st = attrs["sample_type"]
    x = inputs[0]
    if st == "nearest":
        y = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        return _t(y)
    if st == "bilinear":
        n, c, h, w = x.shape
        y = jax.image.resize(x, (n, c, h * scale, w * scale), method="bilinear")
        return _t(y)
    raise MXNetError(f"UpSampling: unknown sample_type {st}")


def _upsampling_infer(attrs, in_shapes):
    ds = in_shapes[0]
    if ds is None:
        return in_shapes, [None]
    s = attrs["scale"]
    return in_shapes, [(ds[0], ds[1], ds[2] * s, ds[3] * s)]


_ups_schema = register("UpSampling", _upsampling,
                       params={"scale": Param("int", None, True),
                               "sample_type": Param("str", None, True),
                               "num_filter": Param("int", 0),
                               "multi_input_mode": Param("str", "concat"),
                               "num_args": Param("int", 1),
                               "workspace": Param("int", 512)},
                       inputs=("data",), key_var_num_args="num_args",
                       infer_shape=_upsampling_infer)

# ---------------------------------------------------------------------------
# loss-layer ops (legacy top-level): custom backward through implied loss
# ---------------------------------------------------------------------------

def _regression_output(name, fwd_fn, grad_fn):
    def fcompute(attrs, octx, data, label):
        gs = attrs["grad_scale"]

        @jax.custom_vjp
        def _fn(d, lbl):
            return fwd_fn(d)

        def _f(d, lbl):
            return fwd_fn(d), (fwd_fn(d), lbl)

        def _b(res, g):
            out, lbl = res
            n = _prod(out.shape[1:])  # reference normalizes by num outputs
            grad = grad_fn(out, lbl) * (gs / n)
            return grad.astype(out.dtype), jnp.zeros_like(lbl)

        _fn.defvjp(_f, _b)
        return _t(_fn(data, label))

    def infer(attrs, in_shapes):
        ds = in_shapes[0]
        in_shapes = list(in_shapes)
        if ds is not None and in_shapes[1] is None:
            in_shapes[1] = ds
        return in_shapes, [ds]

    register(name, fcompute, params={"grad_scale": Param("float", 1.0)},
             inputs=("data", "label"), infer_shape=infer)


_regression_output("LinearRegressionOutput",
                   lambda d: d, lambda o, l: o - l)
_regression_output("LogisticRegressionOutput",
                   jax.nn.sigmoid, lambda o, l: o - l)
_regression_output("MAERegressionOutput",
                   lambda d: d, lambda o, l: jnp.sign(o - l))


def _make_loss_op(attrs, octx, data):
    gs = attrs["grad_scale"]
    norm = attrs["normalization"]
    vt = attrs["valid_thresh"]

    @jax.custom_vjp
    def _fn(d):
        return d

    def _f(d):
        return d, d

    def _b(d, g):
        grad = jnp.full_like(d, gs)
        if norm == "batch":
            grad = grad / d.shape[0]
        elif norm == "valid":
            nv = jnp.maximum(jnp.sum((d > vt).astype(d.dtype)), 1.0)
            grad = grad / nv
        return (grad,)

    _fn.defvjp(_f, _b)
    return _t(_fn(data))

register("MakeLoss", _make_loss_op,
         params={"grad_scale": Param("float", 1.0),
                 "valid_thresh": Param("float", 0.0),
                 "normalization": Param("str", "null")},
         infer_shape=_same1)


def _svm_output(attrs, octx, data, label):
    margin = attrs["margin"]
    coef = attrs["regularization_coefficient"]
    use_linear = attrs["use_linear"]

    @jax.custom_vjp
    def _fn(d, lbl):
        return d

    def _f(d, lbl):
        return d, (d, lbl)

    def _b(res, g):
        d, lbl = res
        oh = jax.nn.one_hot(lbl.astype(jnp.int32), d.shape[-1], dtype=d.dtype)
        # hinge: grad = -coef*label_sign where margin violated
        score_y = jnp.sum(d * oh, axis=-1, keepdims=True)
        if use_linear:
            viol = ((d - score_y + margin) > 0).astype(d.dtype) * (1 - oh)
            grad = coef * (viol - oh * jnp.sum(viol, axis=-1, keepdims=True))
        else:
            viol = jnp.maximum(0.0, d - score_y + margin) * (1 - oh)
            grad = 2 * coef * (viol - oh * jnp.sum(viol, axis=-1, keepdims=True))
        return grad, jnp.zeros_like(lbl)

    _fn.defvjp(_f, _b)
    return _t(_fn(data, label))

register("SVMOutput", _svm_output,
         params={"margin": Param("float", 1.0),
                 "regularization_coefficient": Param("float", 1.0),
                 "use_linear": Param("bool", False)},
         inputs=("data", "label"),
         infer_shape=lambda a, s: (([s[0], (s[0][0],) if s[1] is None and
                                     s[0] is not None else s[1]]), [s[0]]))

# ---------------------------------------------------------------------------
# sequence ops (src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------

def _seq_axes(x):
    # layout: (seq_len, batch, ...) — MXNet sequence ops' default
    return 0, 1


def _sequence_mask(attrs, octx, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return _t(data)
    t = data.shape[0]
    steps = jnp.arange(t).reshape((t,) + (1,) * (data.ndim - 1))
    sl = seq_len.reshape((1, -1) + (1,) * (data.ndim - 2))
    mask = steps < sl
    return _t(jnp.where(mask, data, attrs["value"]).astype(data.dtype))


_seqmask_schema = register(
    "SequenceMask", _sequence_mask,
    params={"use_sequence_length": Param("bool", False),
            "value": Param("float", 0.0), "axis": Param("int", 0)},
    inputs=("data", "sequence_length"))
_seqmask_schema.num_inputs = lambda a: 2 if a["use_sequence_length"] else 1  # type: ignore
_seqmask_schema.list_inputs = lambda a: (["data", "sequence_length"]  # type: ignore
                                         if a["use_sequence_length"] else ["data"])


def _sequence_last(attrs, octx, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return _t(data[-1])
    idx = (seq_len.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1])
    return _t(data[idx, batch])


_seqlast_schema = register(
    "SequenceLast", _sequence_last,
    params={"use_sequence_length": Param("bool", False),
            "axis": Param("int", 0)},
    inputs=("data", "sequence_length"))
_seqlast_schema.num_inputs = lambda a: 2 if a["use_sequence_length"] else 1  # type: ignore
_seqlast_schema.list_inputs = _seqmask_schema.list_inputs  # type: ignore


def _sequence_reverse(attrs, octx, data, seq_len=None):
    if not attrs["use_sequence_length"] or seq_len is None:
        return _t(jnp.flip(data, axis=0))
    t = data.shape[0]
    steps = jnp.arange(t)[:, None]
    sl = seq_len.astype(jnp.int32)[None, :]
    src = jnp.where(steps < sl, sl - 1 - steps, steps)
    batch = jnp.arange(data.shape[1])[None, :]
    return _t(data[src, batch])


_seqrev_schema = register(
    "SequenceReverse", _sequence_reverse,
    params={"use_sequence_length": Param("bool", False),
            "axis": Param("int", 0)},
    inputs=("data", "sequence_length"))
_seqrev_schema.num_inputs = lambda a: 2 if a["use_sequence_length"] else 1  # type: ignore
_seqrev_schema.list_inputs = _seqmask_schema.list_inputs  # type: ignore
