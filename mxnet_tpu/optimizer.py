"""Optimizers.

Parity target: python/mxnet/optimizer.py (SURVEY.md §2.4) — registry + base
`Optimizer` (lr/wd multipliers, update counting, multi_precision fp32 master
weights) and the full optimizer family. The heavily-used optimizers (SGD,
Adam, RMSProp, Ftrl, Signum, FTML) delegate to fused on-device update *ops*
(ops/optimizer_ops.py — reference src/operator/optimizer_op.cc); the long tail
is implemented with NDArray arithmetic (each step still compiles to a handful
of fused XLA executables).
"""
from __future__ import annotations

import logging
import math
import pickle

import numpy

from .base import MXNetError, bfloat16 as _bfloat16
from .ndarray import ndarray as nd
from .ndarray.ndarray import NDArray, zeros
from . import ndarray as ndns


def _needs_master_copy(dtype):
    """True for the half dtypes whose weights need an fp32 master copy
    under multi_precision: float16 (the reference's only case) and
    bfloat16 (mxnet_tpu.amp — same 8-bit mantissa problem: repeated
    small updates round to nothing when accumulated in half)."""
    return dtype == numpy.float16 or dtype == _bfloat16

__all__ = ["Optimizer", "SGD", "Signum", "FTML", "DCASGD", "NAG", "SGLD",
           "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "LBSGD", "Test", "Updater", "get_updater", "register",
           "create"]


class Optimizer:
    """Base optimizer. Tracks per-index update counts for schedulers and
    bias correction; resolves lr/wd multipliers from param attrs
    (python/mxnet/optimizer.py:201-223 multi_precision contract)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("New optimizer %s overriding existing", name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    def __init__(self, rescale_grad=1., param_idx2name=None, wd=0.,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision

        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None \
            else ()
        self.param_dict = param_dict if param_dict else {}

        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp16/bf16 weights get an fp32 master copy as leading state
        (reference multi_precision, optimizer.py:201-223; bf16 extension
        via mxnet_tpu.amp)."""
        weight_master_copy = None
        if self.multi_precision and _needs_master_copy(weight.dtype):
            weight_master_copy = weight.astype(numpy.float32)
            return (weight_master_copy,) + (self.create_state(index, weight_master_copy),)
        if _needs_master_copy(weight.dtype) and not self.multi_precision:
            logging.warning("Accumulating with %s in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option of the "
                            "optimizer", weight.dtype)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _needs_master_copy(weight.dtype):
            weight_master_copy = state[0]
            original_state = state[1]
            grad32 = grad.astype(numpy.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight_master_copy.copyto(weight)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # biases and norm scales are not weight-decayed by default
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


register = Optimizer.register
create = Optimizer.create_optimizer


def _common_kwargs(opt, index):
    kwargs = {"rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        kwargs["clip_gradient"] = opt.clip_gradient
    return kwargs


def _sparse_components(grad):
    """(vals, rows) device arrays of a RowSparseNDArray that was built
    from explicit components (ndarray/sparse.py), else None. Gate for
    the scatter-based lazy-update fast path: with true components the
    update touches only nnz rows instead of masking the full table."""
    ell = getattr(grad, "_ell", None)
    if ell is None or len(ell) != 2:
        # CSR arrays carry a 3-tuple (val, idx, counts); only the
        # row_sparse (vals, rows) pair feeds the scatter kernels
        return None
    vals, rows = ell
    return vals, rows


@register
class SGD(Optimizer):
    """SGD with momentum; fused on-device updates incl. fp16 master-weight
    path (mp_sgd_* ops)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and _needs_master_copy(weight.dtype):
            weight_master_copy = weight.astype(numpy.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        if _needs_master_copy(weight.dtype) and not self.multi_precision:
            logging.warning("Accumulating with %s in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option of the SGD "
                            "optimizer", weight.dtype)
        return self.create_state(index, weight)

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = _common_kwargs(self, index)
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum

        if not multi_precision:
            # lazy_update engages only for row_sparse grads (reference
            # optimizer.py:498: stype = weight.stype if lazy_update):
            # untouched rows skip decay/momentum (ops/optimizer_ops.py:_lazy)
            lazy = self.lazy_update and grad.stype == "row_sparse"
            if lazy and _sparse_components(grad) is not None:
                # scatter fast path: touch only the grad's rows (work
                # scales with nnz rows, reference sparse sgd kernels)
                from .ops import sparse_ops as sp
                vals, rows = _sparse_components(grad)
                rg = kwargs.get("rescale_grad", 1.0)
                cg = kwargs.get("clip_gradient", -1.0)
                if state is not None:
                    new_w, new_m = sp.rows_sgd_mom_update(
                        weight._data, state._data, rows, vals, lr,
                        self.momentum, wd=wd, rescale_grad=rg,
                        clip_gradient=cg)
                    weight._rebind(new_w)
                    state._rebind(new_m)
                else:
                    weight._rebind(sp.rows_sgd_update(
                        weight._data, rows, vals, lr, wd=wd,
                        rescale_grad=rg, clip_gradient=cg))
                return
            if state is not None:
                ndns.sgd_mom_update(weight, grad, state, out=weight,
                                    lr=lr, wd=wd, lazy_update=lazy, **kwargs)
            else:
                ndns.sgd_update(weight, grad, out=weight, lr=lr, wd=wd,
                                lazy_update=lazy, **kwargs)
        else:
            if state[0] is not None:
                ndns.mp_sgd_mom_update(weight, grad, state[0], state[1],
                                       out=weight, lr=lr, wd=wd, **kwargs)
            else:
                ndns.mp_sgd_update(weight, grad, state[1], out=weight,
                                   lr=lr, wd=wd, **kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_multi_precision = self.multi_precision and \
            _needs_master_copy(weight.dtype)
        self._update_impl(index, weight, grad, state,
                          multi_precision=use_multi_precision)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = _common_kwargs(self, index)
        if self.momentum > 0:
            kwargs["momentum"] = self.momentum
        if state is not None:
            ndns.signum_update(weight, grad, state, out=weight,
                               lr=lr, wd=wd, wd_lh=self.wd_lh, **kwargs)
        else:
            ndns.signsgd_update(weight, grad, out=weight, lr=lr, wd=wd,
                                **kwargs)


@register
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        d, v, z = state
        ndns.ftml_update(weight, grad, d, v, z, out=weight, lr=lr, wd=wd,
                         beta1=self.beta1, beta2=self.beta2,
                         epsilon=self.epsilon, t=t,
                         **_common_kwargs(self, index))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (python-side update)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                weight.copy())

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight + self.lamda * grad * grad *
                       (weight - previous_weight))
        if mom is not None:
            mom *= self.momentum
            mom += delta
            delta = mom
        weight.copyto(previous_weight)
        weight += delta


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.context, dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics."""

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        from .ndarray import random as ndrandom
        noise = ndrandom.normal(0, math.sqrt(lr), shape=weight.shape,
                                ctx=weight.context,
                                dtype=str(weight.dtype))
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class ccSGD(SGD):
    """Deprecated alias of SGD (reference keeps it for compat)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)


@register
class Adam(Optimizer):
    """Adam; bias correction folded into lr, fused adam_update op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        lazy = self.lazy_update and grad.stype == "row_sparse"
        if lazy and _sparse_components(grad) is not None:
            from .ops import sparse_ops as sp
            vals, rows = _sparse_components(grad)
            kw = _common_kwargs(self, index)
            new_w, new_m, new_v = sp.rows_adam_update(
                weight._data, mean._data, var._data, rows, vals, lr,
                self.beta1, self.beta2, self.epsilon, wd=wd,
                rescale_grad=kw.get("rescale_grad", 1.0),
                clip_gradient=kw.get("clip_gradient", -1.0))
            weight._rebind(new_w)
            mean._rebind(new_m)
            var._rebind(new_v)
            return
        ndns.adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                         beta1=self.beta1, beta2=self.beta2,
                         epsilon=self.epsilon, lazy_update=lazy,
                         **_common_kwargs(self, index))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        history = state
        history += grad * grad
        div = grad / ((history + self.float_stable_eps) ** 0.5)
        weight += (div + weight * wd) * -lr


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),  # n
                    zeros(weight.shape, weight.context),  # g
                    zeros(weight.shape, weight.context))  # delta
        return (zeros(weight.shape, weight.context),)  # n

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = {"gamma1": self.gamma1, "epsilon": self.epsilon,
                  **_common_kwargs(self, index)}
        if self.centered:
            kwargs["gamma2"] = self.gamma2
        if self.clip_weights:
            kwargs["clip_weights"] = self.clip_weights
        if not self.centered:
            (n,) = state
            ndns.rmsprop_update(weight, grad, n, out=weight, lr=lr, wd=wd,
                                **kwargs)
        else:
            n, g, delta = state
            ndns.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                    lr=lr, wd=wd, **kwargs)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),  # accumulated g
                zeros(weight.shape, weight.context))  # accumulated delta

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1. - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon).sqrt()
                         / (acc_g + self.epsilon).sqrt()) * grad
        acc_delta *= self.rho
        acc_delta += (1. - self.rho) * current_delta * current_delta
        weight -= current_delta + wd * weight


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(**kwargs)
        self.lamda1 = lamda1
        self.beta = beta
        self.lr = learning_rate

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),  # z
                zeros(weight.shape, weight.context))  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        lr = self._get_lr(index)
        z, n = state
        ndns.ftrl_update(weight, grad, z, n, out=weight, lr=lr, wd=wd,
                         lamda1=self.lamda1, beta=self.beta,
                         **_common_kwargs(self, index))


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        m_t, u_t = state
        m_t *= self.beta1
        m_t += (1. - self.beta1) * grad
        new_u = ndns.maximum(self.beta2 * u_t, grad.abs())
        u_t._rebind(new_u._data)
        weight -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context, dtype=str(weight.dtype)),
                zeros(weight.shape, weight.context, dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd._invoke("clip", grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * (pow(0.96, t * self.schedule_decay)))
        momentum_t_1 = self.beta1 * (1. - 0.5 * (pow(0.96, (t + 1) * self.schedule_decay)))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t *= self.beta1
        m_t += (1. - self.beta1) * grad
        v_t *= self.beta2
        v_t += (1. - self.beta2) * grad * grad
        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - pow(self.beta2, t))
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight -= lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise lr adaptation + warmup
    (role of reference LBSGD, optimizer.py:649; simplified to the
    warmup+LARS core)."""

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, multi_precision=multi_precision,
                         **kwargs)
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        strategy = self.warmup_strategy
        maxmult = float(self.batch_scale)
        if nup >= nwup:
            mult = maxmult
        elif nwup <= 1:
            mult = 1.0
        else:
            if strategy == "linear":
                mult = 1.0 + (maxmult - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (maxmult - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (maxmult - 1) * math.sqrt(float(nup) / nwup)
            else:
                mult = 1.0
        return mult

    def _get_lr(self, index):
        lr = super()._get_lr(index)
        self.lbmult = self._get_lbmult(self.num_update + self.init_updates)
        return lr * self.lbmult


@register
class Test(Optimizer):
    """Trivial test optimizer (reference keeps one for unit tests)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state._rebind(weight._data)


class Updater:
    """Applies an optimizer to (index, grad, weight) pairs, lazily creating
    state; picklable for kvstore set_optimizer (reference Updater +
    get_states/set_states)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index],
                                                         weight.context)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            synced_state = (self.sync_state_context(i, context) for i in state)
            return tuple(synced_state) if isinstance(state, tuple) \
                else list(synced_state)
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False, keys=None):
        """Pickle the optimizer state. `keys` restricts the dump to the
        given state indices — a ZeRO rank passes the indices it owns so
        a sharded save serializes only its 1/N of the optimizer state
        (missing keys are simply absent; set_states on a merged stream
        restores the union)."""
        states = self.states if keys is None else {
            k: self.states[k] for k in keys if k in self.states}
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def get_updater(optimizer):
    return Updater(optimizer)
