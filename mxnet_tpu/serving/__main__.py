"""Serving CLI + selftest load generator.

    python -m mxnet_tpu.serving model.mxa --selftest
    python -m mxnet_tpu.serving --selftest            # built-in tiny convnet

The selftest runs a closed-loop load generator (C client threads, each
issuing single-row requests back-to-back) through the DynamicBatcher and
times the same request stream through the raw single-request Predictor
loop, then prints ONE JSON line:

    {"metric": "serving_selftest", "batched_qps": ..., "sequential_qps":
     ..., "speedup": ..., "p50_ms": ..., "p99_ms": ..., "batch_hist": ...}

and exits non-zero when the batched speedup misses --min-speedup
(default 2.0 — the acceptance bar; micro-batching onto the export batch
should beat pad-to-full single-request serving by far more).

Uses stdlib + numpy only on the driver side; the built-in model export
path imports mxnet_tpu lazily (pass an existing .mxa to skip it).
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time

import numpy as np


def _export_tiny_convnet(batch=8):
    """Train-free tiny convnet -> .mxa in a temp dir (the ci smoke
    model; Xavier init is enough — serving cares about shapes, not
    weights)."""
    import os
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.export import export_model

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes = (batch, 3, 16, 16)
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", shapes)],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier())
    args, auxs = mod.get_params()
    path = os.path.join(tempfile.mkdtemp(prefix="mxa_selftest_"),
                        "model.mxa")
    export_model(path, sym, args, auxs, {"data": shapes})
    return path


def _sequential_qps(path, sample, requests):
    """Baseline: the pre-serving deployment story — one Predictor, one
    request per forward (padded to the export batch, as any fixed-shape
    artifact must)."""
    from ..predictor import Predictor
    pred = Predictor(path)
    pred.forward(sample)                       # warm the compile
    t0 = time.perf_counter()
    for _ in range(requests):
        pred.forward(sample)
    return requests / (time.perf_counter() - t0)


def _batched_qps(batcher, sample, requests, concurrency):
    """Closed-loop load gen: C threads, each issuing single-row
    requests back-to-back until the shared budget is spent."""
    remaining = [requests]
    lock = threading.Lock()
    errors = []
    start = threading.Barrier(concurrency + 1)

    def client():
        start.wait()
        while True:
            with lock:
                if remaining[0] <= 0:
                    return
                remaining[0] -= 1
            try:
                batcher.infer(sample, timeout_ms=30000)
            except Exception as e:               # pragma: no cover
                with lock:
                    errors.append(repr(e))
                return

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errors:
        raise RuntimeError(f"load generator hit errors: {errors[:3]}")
    return requests / dt


def selftest(path=None, requests=256, concurrency=8, max_wait_us=2000,
             queue_depth=256, min_speedup=2.0):
    """Run the sequential-vs-batched comparison; returns the result
    dict (also usable programmatically — tools/serving_bench.py)."""
    from . import DynamicBatcher, ServingEngine
    if path is None:
        path = _export_tiny_convnet()
    eng = ServingEngine(path)                    # warms every bucket
    shape = tuple(eng._pred._input_shapes[eng.input_names[0]])
    sample = np.random.RandomState(0) \
        .uniform(0, 1, (1,) + shape[1:]).astype(np.float32)

    seq_qps = _sequential_qps(path, sample, min(requests, 64))
    with DynamicBatcher(eng, max_wait_us=max_wait_us,
                        queue_depth=queue_depth) as bat:
        bat_qps = _batched_qps(bat, sample, requests, concurrency)
        snap = bat.metrics.snapshot()
        # closed-loop observability check: scrape our own /metrics while
        # the batcher is still live and confirm the serving counters made
        # it through the registry -> Prometheus path
        scrape = _self_scrape(bat.metrics.name)
    speedup = bat_qps / seq_qps if seq_qps else float("inf")
    return {
        "metric": "serving_selftest",
        "model": path,
        "requests": requests,
        "concurrency": concurrency,
        "max_batch": eng.max_batch,
        "buckets": eng.buckets,
        "max_wait_us": max_wait_us,
        "batched_qps": round(bat_qps, 2),
        "sequential_qps": round(seq_qps, 2),
        "speedup": round(speedup, 2),
        "p50_ms": snap["p50_ms"],
        "p99_ms": snap["p99_ms"],
        "avg_batch_rows": snap["avg_batch_rows"],
        "batch_hist": snap["batch_hist"],
        "shed": snap["shed"],
        "timeouts": snap["timeouts"],
        "telemetry_port": scrape["port"],
        "telemetry_scrape_ok": scrape["ok"],
        "ok": speedup >= min_speedup and scrape["ok"],
    }


def _self_scrape(metrics_name):
    """Start (or reuse) the telemetry exporter, GET /metrics, and verify
    this batcher's completed/qps/p50/p99/shed counters are present in
    Prometheus text form. Returns {"port", "ok", "missing"}."""
    import urllib.request
    from ..telemetry import start_server
    mname = metrics_name.replace("#", "_")
    expect = [f"mxnet_{mname}_{k}" for k in
              ("completed", "qps", "p50_ms", "p99_ms", "shed",
               "queue_depth")] + \
             [f"mxnet_{mname}_request_latency_seconds_bucket"]
    try:
        srv = start_server()
        body = urllib.request.urlopen(srv.url + "/metrics",
                                      timeout=10).read().decode()
        missing = [e for e in expect if e not in body]
        return {"port": srv.port, "ok": not missing, "missing": missing}
    except Exception as e:                       # pragma: no cover
        return {"port": None, "ok": False, "missing": [repr(e)]}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving",
        description="serve / selftest an exported .mxa artifact")
    ap.add_argument("model", nargs="?", default=None,
                    help=".mxa artifact (selftest exports a tiny "
                         "convnet when omitted)")
    ap.add_argument("--selftest", action="store_true",
                    help="closed-loop load test; print one perf JSON "
                         "line")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-wait-us", type=int, default=2000)
    ap.add_argument("--queue-depth", type=int, default=256)
    ap.add_argument("--min-speedup", type=float, default=2.0,
                    help="exit non-zero when batched/sequential falls "
                         "below this (default 2.0)")
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.error("only --selftest mode is implemented; a network "
                 "frontend belongs to the host app (see docs/SERVING.md)")
    res = selftest(args.model, requests=args.requests,
                   concurrency=args.concurrency,
                   max_wait_us=args.max_wait_us,
                   queue_depth=args.queue_depth,
                   min_speedup=args.min_speedup)
    print(json.dumps(res), flush=True)
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
