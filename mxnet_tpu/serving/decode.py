"""Decode-mode serving: per-session KV-cache pool + continuous batching.

One-shot ``predict`` (serving/engine.py) re-runs the whole context per
request; autoregressive generation needs the opposite shape — a prompt is
*prefilled* once into a per-session KV cache, then a tiny fixed-shape
*decode step* (q_len = 1) advances every live session one token per
dispatch. Two invariants drive the design:

* **One executable, any occupancy.** The decode step is compiled exactly
  once, for the full slot count. Sessions join at prefill-completion and
  leave at EOS/max_len by flipping a per-slot ``active`` mask — shapes
  never change, so occupancy changes never recompile (the hloaudit
  ``fit_decode`` recompile-storm check binds on this). All per-slot math
  is row-independent (masked writes, per-row attention, per-row argmax),
  so a session's token stream is bit-identical whether it runs alone or
  packed with seven neighbours — the selftest asserts this.
* **Caches are pool memory, sized up front.** The KV pool
  (layers x {K,V} x num_slots x kv_heads x max_len x head_dim) is
  allocated once and preflighted against the devstats HBM budget
  (telemetry/devstats.py, PR 14): a pool that cannot fit fails at
  construction with a sized ``HBMPreflightError`` instead of OOMing
  mid-request, and a submit that cannot get a block (slots + wait queue
  exhausted) raises :class:`SessionPoolFull` — the frontend maps both to
  HTTP 507. Cache buffers are donated between steps
  (``donate_argnums``), so steady-state decode holds ONE pool, not two.

Prefill reuses the serving tier's power-of-two bucket ladder (one
compiled prefill plan per prompt bucket; slot index and true length are
traced scalars, so neither re-keys the plan) and writes straight into
the session's pool block. Attention is ``ops.attention``: causal flash
attention for prefill, the decode-mode (q_len = 1) kernel for steps.
Weights with a ``{name}__scale`` companion (weight-only int8/fp8 from
contrib/quantization.py) are consumed through ``ops.quantization.
quantized_matmul`` — dequant fused into the matmul, halving the weight
bytes each decode step streams.

``python -m mxnet_tpu.serving.decode --selftest`` generates with 8
concurrent staggered sessions on a few-layer GQA transformer and
asserts the streams are bit-identical to sequential per-session decode
at strictly higher aggregate tokens/s.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zipfile
from collections import deque

import numpy as np

from .. import config as _config
from ..base import MXNetError
from ..telemetry import devstats
from .batcher import Future

__all__ = ["DecodeModel", "DecodeEngine", "Session", "SessionPool",
           "SessionPoolFull", "prompt_buckets"]

# Reviewed single-writer surfaces (locklint): the engine's loop thread is
# the ONLY writer of the device state (_k/_v, per-slot token/length
# vectors, the lazily-built plans) and of the perf counters after
# __init__'s warmup (which happens-before the thread starts). Caller
# threads only read them — stats() tolerates stale-by-one counter reads.
# Pool/queue state, by contrast, IS lock-guarded: every SessionPool call
# sits under DecodeEngine._cv.
__analysis_thread_safe__ = {
    "DecodeEngine._k", "DecodeEngine._v", "DecodeEngine._tokens",
    "DecodeEngine._lengths", "DecodeEngine._active",
    "DecodeEngine._step_plan", "DecodeEngine.step_compiles",
    "DecodeEngine.plan_compiles", "DecodeEngine.plan_resident_bytes",
    "DecodeEngine.step_executions", "DecodeEngine.prefill_executions",
    "DecodeEngine.tokens_generated", "DecodeEngine.sessions_done",
}


def _int_knob(name):
    v = _config.get(name)
    return int(v) if v is not None else None


def prompt_buckets(max_len, lo=8):
    """Power-of-two prompt-bucket ladder: lo, 2*lo, ... capped at (and
    always including) max_len — the serving-tier ladder, applied to the
    sequence axis instead of the batch axis."""
    if max_len < 1:
        raise MXNetError("prompt_buckets: max_len must be >= 1")
    buckets, b = [], lo
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_len))
    return buckets


# -- model ------------------------------------------------------------------

class DecodeModel:
    """Functional GQA transformer LM over a flat {name: array} param dict.

    Pre-norm blocks (RMSNorm), learned positions, gelu MLP. Every linear
    goes through :meth:`_mm`, which transparently uses the fused
    quantized matmul when the param carries a ``{name}__scale``
    companion — the float and quantized artifacts share one code path.

    Two entry points, both pure (jit/AOT-friendly):

    * :meth:`prefill` — full-sequence causal pass over one padded prompt
      bucket; writes K/V for positions [0, bucket) into one slot of the
      cache and returns the first generated token.
    * :meth:`step` — one decode step for ALL slots at once (q_len = 1
      against the cache); inactive slots are masked inert so the same
      executable serves any occupancy.
    """

    def __init__(self, vocab, layers=2, d_model=64, heads=4, kv_heads=None,
                 d_ff=None, max_len=None, attention=None, matmul=None):
        kv_heads = int(kv_heads) if kv_heads else int(heads)
        if heads % kv_heads:
            raise MXNetError("DecodeModel: heads %% kv_heads != 0")
        if d_model % heads:
            raise MXNetError("DecodeModel: d_model %% heads != 0")
        self.vocab = int(vocab)
        self.layers = int(layers)
        self.d_model = int(d_model)
        self.heads = int(heads)
        self.kv_heads = kv_heads
        self.d_ff = int(d_ff) if d_ff else 4 * self.d_model
        self.max_len = int(max_len) if max_len \
            else _int_knob("MXNET_DECODE_MAX_LEN")
        self.head_dim = self.d_model // self.heads
        self.attention = attention       # force arg for ops.attention
        self.matmul = matmul             # force arg for quantized_matmul

    def config(self):
        """Manifest-serializable architecture block."""
        return {"vocab": self.vocab, "layers": self.layers,
                "d_model": self.d_model, "heads": self.heads,
                "kv_heads": self.kv_heads, "d_ff": self.d_ff,
                "max_len": self.max_len}

    @classmethod
    def from_config(cls, cfg, **kw):
        return cls(vocab=cfg["vocab"], layers=cfg["layers"],
                   d_model=cfg["d_model"], heads=cfg["heads"],
                   kv_heads=cfg["kv_heads"], d_ff=cfg["d_ff"],
                   max_len=cfg["max_len"], **kw)

    def param_names(self):
        names = ["embed", "pos"]
        for i in range(self.layers):
            names += [f"l{i}.ln1", f"l{i}.wq", f"l{i}.wk", f"l{i}.wv",
                      f"l{i}.wo", f"l{i}.ln2", f"l{i}.w1", f"l{i}.w2"]
        names += ["lnf", "head"]
        return names

    def init_params(self, seed=0):
        rng = np.random.RandomState(seed)
        d, h, hkv, hd = self.d_model, self.heads, self.kv_heads, \
            self.head_dim

        def w(*shape):
            return (rng.standard_normal(shape)
                    / np.sqrt(shape[0])).astype(np.float32)

        p = {"embed": w(self.vocab, d), "pos": 0.1 * w(self.max_len, d),
             "lnf": np.ones(d, np.float32), "head": w(d, self.vocab)}
        for i in range(self.layers):
            p[f"l{i}.ln1"] = np.ones(d, np.float32)
            p[f"l{i}.wq"] = w(d, h * hd)
            p[f"l{i}.wk"] = w(d, hkv * hd)
            p[f"l{i}.wv"] = w(d, hkv * hd)
            p[f"l{i}.wo"] = w(h * hd, d)
            p[f"l{i}.ln2"] = np.ones(d, np.float32)
            p[f"l{i}.w1"] = w(d, self.d_ff)
            p[f"l{i}.w2"] = w(self.d_ff, d)
        return p

    def session_cache_bytes(self, dtype_size=4):
        """Per-session KV block: layers x {K,V} x kv_heads x max_len x
        head_dim — the unit the pool admission math is denominated in."""
        return (self.layers * 2 * self.kv_heads * self.max_len
                * self.head_dim * dtype_size)

    def init_cache(self, num_slots):
        """(kc, vc): per-layer tuples of (num_slots, kv_heads, max_len,
        head_dim) f32 — tuples (not one stacked array) so layer writes
        never materialize a whole-pool copy and donation aliases every
        leaf independently."""
        import jax.numpy as jnp
        shape = (num_slots, self.kv_heads, self.max_len, self.head_dim)
        kc = tuple(jnp.zeros(shape, jnp.float32)
                   for _ in range(self.layers))
        vc = tuple(jnp.zeros(shape, jnp.float32)
                   for _ in range(self.layers))
        return kc, vc

    # -- building blocks ----------------------------------------------------

    def _mm(self, params, name, x):
        import jax
        import jax.numpy as jnp
        w = params[name]
        s = params.get(name + "__scale")
        if s is not None:
            from ..ops.quantization import quantized_matmul
            return quantized_matmul(x, w, s, force=self.matmul)
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)

    @staticmethod
    def _norm(x, g):
        import jax
        import jax.numpy as jnp
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g

    # -- prefill ------------------------------------------------------------

    def prefill(self, params, kc, vc, tokens, true_len, slot):
        """One prompt into one pool slot. tokens (1, S_b) int32 padded to
        its bucket; ``true_len`` / ``slot`` are TRACED int32 scalars (no
        per-slot or per-length recompile). Positions >= true_len are pad:
        causal masking keeps them out of every valid row's softmax, and
        the decode step's length mask keeps their cached K/V dead.
        Returns (kc, vc, first_token, last_logits)."""
        import jax
        import jax.numpy as jnp
        from ..ops.attention import flash_attention

        s_b = tokens.shape[1]
        h, hkv, hd = self.heads, self.kv_heads, self.head_dim
        x = params["embed"][tokens] + params["pos"][None, :s_b]
        for i in range(self.layers):
            pfx = f"l{i}."
            hn = self._norm(x, params[pfx + "ln1"])
            q = self._mm(params, pfx + "wq", hn) \
                .reshape(1, s_b, h, hd).transpose(0, 2, 1, 3)
            k = self._mm(params, pfx + "wk", hn) \
                .reshape(1, s_b, hkv, hd).transpose(0, 2, 1, 3)
            v = self._mm(params, pfx + "wv", hn) \
                .reshape(1, s_b, hkv, hd).transpose(0, 2, 1, 3)
            a = flash_attention(q, k, v, causal=True, force=self.attention)
            x = x + self._mm(params, pfx + "wo",
                             a.transpose(0, 2, 1, 3).reshape(1, s_b,
                                                             h * hd))
            hn2 = self._norm(x, params[pfx + "ln2"])
            x = x + self._mm(params, pfx + "w2",
                             jax.nn.gelu(self._mm(params, pfx + "w1",
                                                  hn2)))
            kc = kc[:i] + (jax.lax.dynamic_update_slice(
                kc[i], k, (slot, 0, 0, 0)),) + kc[i + 1:]
            vc = vc[:i] + (jax.lax.dynamic_update_slice(
                vc[i], v, (slot, 0, 0, 0)),) + vc[i + 1:]
        # logits for the LAST VALID position only — slice before the head
        # matmul so the vocab projection runs on one row, not the bucket
        xlast = jax.lax.dynamic_slice(
            x[0], (true_len - 1, 0), (1, self.d_model))
        logits = self._mm(params, "head", self._norm(xlast, params["lnf"]))
        tok0 = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)
        return kc, vc, tok0, logits[0]

    # -- decode step --------------------------------------------------------

    def step(self, params, kc, vc, tokens, lengths, active):
        """Advance every slot one token. tokens/lengths (N,) int32,
        active (N,) bool. Writes each row's K/V at position lengths[n],
        attends over lengths[n]+1 cached positions, emits the greedy
        next token. Inactive rows are inert: their token/length pass
        through unchanged and their (garbage) cache writes land in their
        own retired block, which the next prefill overwrites before any
        read. Every op is row-independent, so a slot's stream does not
        depend on who else is resident — the bit-identity the selftest
        checks. Returns (kc, vc, next_tokens, new_lengths, logits)."""
        import jax
        import jax.numpy as jnp
        from ..ops.attention import decode_attention

        n = tokens.shape[0]
        h, hkv, hd = self.heads, self.kv_heads, self.head_dim
        pos = jnp.clip(lengths, 0, self.max_len - 1)
        att_len = jnp.minimum(pos + 1, self.max_len)
        x = params["embed"][tokens] + params["pos"][pos]

        def write_row(row_cache, new_row, p):
            # (hkv, S, hd) <- (hkv, hd) at position p
            return jax.lax.dynamic_update_slice(
                row_cache, new_row[:, None, :], (0, p, 0))

        for i in range(self.layers):
            pfx = f"l{i}."
            hn = self._norm(x, params[pfx + "ln1"])
            q = self._mm(params, pfx + "wq", hn).reshape(n, h, hd)
            k = self._mm(params, pfx + "wk", hn).reshape(n, hkv, hd)
            v = self._mm(params, pfx + "wv", hn).reshape(n, hkv, hd)
            kc = kc[:i] + (jax.vmap(write_row)(kc[i], k, pos),) + kc[i + 1:]
            vc = vc[:i] + (jax.vmap(write_row)(vc[i], v, pos),) + vc[i + 1:]
            a = decode_attention(q, kc[i], vc[i], att_len,
                                 force=self.attention)
            x = x + self._mm(params, pfx + "wo", a.reshape(n, h * hd))
            hn2 = self._norm(x, params[pfx + "ln2"])
            x = x + self._mm(params, pfx + "w2",
                             jax.nn.gelu(self._mm(params, pfx + "w1",
                                                  hn2)))
        logits = self._mm(params, "head", self._norm(x, params["lnf"]))
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tokens)
        new_len = jnp.where(active, pos + 1, lengths)
        return kc, vc, nxt, new_len, logits


# -- sessions ---------------------------------------------------------------

class SessionPoolFull(devstats.HBMPreflightError):
    """No free KV block and the wait queue is at capacity. Subclasses the
    HBM preflight error so frontend.status_for maps it to HTTP 507 —
    the block the session needs IS pool memory."""


class Session:
    """One generation request: prompt in, greedy token stream out."""

    __slots__ = ("sid", "prompt", "max_new", "eos_id", "tokens", "slot",
                 "future", "t_submit", "t_done")

    def __init__(self, sid, prompt, max_new, eos_id, deadline):
        self.sid = sid
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tokens = []
        self.slot = None
        self.future = Future(deadline)
        self.t_submit = time.monotonic()
        self.t_done = None

    def result(self, timeout=None):
        return self.future.result(timeout)


class SessionPool:
    """Slot bookkeeping for the KV pool: free list, wait queue, admission.

    The caller (DecodeEngine) holds its lock around every method. A
    session is admitted iff a block or a queue seat exists; it binds to a
    concrete slot at prefill time and frees it at retirement — EOS,
    token budget, or max_len, whichever first."""

    def __init__(self, num_slots, max_len, session_bytes, queue_depth=None):
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.session_bytes = int(session_bytes)
        self.queue_depth = (2 * self.num_slots if queue_depth is None
                            else int(queue_depth))
        self._free = list(range(self.num_slots - 1, -1, -1))
        self._by_slot = {}
        self._pending = deque()
        self.admitted = 0
        self.rejected = 0
        self.retired = 0

    def occupancy(self):
        return self.num_slots - len(self._free)

    def depth(self):
        return len(self._pending)

    def admit(self, sess):
        if len(self._pending) >= self.queue_depth and not self._free:
            self.rejected += 1
            raise SessionPoolFull(
                f"decode pool full: {self.num_slots} KV blocks "
                f"({self.session_bytes} B each) busy and wait queue at "
                f"{self.queue_depth}")
        self._pending.append(sess)
        self.admitted += 1

    def assign(self):
        """Bind queued sessions to free slots; returns the newly bound."""
        out = []
        while self._pending and self._free:
            sess = self._pending.popleft()
            sess.slot = self._free.pop()
            self._by_slot[sess.slot] = sess
            out.append(sess)
        return out

    def retire(self, slot):
        sess = self._by_slot.pop(slot)
        self._free.append(slot)
        self.retired += 1
        return sess

    def active_sessions(self):
        return dict(self._by_slot)


# -- engine -----------------------------------------------------------------

def _load_decode_artifact(path):
    """Read a decode .mxa (contrib.export.export_decode_model): manifest
    ``decode`` block -> DecodeModel config, params.bin -> param dict
    (fp8 tensors ride as uint8 bytes; the quant block says which to
    view back). Returns (config, params, model_name, quant)."""
    from ..predictor import _read_container_dense
    with zipfile.ZipFile(path) as zf:
        manifest = json.loads(zf.read("MANIFEST.json"))
        raw = _read_container_dense(zf.read("params.bin"))
    dec = manifest.get("decode")
    if dec is None:
        raise MXNetError(f"{path}: no 'decode' block in manifest — not a "
                         "decode artifact (use ServingEngine for predict "
                         "models)")
    params = {n.split(":", 1)[1]: v for n, v in raw.items()}
    quant = manifest.get("quant")
    if quant and quant.get("dtype") == "fp8":
        from ..ops.quantization import _fp8_dtype
        f8 = _fp8_dtype()
        if f8 is None:
            raise MXNetError(f"{path}: fp8 artifact but this jax has no "
                             "float8_e4m3fn")
        for n in quant.get("params", []):
            params[n] = params[n].view(f8)
    return dec, params, manifest.get("model_name"), quant


class DecodeEngine:
    """Continuous-batching decode runtime over one :class:`DecodeModel`.

    A background loop owns the device state (params, KV pool, per-slot
    token/length/active vectors): it prefify-admits queued sessions into
    free slots, then dispatches THE decode-step plan while anyone is
    active. Callers interact through :meth:`submit` (non-blocking,
    returns a :class:`Session` whose future resolves to the token list)
    or :meth:`generate` (blocking convenience).

    Accepts a (model, params) pair or a decode ``.mxa`` path."""

    def __init__(self, model, params=None, num_slots=None, max_len=None,
                 queue_depth=None, attention=None, matmul=None, name=None,
                 warmup=True):
        import jax
        if isinstance(model, (str, os.PathLike)):
            cfg, params, mname, _quant = _load_decode_artifact(str(model))
            if max_len is not None:
                cfg = dict(cfg, max_len=int(max_len))
            model = DecodeModel.from_config(cfg, attention=attention,
                                            matmul=matmul)
            name = name or mname
        self.model = model
        self.name = str(name) if name else "decode"
        self.num_slots = int(num_slots) if num_slots \
            else _int_knob("MXNET_DECODE_SLOTS")
        self.max_len = model.max_len
        self.max_prompt = self.max_len - 1   # >= 1 token must be generable
        if params is None:
            raise MXNetError("DecodeEngine: params required with a model "
                             "instance")

        self._names = sorted(params)
        self._flat = tuple(jax.device_put(np.asarray(params[n]))
                           for n in self._names)
        self.params_bytes = sum(int(v.nbytes) for v in self._flat)
        self.session_bytes = model.session_cache_bytes()
        self.cache_bytes = self.num_slots * self.session_bytes
        # pool admission: the whole KV pool + weights must fit the HBM
        # budget BEFORE we allocate — a sized 507 beats an OOM later
        if devstats.enabled():
            devstats.preflight("%s.pool" % self.name,
                               self.cache_bytes + self.params_bytes,
                               what="decode KV pool + weights")
        self._k, self._v = model.init_cache(self.num_slots)
        self._tokens = np.zeros(self.num_slots, np.int32)
        self._lengths = np.zeros(self.num_slots, np.int32)
        self._active = np.zeros(self.num_slots, np.bool_)

        self.pool = SessionPool(self.num_slots, self.max_len,
                                self.session_bytes, queue_depth)
        self._buckets = prompt_buckets(self.max_len)
        self._step_plan = None
        self._prefill_plans = {}
        self.plan_compiles = 0
        self.step_compiles = 0      # MUST stay 1: occupancy never re-keys
        self.plan_resident_bytes = 0
        self.step_executions = 0
        self.prefill_executions = 0
        self.tokens_generated = 0
        self.sessions_done = 0
        self._t0 = time.monotonic()
        self._seq = 0
        self._cv = threading.Condition()
        self._closed = False

        # one series per engine name under shared metric names (the
        # registry keys on (name, series)), so concurrent engines — tests,
        # router-managed models — never fight over label sets
        from ..telemetry import counter, gauge, histogram
        labels = {"engine": self.name}
        self._m_tokens = counter(
            "mxnet_decode_tokens_total",
            help="greedy tokens emitted across all sessions",
            labels=labels, series=self.name)
        self._m_occ = gauge(
            "mxnet_decode_kv_occupancy",
            help="KV-pool slots holding a live session", labels=labels,
            series=self.name)
        self._m_cache = gauge(
            "mxnet_decode_kv_cache_bytes",
            help="bytes preallocated for the KV pool", labels=labels,
            series=self.name)
        self._m_step = histogram(
            "mxnet_decode_step_seconds",
            help="wall time of one decode-step dispatch", labels=labels,
            series=self.name)
        self._m_cache.set(self.cache_bytes)
        self._m_occ.set(0)

        if warmup:
            self._ensure_step_plan()
            self._prefill_plan(self._buckets[0])
        self._thread = threading.Thread(target=self._loop,
                                        name=f"{self.name}-loop",
                                        daemon=True)
        self._thread.start()

    # -- plans --------------------------------------------------------------

    def _record_plan(self, label, compiled):
        """devstats accounting, mirroring ServingEngine._plan: record the
        program, preflight its peak against what's already resident."""
        self.plan_compiles += 1
        if not devstats.enabled():
            return
        pname = f"{self.name}.{label}"
        stats = devstats.record_program(pname, compiled=compiled,
                                        kind="serving")
        resident = int(stats["generated_code_bytes"]
                       or (stats["argument_bytes"]
                           + stats["output_bytes"]))
        devstats.preflight(pname, int(stats["peak_bytes"]),
                           resident_bytes=self.plan_resident_bytes,
                           what="decode plan")
        devstats.note_compile(pname)
        self.plan_resident_bytes += resident

    def _specs(self, arrays):
        import jax
        return tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in arrays)

    def _ensure_step_plan(self):
        if self._step_plan is not None:
            return self._step_plan
        import jax
        import jax.numpy as jnp
        model, names = self.model, self._names

        def step_fn(flat, kc, vc, tokens, lengths, active):
            kc, vc, nxt, ln, _ = model.step(dict(zip(names, flat)),
                                            kc, vc, tokens, lengths,
                                            active)
            return kc, vc, nxt, ln

        n = self.num_slots
        specs = (self._specs(self._flat), self._specs(self._k),
                 self._specs(self._v),
                 jax.ShapeDtypeStruct((n,), jnp.int32),
                 jax.ShapeDtypeStruct((n,), jnp.int32),
                 jax.ShapeDtypeStruct((n,), jnp.bool_))
        # donate the caches: steady-state decode holds ONE pool, and the
        # executable aliases inputs to outputs (hloaudit checks this)
        self._step_plan = jax.jit(
            step_fn, donate_argnums=(1, 2)).lower(*specs).compile()
        self.step_compiles += 1
        self._record_plan("step", self._step_plan)
        return self._step_plan

    def _prefill_plan(self, bucket):
        plan = self._prefill_plans.get(bucket)
        if plan is not None:
            return plan
        import jax
        import jax.numpy as jnp
        model, names = self.model, self._names

        def prefill_fn(flat, kc, vc, tokens, true_len, slot):
            return model.prefill(dict(zip(names, flat)), kc, vc,
                                 tokens, true_len, slot)

        specs = (self._specs(self._flat), self._specs(self._k),
                 self._specs(self._v),
                 jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32),
                 jax.ShapeDtypeStruct((), jnp.int32))
        plan = jax.jit(
            prefill_fn, donate_argnums=(1, 2)).lower(*specs).compile()
        self._record_plan("prefill.b%d" % bucket, plan)
        self._prefill_plans[bucket] = plan
        return plan

    def _bucket_for(self, n):
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # -- public API ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               timeout_ms=None):
        """Queue one generation; returns a :class:`Session` immediately.
        Raises ValueError on a malformed/oversized prompt (HTTP 400) and
        :class:`SessionPoolFull` when no KV block or queue seat exists
        (HTTP 507)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("decode: empty prompt")
        if any(t < 0 or t >= self.model.vocab for t in prompt):
            raise ValueError("decode: prompt token outside vocab "
                             f"[0, {self.model.vocab})")
        if len(prompt) > self.max_prompt:
            raise ValueError(
                f"decode: prompt length {len(prompt)} exceeds "
                f"max_len-1 = {self.max_prompt} (KV block holds "
                f"{self.max_len} positions incl. generated tokens)")
        max_new = int(max_new_tokens) if max_new_tokens \
            else _int_knob("MXNET_DECODE_MAX_NEW")
        if max_new < 1:
            raise ValueError("decode: max_new_tokens must be >= 1")
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        with self._cv:
            if self._closed:
                raise RuntimeError("DecodeEngine is closed")
            self._seq += 1
            sess = Session(self._seq, prompt, max_new, eos_id, deadline)
            self.pool.admit(sess)
            self._cv.notify_all()
        return sess

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 timeout_ms=None):
        """Blocking submit: returns the generated token list."""
        return self.submit(prompt, max_new_tokens, eos_id,
                           timeout_ms).result()

    def stats(self):
        dt = max(time.monotonic() - self._t0, 1e-9)
        with self._cv:
            occ, depth = self.pool.occupancy(), self.pool.depth()
        return {"engine": self.name, "num_slots": self.num_slots,
                "max_len": self.max_len, "occupancy": occ,
                "queue_depth": depth,
                "sessions_admitted": self.pool.admitted,
                "sessions_rejected": self.pool.rejected,
                "sessions_done": self.sessions_done,
                "tokens_generated": self.tokens_generated,
                "tokens_per_s": self.tokens_generated / dt,
                "step_executions": self.step_executions,
                "prefill_executions": self.prefill_executions,
                "plan_compiles": self.plan_compiles,
                "plan_resident_bytes": self.plan_resident_bytes,
                "session_cache_bytes": self.session_bytes,
                "kv_cache_bytes": self.cache_bytes,
                "params_bytes": self.params_bytes}

    def resident_bytes(self):
        return self.cache_bytes + self.params_bytes \
            + self.plan_resident_bytes

    def close(self, drain=True):
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self.pool._pending:
                    sess = self.pool._pending.popleft()
                    sess.future._set_exception(
                        RuntimeError("DecodeEngine closed"))
            self._cv.notify_all()
        self._thread.join(timeout=60.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- decode loop --------------------------------------------------------

    def _loop(self):
        while True:
            with self._cv:
                while (not self.pool._pending and not self.pool._by_slot
                       and not self._closed):
                    self._cv.wait()
                if (self._closed and not self.pool._pending
                        and not self.pool._by_slot):
                    return
                newly = self.pool.assign()
                self._m_occ.set(self.pool.occupancy())
            for sess in newly:
                try:
                    self._do_prefill(sess)
                except Exception as e:           # noqa: BLE001
                    sess.future._set_exception(e)
                    with self._cv:
                        self.pool.retire(sess.slot)
                        self._active[sess.slot] = False
                        self._m_occ.set(self.pool.occupancy())
            if self._active.any():
                self._do_step()

    def _do_prefill(self, sess):
        bucket = self._bucket_for(len(sess.prompt))
        plan = self._prefill_plan(bucket)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :len(sess.prompt)] = sess.prompt
        self._k, self._v, tok0, _ = plan(
            self._flat, self._k, self._v, toks,
            np.int32(len(sess.prompt)), np.int32(sess.slot))
        self.prefill_executions += 1
        tok0 = int(tok0)
        slot = sess.slot
        self._tokens[slot] = tok0
        self._lengths[slot] = len(sess.prompt)
        self._active[slot] = True
        self._emit(sess, tok0)

    def _do_step(self):
        t0 = time.perf_counter()
        plan = self._ensure_step_plan()
        self._k, self._v, nxt, new_len = plan(
            self._flat, self._k, self._v, self._tokens, self._lengths,
            self._active)
        self.step_executions += 1
        self._tokens = np.array(nxt, np.int32)
        self._lengths = np.array(new_len, np.int32)
        self._m_step.observe(time.perf_counter() - t0)
        with self._cv:
            live = list(self.pool._by_slot.items())
        for slot, sess in live:
            if self._active[slot]:
                self._emit(sess, int(self._tokens[slot]))

    def _emit(self, sess, tok):
        """Record one generated token; retire the session when its stream
        is complete (EOS, token budget, or cache exhausted)."""
        sess.tokens.append(tok)
        self.tokens_generated += 1
        self._m_tokens.inc()
        done = (len(sess.tokens) >= sess.max_new
                or (sess.eos_id is not None and tok == sess.eos_id)
                # the next step would write this token's K/V at position
                # lengths — no position left means the stream ends here
                or int(self._lengths[sess.slot]) >= self.max_len)
        if done:
            with self._cv:
                self.pool.retire(sess.slot)
                self._active[sess.slot] = False
                self._m_occ.set(self.pool.occupancy())
            sess.t_done = time.monotonic()
            self.sessions_done += 1
            sess.future._set(list(sess.tokens))


# -- selftest ---------------------------------------------------------------

def _selftest(sessions=8, new_tokens=40, stagger_ms=1.0):
    """8 concurrent staggered sessions vs the same prompts decoded
    sequentially (one live session at a time) through the SAME engine:
    token streams must be bit-identical and batched tokens/s strictly
    (and for the PR gate, >= 3x) higher."""
    model = DecodeModel(vocab=64, layers=2, d_model=64, heads=4,
                        kv_heads=2, d_ff=128, max_len=64)
    params = model.init_params(seed=7)
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, model.vocab, size=rng.randint(3, 8)).tolist()
               for _ in range(sessions)]
    eng = DecodeEngine(model, params, num_slots=sessions, name="selftest")
    try:
        # warm every bucket the prompts will touch + the step plan
        eng.generate(prompts[0], max_new_tokens=2)

        t0 = time.perf_counter()
        seq = [eng.generate(p, max_new_tokens=new_tokens)
               for p in prompts]
        t_seq = time.perf_counter() - t0

        t0 = time.perf_counter()
        pending = []
        for p in prompts:
            pending.append(eng.submit(p, max_new_tokens=new_tokens))
            time.sleep(stagger_ms / 1000.0)   # staggered joins
        conc = [s.result(timeout=120.0) for s in pending]
        t_conc = time.perf_counter() - t0

        n_tok = sessions * new_tokens
        seq_tps = n_tok / t_seq
        conc_tps = n_tok / t_conc
        identical = conc == seq
        speedup = conc_tps / seq_tps
        stats = eng.stats()
    finally:
        eng.close()
    return {"metric": "decode_selftest", "sessions": sessions,
            "new_tokens": new_tokens, "identical": bool(identical),
            "seq_tokens_per_s": round(seq_tps, 1),
            "batched_tokens_per_s": round(conc_tps, 1),
            "speedup": round(speedup, 2),
            "step_executions": stats["step_executions"],
            "plan_compiles": stats["plan_compiles"],
            "kv_cache_bytes": stats["kv_cache_bytes"],
            "ok": bool(identical and speedup > 1.0)}


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving.decode",
        description="continuous-batching decode engine selftest")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--sessions", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=40)
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.error("nothing to do (pass --selftest)")
    out = _selftest(sessions=args.sessions, new_tokens=args.new_tokens)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
