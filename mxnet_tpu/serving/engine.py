"""ServingEngine — bucketed compiled-plan cache over an exported .mxa.

The inference artifact binds ONE batch shape at export time (the
MXPredCreate contract, contrib/export.py). Under serving load the
request batch is whatever the micro-batcher coalesced this tick — and
XLA recompiles per shape, so naively executing each distinct batch size
would either thrash the compile cache or waste the MXU padding
everything to the export batch on the host.

The engine takes the middle path the serving literature converged on
(Clipper-style adaptive batching over fixed-shape accelerators):

  - a ladder of power-of-two batch *buckets* up to the export batch
    (read from MANIFEST.json's `serving` block when present, derived
    otherwise);
  - one compiled plan per bucket, built lazily and cached: an
    ahead-of-time compiled (``jit(fn).lower(specs).compile()``) program
    that zero-pads the bucket batch up to the export batch ON DEVICE,
    calls the exported StableHLO module, and slices outputs back to the
    bucket — pad and slice are fused into the XLA program, so the host
    only ever pads request->bucket (cheap numpy). The AOT ``Compiled``
    object is the plan: dispatch never consults the jit cache (no
    shape/commitment re-keying) and its cost/memory analytics feed
    telemetry.devstats — per-plan FLOPs/bytes gauges on /metrics, a
    total-resident-bytes account of the plan cache (`plan_resident_bytes`,
    the eviction input), and an HBM preflight that rejects a bucket whose
    estimated footprint will not fit the device memory budget *before*
    it is admitted;
  - `warmup()` pre-compiles every bucket so no request pays a compile.

Thread-safe: plan creation and device execution are serialized with an
internal lock (one device stream; the DynamicBatcher drives it from a
single worker thread anyway, but direct `infer` from many threads is
safe too).
"""
from __future__ import annotations

import threading

import numpy as np

from ..predictor import Predictor
from ..telemetry import tracing as _tracing


def _pow2_buckets(max_batch):
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_batch))
    return buckets


class ServingEngine:
    """Load a .mxa artifact (or wrap an existing Predictor) and serve
    any request batch <= the export batch through bucketed compiled
    plans."""

    def __init__(self, model, device=None, buckets=None, warmup=True):
        self._pred = model if isinstance(model, Predictor) \
            else Predictor(model, device=device)
        man = self._pred.manifest
        serving = man.get("serving", {})
        # compute dtype baked into the artifact (mxnet_tpu.amp); request
        # and response I/O are fp32 either way — the casts are fused
        # inside each bucket's jitted plan (exp.call carries them)
        self.amp_dtype = serving.get("amp_dtype") \
            or man.get("dtype", "float32")
        self.batch_axis = int(serving.get("batch_axis", 0))
        if self.batch_axis != 0:
            raise ValueError("ServingEngine: only batch_axis 0 artifacts "
                             "are supported")
        self.max_batch = self._pred.export_batch
        ladder = buckets or serving.get("buckets") \
            or _pow2_buckets(self.max_batch)
        ladder = sorted({int(b) for b in ladder if 1 <= int(b)})
        if any(b > self.max_batch for b in ladder):
            raise ValueError(f"ServingEngine: bucket larger than the "
                             f"export batch {self.max_batch}")
        if not ladder or ladder[-1] != self.max_batch:
            ladder.append(self.max_batch)
        self.buckets = ladder
        self.input_names = list(self._pred._input_names)
        self.output_names = list(self._pred.output_names)
        # per-model metrics label (serving/metrics.py): recorded by
        # contrib.export when the artifact was built with a name
        self.model_name = str(man.get("model_name")
                              or serving.get("model") or "model")
        self._plans = {}
        self.plan_bytes = {}            # bucket -> resident-bytes estimate
        self.plan_peak_bytes = {}       # bucket -> est. execution footprint
        self.plan_resident_bytes = 0    # sum over cached plans (eviction input)
        self._lock = threading.RLock()
        self.plan_compiles = 0          # bucket plans built (cache misses)
        self.executions = 0             # compiled-plan invocations
        self.padded_rows = 0            # host-side request->bucket padding
        if warmup:
            self.warmup()

    @classmethod
    def from_symbol(cls, symbol, arg_params, aux_params, data_shapes,
                    path=None, **kwargs):
        """Export `symbol` through contrib.export and serve the artifact
        — the one-call train->serve bridge (uses the same _build_runner
        lowering the Executor runs)."""
        import tempfile
        import os
        from ..contrib.export import export_model
        if path is None:
            path = os.path.join(tempfile.mkdtemp(prefix="mxa_serve_"),
                                "model.mxa")
        export_model(path, symbol, arg_params, aux_params, data_shapes)
        return cls(path, **kwargs)

    # -- plan cache ---------------------------------------------------------

    def bucket_for(self, n):
        """Smallest bucket >= n (the plan that serves an n-row batch)."""
        if n < 1 or n > self.max_batch:
            raise ValueError(f"batch {n} outside [1, {self.max_batch}]")
        for b in self.buckets:
            if b >= n:
                return b
        return self.max_batch          # unreachable (ladder ends at max)

    def _plan(self, bucket):
        plan = self._plans.get(bucket)
        if plan is not None:
            return plan
        import jax
        import jax.numpy as jnp
        exp = self._pred._exp
        B = self.max_batch

        def fn(inputs, state, rng):
            feed = []
            for x in inputs:
                if x.ndim > 0 and x.shape[0] == bucket and bucket < B:
                    pad = jnp.zeros((B - bucket,) + x.shape[1:], x.dtype)
                    x = jnp.concatenate([x, pad], axis=0)
                feed.append(x)
            outs = exp.call(*feed, *state, rng)
            return tuple(o[:bucket]
                         if getattr(o, "ndim", 0) and o.shape[0] == B
                         else o for o in outs)

        # AOT: lower against this bucket's exact specs and keep the
        # Compiled object itself as the plan. Compiled is directly
        # callable, so dispatch pays no jit-cache keying — and the same
        # executable yields cost/memory analytics for free.
        from ..telemetry import devstats
        in_specs = tuple(jax.ShapeDtypeStruct(
            (bucket,) + tuple(self._pred._input_shapes[n][1:]),
            jnp.float32) for n in self.input_names)
        state_specs = tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                            for s in self._pred._state)
        rng_spec = jax.ShapeDtypeStruct(self._pred._rng.shape,
                                        self._pred._rng.dtype)
        compiled = jax.jit(fn).lower(in_specs, state_specs,
                                     rng_spec).compile()
        resident = peak = 0
        if devstats.enabled():
            name = "serving.b%d" % bucket
            stats = devstats.record_program(name, compiled=compiled,
                                            kind="serving")
            # resident = what keeping the plan cached pins (the
            # executable); cpu reports no code size — fall back to the
            # I/O footprint so the account is never silently zero
            resident = int(stats["generated_code_bytes"]
                           or (stats["argument_bytes"]
                               + stats["output_bytes"]))
            peak = int(stats["peak_bytes"])
            # shed the bucket BEFORE admitting it to the cache: a sized
            # HBMPreflightError beats a runtime OOM mid-request
            devstats.preflight(name, peak,
                               resident_bytes=self.plan_resident_bytes,
                               what="serving bucket plan")
            devstats.note_compile(name)
        self._plans[bucket] = compiled
        self.plan_bytes[bucket] = resident
        self.plan_peak_bytes[bucket] = peak
        self.plan_resident_bytes = sum(self.plan_bytes.values())
        self.plan_compiles += 1
        return compiled

    def warmup(self):
        """Compile every bucket plan up front (serving must not pay XLA
        compiles on the request path). Bucket b+1's dummy inputs are
        built on the async device feed's thread (pipeline.py) while
        bucket b compiles; with MXNET_COMPILE_CACHE set, re-runs load
        every bucket plan from the disk cache instead of recompiling.

        The dummies stay host-side numpy (the shape requests arrive in);
        plans are AOT Compiled objects, so input commitment cannot key a
        fresh compile either way."""
        from ..pipeline import feed_or_inline, close_feed

        def _stage(b):
            return b, [np.zeros((b,) + tuple(
                self._pred._input_shapes[n][1:]), np.float32)
                for n in self.input_names]

        feed = feed_or_inline(iter(self.buckets), _stage,
                              name="serving_warmup")
        try:
            with self._lock:
                for b, staged in feed:
                    self._run(b, staged)
        finally:
            close_feed(feed)

    # -- request path -------------------------------------------------------

    def _run(self, bucket, arrays):
        plan = self._plan(bucket)
        outs = plan(tuple(arrays), tuple(self._pred._state),
                    self._pred._rng)
        self.executions += 1
        return outs

    def infer(self, *arrays):
        """Run one already-coalesced batch (n rows, 1 <= n <= max_batch,
        batch axis 0). Returns a list of numpy arrays sliced to n."""
        arrays = [np.asarray(getattr(a, "_data", a), np.float32)
                  for a in arrays]
        if len(arrays) != len(self.input_names):
            raise ValueError(f"expected {len(self.input_names)} inputs "
                             f"{self.input_names}, got {len(arrays)}")
        n = int(arrays[0].shape[0])
        for name, a in zip(self.input_names, arrays):
            want = self._pred._input_shapes[name]
            if a.shape[0] != n or tuple(a.shape[1:]) != tuple(want[1:]):
                raise ValueError(
                    f"input {name!r}: shape {tuple(a.shape)} is not "
                    f"(n<= {self.max_batch},)+{tuple(want[1:])}")
        bucket = self.bucket_for(n)
        if bucket != n:
            arrays = [np.concatenate(
                [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)],
                axis=0) for a in arrays]
        # "serve" span covers lock wait + plan execution — the
        # request-visible compute latency
        with _tracing.span("serve.compute", phase="serve",
                           bucket=bucket, rows=n):
            with self._lock:
                # padding accounting under the lock: infer() runs
                # concurrently on batcher-worker and direct-caller
                # threads, and += on a bare attribute loses updates
                # under that interleaving
                if bucket != n:
                    self.padded_rows += bucket - n
                outs = self._run(bucket, arrays)
        return [np.asarray(o)[:n]
                if getattr(o, "ndim", 0) and np.asarray(o).shape[0] == bucket
                else np.asarray(o) for o in outs]

    def stats(self):
        return {"buckets": list(self.buckets),
                "max_batch": self.max_batch,
                "amp_dtype": self.amp_dtype,
                "model": self.model_name,
                "plan_compiles": self.plan_compiles,
                "plans": len(self._plans),
                "plan_bytes": dict(self.plan_bytes),
                "plan_peak_bytes": dict(self.plan_peak_bytes),
                "plan_resident_bytes": self.plan_resident_bytes,
                "executions": self.executions,
                "padded_rows": self.padded_rows}
