"""ServingFrontend — the network front door: HTTP/1.1 JSON over a
ModelRouter.

    python -m mxnet_tpu.serving.frontend model_a.mxa model_b.mxa
    python -m mxnet_tpu.serving.frontend --selftest

Stdlib-only (threaded `http.server`, JSON wire format), one server per
frontend on a daemon thread, one ModelRouter behind it:

    POST /v1/models/<name>:predict   {"inputs": [...], "priority":
                                      "interactive"|"batch",
                                      "timeout_ms": N}
                                  -> {"model": ..., "outputs": [...]}
    POST /v1/models/<name>:load      {"path": "/path/to/model.mxa"}
    POST /v1/models/<name>:unload    {}
    GET  /v1/models                  router table + per-model stats
    GET  /healthz                    liveness + model count
    GET  /metrics                    telemetry registry (Prometheus)

Status mapping is the overload contract on the wire: 404 unknown model,
429 `ServingQueueFull` (shed — the batch class sheds first), 504
`RequestTimeout` (deadline passed in queue), 507 `HBMPreflightError`
(model rejected by the admission preflight before any plan compiled),
400 malformed request, 409 racing a closed router/batcher.

`--selftest` drives the whole tier through real sockets: 64+ concurrent
client threads against two hot models (p99 within the interactive
deadline), a mixed-priority overload proving batch sheds before
interactive, and a budget-bound load -> LRU-evict -> reload cycle where
an over-budget model 507s with the router table provably untouched.
"""
from __future__ import annotations

import argparse
import atexit
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .batcher import RequestTimeout, ServingQueueFull
from .router import ModelRouter, UnknownModel, manifest_need_bytes
from ..telemetry import devstats
from ..telemetry.registry import get_registry

__all__ = ["ServingFrontend", "status_for"]

_JSON = "application/json"
_METRICS_CTYPE = "text/plain; version=0.0.4; charset=utf-8"


def status_for(exc):
    """Exception -> HTTP status. Order matters: the serving exceptions
    subclass RuntimeError/KeyError, so they are matched first."""
    if isinstance(exc, UnknownModel):
        return 404
    if isinstance(exc, ServingQueueFull):
        return 429
    if isinstance(exc, RequestTimeout):
        return 504
    if isinstance(exc, devstats.HBMPreflightError):
        return 507
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return 400
    if isinstance(exc, RuntimeError):
        return 409              # closed router/batcher, table full
    return 500


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-serving/1.0"
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------------

    def _reply(self, code, body, ctype=_JSON):
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _fail(self, exc):
        code = status_for(exc)
        self._reply(code, {"error": type(exc).__name__,
                           "message": str(exc)})

    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        if not raw:
            return {}
        out = json.loads(raw.decode("utf-8"))
        if not isinstance(out, dict):
            raise ValueError("request body must be a JSON object")
        return out

    def log_message(self, fmt, *args):
        if os.environ.get("MXNET_TELEMETRY_HTTP_LOG"):
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    @property
    def frontend(self):
        return self.server.frontend

    # -- routes --------------------------------------------------------------

    def do_GET(self):                               # noqa: N802 (stdlib api)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                router = self.frontend.router
                self._reply(200, {
                    "status": "ok", "pid": os.getpid(),
                    "models": router.models(),
                    "resident_bytes": router.resident_bytes(),
                })
            elif path == "/metrics":
                self._reply(200,
                            get_registry().render_prometheus().encode(),
                            ctype=_METRICS_CTYPE)
            elif path == "/v1/models":
                self._reply(200, self.frontend.router.stats())
            elif path.startswith("/v1/models/"):
                name = path[len("/v1/models/"):]
                self._reply(200, self.frontend.router.stats(name))
            else:
                self._reply(404, {"error": "NotFound", "message":
                                  "try /v1/models, /healthz, /metrics"})
        except Exception as e:
            self._fail(e)

    def do_POST(self):                              # noqa: N802 (stdlib api)
        path = self.path.split("?", 1)[0]
        try:
            if not path.startswith("/v1/models/") or ":" not in path:
                raise UnknownModel(f"no POST route {path!r}")
            name, _, verb = path[len("/v1/models/"):].rpartition(":")
            if not name:
                raise ValueError("empty model name")
            body = self._body()
            if verb == "predict":
                self._predict(name, body)
            elif verb == "generate":
                self._generate(name, body)
            elif verb == "load":
                st = self.frontend.router.load(name, str(body["path"]))
                self._reply(200, st)
            elif verb == "unload":
                self.frontend.router.unload(name)
                self._reply(200, {"unloaded": name})
            else:
                raise ValueError(f"unknown verb {verb!r}")
        except Exception as e:
            self._fail(e)

    def _predict(self, name, body):
        inputs = body.get("inputs")
        if inputs is None:
            raise ValueError("predict body needs 'inputs'")
        # positional list of arrays (batch axis first on each), or
        # {input_name: array}
        if isinstance(inputs, dict):
            order = self.frontend.input_names(name)
            try:
                inputs = [inputs[k] for k in order]
            except KeyError as e:
                raise ValueError(f"missing input {e.args[0]!r} "
                                 f"(expects {order})")
        elif not isinstance(inputs, list):
            raise ValueError("inputs must be a list (one array per "
                             "model input) or a name->array object")
        arrays = [np.asarray(a, np.float32) for a in inputs]
        priority = str(body.get("priority") or "interactive")
        timeout_ms = body.get("timeout_ms")
        fut = self.frontend.router.predict(
            name, arrays, timeout_ms=timeout_ms, priority=priority)
        outs = fut.result()
        self._reply(200, {"model": name,
                          "outputs": [np.asarray(o).tolist()
                                      for o in outs]})

    def _generate(self, name, body):
        """Decode-model session API: {"tokens": [...], "max_new_tokens":
        N, "eos_id": E, "timeout_ms": T} -> the greedy completion. The
        session blocks this handler thread only (ThreadingHTTPServer);
        the decode loop packs it with every other live session."""
        tokens = body.get("tokens")
        if tokens is None:
            raise ValueError("generate body needs 'tokens' (prompt ids)")
        if not isinstance(tokens, list):
            raise ValueError("'tokens' must be a list of token ids")
        sess = self.frontend.router.generate(
            name, tokens,
            max_new_tokens=body.get("max_new_tokens"),
            eos_id=body.get("eos_id"),
            timeout_ms=body.get("timeout_ms"))
        out = sess.result()
        self._reply(200, {"model": name, "session": sess.sid,
                          "prompt_tokens": len(tokens),
                          "tokens": out})


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # a burst of N concurrent clients all connect before the accept loop
    # catches up; the stdlib default backlog (5) resets the overflow
    request_queue_size = 256


class ServingFrontend:
    """HTTP server + ModelRouter. `port=None` reads MXNET_SERVING_PORT
    (0 = ephemeral; `self.port` has the bound one). Extra kwargs build
    the router (budget, replicas, queue_depth, buckets, ...); passing
    `router=` uses yours and leaves its lifecycle to you."""

    def __init__(self, router=None, host="127.0.0.1", port=None,
                 **router_kw):
        if port is None:
            from .. import config
            raw = config.get("MXNET_SERVING_PORT")
            port = int(raw) if raw not in (None, "") else 0
        self._owns_router = router is None
        self.router = router if router is not None \
            else ModelRouter(**router_kw)
        self._closed = False
        self._close_lock = threading.Lock()
        self._httpd = _Server((host, int(port)), _Handler)
        self._httpd.frontend = self
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="mxnet_tpu-serving-frontend", daemon=True)
        self._thread.start()
        _FRONTENDS.add(self)
        _install_atexit()

    @property
    def url(self):
        host = "127.0.0.1" if self.host in ("0.0.0.0", "::") else self.host
        return f"http://{host}:{self.port}"

    def input_names(self, model):
        """Input order of a loaded model (for dict-shaped predict
        bodies)."""
        with self.router._lock:
            entry = self.router._models.get(str(model))
            pool = entry.pool if entry is not None else None
        if pool is None:
            raise UnknownModel(f"model {model!r} is not loaded")
        return list(getattr(pool.engines[0], "input_names", []))

    def close(self):
        """Idempotent: stop accepting, join the server thread, then
        close the router (owned routers only) — every batcher worker
        joins before this returns."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:               # pragma: no cover
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=10.0)
        if self._owns_router:
            self.router.close()

    __enter__ = lambda self: self

    def __exit__(self, *exc):
        self.close()
        return False


# interpreter exit: close every live frontend exactly once (WeakSet —
# a collected frontend already closed; registration is install-once)
_FRONTENDS = weakref.WeakSet()
_atexit_lock = threading.Lock()
_atexit_installed = [False]


def _close_all():
    for fe in list(_FRONTENDS):
        fe.close()


def _install_atexit():
    with _atexit_lock:
        if not _atexit_installed[0]:
            atexit.register(_close_all)
            _atexit_installed[0] = True


# ---------------------------------------------------------------- selftest

def _export_mlp(dirpath, name, batch=8, in_dim=16, hidden=16):
    """Tiny MLP -> .mxa named `name` (Xavier init; serving cares about
    shapes and plan sizes, not weights)."""
    import mxnet_tpu as mx
    from ..contrib.export import export_model

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (batch, in_dim))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.init.Xavier())
    args, auxs = mod.get_params()
    path = os.path.join(dirpath, f"{name}.mxa")
    export_model(path, sym, args, auxs, {"data": (batch, in_dim)},
                 model_name=name)
    return path


def _http(method, url, body=None, timeout=60):
    """(status, parsed-json) — HTTPError bodies parse too; transport
    failures come back as status 0 instead of raising (a load-gen
    thread must count them, not die)."""
    import urllib.error
    import urllib.request
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": _JSON} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read().decode()
            code = r.status
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        code = e.code
    except OSError as e:
        return 0, {"error": type(e).__name__, "message": str(e)}
    try:
        return code, json.loads(raw or "{}")
    except ValueError:
        return code, {"raw": raw}


def _closed_loop(base, jobs):
    """Run len(jobs) client threads; each job is (model, priority,
    timeout_ms, n_requests, row). Returns per-class dicts of status
    counts and sorted 200-latencies (ms)."""
    lock = threading.Lock()
    counts = {}                 # (klass, status) -> n
    lats = {}                   # klass -> [ms]
    start = threading.Barrier(len(jobs) + 1)

    def client(model, priority, timeout_ms, n, row):
        url = f"{base}/v1/models/{model}:predict"
        body = {"inputs": row, "priority": priority,
                "timeout_ms": timeout_ms}
        start.wait()
        for _ in range(n):
            t0 = time.perf_counter()
            code, _payload = _http("POST", url, body)
            ms = (time.perf_counter() - t0) * 1e3
            with lock:
                counts[(priority, code)] = \
                    counts.get((priority, code), 0) + 1
                if code == 200:
                    lats.setdefault(priority, []).append(ms)

    threads = [threading.Thread(target=client, args=j, daemon=True)
               for j in jobs]
    for t in threads:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    for v in lats.values():
        v.sort()
    return counts, lats, dt


def _pctl(sorted_ms, p):
    if not sorted_ms:
        return None
    i = min(len(sorted_ms) - 1,
            int(round(p / 100.0 * (len(sorted_ms) - 1))))
    return round(sorted_ms[i], 2)


def _phase_throughput(res, paths, requests, concurrency, replicas,
                      deadline_ms):
    """>=64 concurrent interactive clients, 2 hot models, all 200, p99
    within deadline."""
    fe = ServingFrontend(replicas=replicas, queue_depth=max(concurrency,
                                                            64),
                         max_wait_us=1000, buckets=[1, 4, 8])
    try:
        for name, path in paths.items():
            code, payload = _http("POST",
                                  f"{fe.url}/v1/models/{name}:load",
                                  {"path": path})
            assert code == 200, f"load {name}: {code} {payload}"
        names = list(paths)
        per = max(1, requests // concurrency)
        row = [[[0.5] * 16]]
        jobs = [(names[i % len(names)], "interactive", deadline_ms, per,
                 row) for i in range(concurrency)]
        counts, lats, dt = _closed_loop(fe.url, jobs)
        n_ok = counts.get(("interactive", 200), 0)
        total = sum(counts.values())
        assert n_ok == total, f"non-200 under open load: {counts}"
        p99 = _pctl(lats["interactive"], 99)
        assert p99 is not None and p99 <= deadline_ms, \
            f"interactive p99 {p99}ms over the {deadline_ms}ms deadline"
        code, models = _http("GET", f"{fe.url}/v1/models")
        assert code == 200 and set(models["models"]) == set(names)
        code, health = _http("GET", f"{fe.url}/healthz")
        assert code == 200 and set(health["models"]) == set(names)
        res.update({
            "throughput_requests": total,
            "throughput_concurrency": concurrency,
            "qps": round(total / dt, 2),
            "p50_ms": _pctl(lats["interactive"], 50),
            "p99_ms": p99,
            "deadline_ms": deadline_ms,
        })
        return fe
    except BaseException:
        fe.close()
        raise


def _phase_overload(res, fe, model, deadline_ms):
    """Mixed-priority flood of ONE model with a tiny batch-class quota:
    batch sheds (429s) while interactive stays whole and in-deadline."""
    with fe.router._lock:
        pools = [e.pool for e in fe.router._models.values() if e.pool]
    for p in pools:
        for b in p.batchers:
            b.batch_queue_depth = 2  # overload knob: shed batch early
        for e in p.engines:
            # a cpu-tick MLP never builds a queue: give every coalesced
            # batch a real service time so the closed loop overloads
            orig = e.infer

            def slowed(*arrays, _orig=orig):
                time.sleep(0.02)
                return _orig(*arrays)

            e.infer = slowed
    row = [[[0.5] * 16]]
    jobs = [(model, "interactive", deadline_ms, 24, row)
            for _ in range(24)] + \
           [(model, "batch", deadline_ms, 24, row) for _ in range(24)]
    counts, lats, _dt = _closed_loop(fe.url, jobs)

    def frac(klass, code):
        tot = sum(n for (k, c), n in counts.items() if k == klass)
        return (sum(n for (k, c), n in counts.items()
                    if k == klass and c == code) / tot) if tot else 0.0

    shed_b, shed_i = frac("batch", 429), frac("interactive", 429)
    assert counts.get(("batch", 429), 0) > 0, \
        f"overload never shed batch: {counts}"
    assert shed_b > shed_i, \
        f"batch shed frac {shed_b:.3f} !> interactive {shed_i:.3f}"
    p99_i = _pctl(lats.get("interactive", []), 99)
    assert p99_i is not None and p99_i <= deadline_ms, \
        f"interactive p99 {p99_i}ms over deadline under overload"
    # the per-class counters made it to /metrics with model labels
    code, _ = _http("GET", f"{fe.url}/healthz")
    assert code == 200
    import urllib.request
    text = urllib.request.urlopen(fe.url + "/metrics",
                                  timeout=30).read().decode()
    shed_lines = [ln for ln in text.splitlines()
                  if "shed_total{" in ln and 'class="batch"' in ln
                  and f'model="{model}"' in ln]
    assert shed_lines, "no per-class shed series on /metrics"
    res.update({
        "overload_counts": {f"{k}:{c}": n
                            for (k, c), n in sorted(counts.items())},
        "overload_shed_frac_batch": round(shed_b, 3),
        "overload_shed_frac_interactive": round(shed_i, 3),
        "overload_p99_interactive_ms": p99_i,
    })


def _phase_lru_cycle(res, tmp, paths):
    """Budget-bound router over HTTP: load -> LRU-evict -> reload, and
    an over-budget model 507s BEFORE any plan enters any cache."""
    # probe: measured resident of one tiny model at replicas=1 — the
    # artifacts are architecturally identical, so r is each model's cost
    with ServingFrontend(replicas=1, buckets=[1, 8]) as probe:
        code, st = _http("POST", f"{probe.url}/v1/models/pa:load",
                         {"path": paths["alpha"]})
        assert code == 200, f"probe load: {code} {st}"
        r = int(st["resident_bytes"])
        plans_each = int(st["plans"])
        code, st_b = _http("POST", f"{probe.url}/v1/models/pb:load",
                           {"path": paths["beta"]})
        assert code == 200 and int(st_b["resident_bytes"]) == r, \
            "identical artifacts measured different plan residents"
    need = int(manifest_need_bytes(paths["alpha"]))
    assert r > 0 and need > 0
    budget = 2 * r + need - 1   # alpha+beta fit; a third forces evicts
    gamma = _export_mlp(tmp, "gamma")
    omega = _export_mlp(tmp, "omega", in_dim=256, hidden=2048)
    need_omega = int(manifest_need_bytes(omega))
    assert need_omega > budget, \
        f"omega estimate {need_omega} does not exceed budget {budget}"
    fe = ServingFrontend(replicas=1, buckets=[1, 8], budget=budget)
    try:
        u = fe.url
        assert _http("POST", f"{u}/v1/models/alpha:load",
                     {"path": paths["alpha"]})[0] == 200
        assert _http("POST", f"{u}/v1/models/beta:load",
                     {"path": paths["beta"]})[0] == 200
        # touch beta so alpha is the LRU victim
        row = [[[0.5] * 16]]
        assert _http("POST", f"{u}/v1/models/beta:predict",
                     {"inputs": row})[0] == 200
        code, _ = _http("POST", f"{u}/v1/models/gamma:load",
                        {"path": gamma})
        assert code == 200, f"gamma load: {code}"
        code, models = _http("GET", f"{u}/v1/models")
        held = set(models["models"])
        assert held == {"beta", "gamma"}, \
            f"expected alpha LRU-evicted, table = {held}"
        # reload alpha: the cycle closes (beta is now the LRU victim)
        assert _http("POST", f"{u}/v1/models/alpha:load",
                     {"path": paths["alpha"]})[0] == 200
        code, models = _http("GET", f"{u}/v1/models")
        held = set(models["models"])
        assert held == {"gamma", "alpha"}, f"reload cycle broke: {held}"
        assert _http("POST", f"{u}/v1/models/alpha:predict",
                     {"inputs": row})[0] == 200
        # over-budget model: 507 from the admission preflight BEFORE
        # eviction and BEFORE any plan compiles — table/caches untouched
        before = _http("GET", f"{u}/v1/models")[1]
        plans_before = sum(m.get("plans", 0)
                           for m in before["models"].values())
        code, payload = _http("POST", f"{u}/v1/models/omega:load",
                              {"path": omega})
        assert code == 507, f"over-budget load gave {code}: {payload}"
        after = _http("GET", f"{u}/v1/models")[1]
        assert set(after["models"]) == held, \
            f"507 mutated the table: {set(after['models'])}"
        plans_after = sum(m.get("plans", 0)
                          for m in after["models"].values())
        assert plans_after == plans_before == 2 * plans_each
        assert after["resident_bytes"] == before["resident_bytes"] \
            == 2 * r
        assert _http("GET", f"{u}/v1/models/omega")[0] == 404
        res.update({
            "lru_budget_bytes": budget,
            "lru_resident_per_model": r,
            "lru_evictions_seen": 2,
            "overbudget_status": code,
            "overbudget_need_bytes": need_omega,
        })
    finally:
        fe.close()


def selftest(requests=512, concurrency=64, replicas=2,
             deadline_ms=15000):
    """The acceptance run. Returns the result dict; "ok" gates exit."""
    res = {"metric": "serving_frontend_selftest",
           "concurrency": concurrency, "replicas": replicas}
    tmp = tempfile.mkdtemp(prefix="mxa_frontend_")
    try:
        paths = {"alpha": _export_mlp(tmp, "alpha"),
                 "beta": _export_mlp(tmp, "beta")}
        fe = _phase_throughput(res, paths, requests, concurrency,
                               replicas, deadline_ms)
        try:
            _phase_overload(res, fe, "alpha", deadline_ms)
        finally:
            fe.close()
        _phase_lru_cycle(res, tmp, paths)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    res["ok"] = True
    return res


def bench(requests=768, concurrency=64, replicas=2, batch_frac=0.25,
          deadline_ms=15000):
    """One mixed-priority closed loop for bench.py's serving_net lane:
    prints QPS / p50 / p99 / shed fraction at `concurrency`."""
    tmp = tempfile.mkdtemp(prefix="mxa_frontend_bench_")
    try:
        paths = {"alpha": _export_mlp(tmp, "alpha"),
                 "beta": _export_mlp(tmp, "beta")}
        return _bench_run(paths, requests, concurrency, replicas,
                          batch_frac, deadline_ms)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_run(paths, requests, concurrency, replicas, batch_frac,
               deadline_ms):
    n_batch = int(concurrency * batch_frac)
    n_inter = concurrency - n_batch
    with ServingFrontend(replicas=replicas, queue_depth=16,
                         batch_queue_depth=4, max_wait_us=1000,
                         buckets=[1, 4, 8]) as fe:
        for name, path in paths.items():
            code, payload = _http("POST",
                                  f"{fe.url}/v1/models/{name}:load",
                                  {"path": path})
            if code != 200:
                raise RuntimeError(f"load {name}: {code} {payload}")
        names = list(paths)
        per = max(1, requests // concurrency)
        row = [[[0.5] * 16]]
        jobs = [(names[i % 2], "interactive", deadline_ms, per, row)
                for i in range(n_inter)] + \
               [(names[i % 2], "batch", deadline_ms, per, row)
                for i in range(n_batch)]
        counts, lats, dt = _closed_loop(fe.url, jobs)
    total = sum(counts.values())
    ok = sum(n for (_, c), n in counts.items() if c == 200)
    shed = sum(n for (_, c), n in counts.items() if c == 429)
    inter = lats.get("interactive", [])
    return {
        "metric": "serving_net",
        "concurrency": concurrency,
        "replicas": replicas,
        "models": len(names),
        "requests": total,
        "completed": ok,
        "qps": round(ok / dt, 2),
        "p50_ms": _pctl(inter, 50),
        "p99_ms": _pctl(inter, 99),
        "shed_frac": round(shed / total, 4) if total else 0.0,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.serving.frontend",
        description="HTTP serving front door over a ModelRouter")
    ap.add_argument("models", nargs="*", default=[],
                    help=".mxa artifacts to pre-load (named by their "
                         "manifest model_name / file stem)")
    ap.add_argument("--selftest", action="store_true",
                    help="socket-level acceptance run; one JSON line")
    ap.add_argument("--bench", action="store_true",
                    help="closed-loop load numbers; one JSON line")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--concurrency", type=int, default=64)
    args = ap.parse_args(argv)
    if args.selftest:
        try:
            res = selftest(requests=args.requests or 512,
                           concurrency=args.concurrency,
                           replicas=args.replicas or 2)
        except AssertionError as e:
            res = {"metric": "serving_frontend_selftest", "ok": False,
                   "error": str(e)}
        print(json.dumps(res), flush=True)
        return 0 if res.get("ok") else 1
    if args.bench:
        res = bench(requests=args.requests or 768,
                    concurrency=args.concurrency,
                    replicas=args.replicas or 2)
        print(json.dumps(res), flush=True)
        return 0
    fe = ServingFrontend(host=args.host, port=args.port,
                         replicas=args.replicas)
    for path in args.models:
        name = os.path.splitext(os.path.basename(path))[0]
        fe.router.load(name, path)
    print(json.dumps({"serving": fe.url,
                      "models": fe.router.models()}), flush=True)
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        return 0
    finally:
        fe.close()


if __name__ == "__main__":
    sys.exit(main())
