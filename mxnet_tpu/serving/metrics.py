"""Serving counters — QPS, latency percentiles, batch histogram, queue
depth, shed/timeout counts — wired into mx.profiler.

Two consumption paths, same numbers:
  - `snapshot()` / `to_json()` for the serving CLI and tools;
  - every `ServingMetrics` registers itself as a profiler counter-export
    hook (profiler.register_counter_export), so `mx.profiler.dump()`
    embeds the serving counters in the chrome-trace JSON and
    `mx.profiler.export_counters()` returns them live. Queue depth and
    shed count additionally tick profiler `Counter` objects in a
    "serving" `Domain`, which emits 'C' (counter) trace events on the
    profiler timeline when profiling is on.

Latency percentiles come from a bounded reservoir of the most recent
`latency_window` request latencies (deque ring) — O(1) record, exact
percentiles over the window, no unbounded growth under sustained load.

The telemetry registry (mxnet_tpu.telemetry) absorbs the snapshot hook,
so every field here appears at /metrics as `mxnet_serving_*`; queue
depth, request latency and the engine's compiled-plan cache footprint
(`mxnet_serving_plan_resident_bytes`, fed by devstats accounting via
`record_plan_bytes`) additionally feed native registry series so
Prometheus sees real cumulative-bucket distributions, not just window
percentiles. When the engine's .mxa manifest names the model, every
native series carries a `model="<name>"` label (plus `replica="N"` in
an EnginePool). Shed and timeout totals are native counters too — keyed
per admission class (`class="interactive"|"batch"`) — so the labels
survive even when a request dies in the batcher before any engine is
bound to it.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

from .. import profiler


class ServingMetrics:
    """Thread-safe serving counters; one instance per batcher/engine."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, name="serving", latency_window=4096, model=None,
                 replica=None):
        with ServingMetrics._seq_lock:
            ServingMetrics._seq += 1
            seq = ServingMetrics._seq
        self.name = name if seq == 1 else f"{name}#{seq}"
        self.model = str(model) if model else None
        self.replica = None if replica is None else int(replica)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.requests = 0          # accepted submits
        self.completed = 0         # futures resolved with a result
        self.shed = 0              # rejected at submit (queue full)
        self.timeouts = 0          # expired before execution
        self.shed_by_class = {}    # admission class -> shed count
        self.timeouts_by_class = {}
        self.errors = 0            # engine raised; future got the error
        self.batches = 0           # compiled-plan invocations
        self.batched_rows = 0      # rows across all batches
        self.queue_depth = 0       # live queue size (gauge)
        self.plan_resident_bytes = 0   # engine plan-cache footprint
        self.plans = 0                 # cached bucket plans
        self._batch_hist = {}      # rows -> count
        self._lat = deque(maxlen=latency_window)
        dom = profiler.Domain(self.name)
        self._c_depth = dom.new_counter("queue_depth")
        self._c_shed = dom.new_counter("shed_total")
        profiler.register_counter_export(self.name, self.snapshot)
        # native registry series ("#2" -> "_2" for metric-name legality);
        # model name from the .mxa manifest rides as a constant label so
        # a multi-model process gets distinguishable series without the
        # model leaking into metric names
        from ..telemetry import counter, gauge, histogram
        self._counter = counter
        mname = self.name.replace("#", "_")
        labels = {}
        if self.model:
            labels["model"] = self.model
        if self.replica is not None:
            labels["replica"] = str(self.replica)
        labels = labels or None
        self._mname = mname
        self._base_labels = dict(labels or {})
        self._g_depth = gauge(
            f"mxnet_{mname}_queue_depth",
            help="live dynamic-batcher queue size", labels=labels)
        self._h_lat = histogram(
            f"mxnet_{mname}_request_latency_seconds",
            help="submit-to-resolve request latency", labels=labels)
        self._g_plan_bytes = gauge(
            f"mxnet_{mname}_plan_resident_bytes",
            help="bytes resident in the engine's compiled bucket-plan "
                 "cache (devstats accounting)", labels=labels)
        # per-class shed/timeout counters created lazily on first record,
        # one `series=` per admission class under a shared metric name —
        # the model/replica/class labels ride on EVERY shed or timeout,
        # including requests shed at submit before an engine is bound
        self._c_shed_cls = {}
        self._c_timeout_cls = {}

    def _class_counter(self, table, what, klass):
        """Get-or-create the per-class counter. Caller holds self._lock
        (the registry's own get-or-create makes a race merely wasteful,
        but the table write must be guarded like every other field)."""
        c = table.get(klass)
        if c is None:
            labels = dict(self._base_labels)
            labels["class"] = klass
            c = self._counter(
                f"mxnet_{self._mname}_{what}_total",
                help=f"requests {what} per admission class",
                labels=labels, series=klass)
            table[klass] = c
        return c

    def close(self):
        profiler.unregister_counter_export(self.name)

    # -- recording ----------------------------------------------------------

    def record_submit(self):
        with self._lock:
            self.requests += 1

    def record_shed(self, klass="interactive"):
        with self._lock:
            self.shed += 1
            self.shed_by_class[klass] = self.shed_by_class.get(klass, 0) + 1
            c = self._class_counter(self._c_shed_cls, "shed", klass)
        c.inc()
        self._c_shed.increment()

    def record_timeout(self, klass="interactive"):
        with self._lock:
            self.timeouts += 1
            self.timeouts_by_class[klass] = \
                self.timeouts_by_class.get(klass, 0) + 1
            c = self._class_counter(self._c_timeout_cls, "timeout", klass)
        c.inc()

    def record_error(self):
        with self._lock:
            self.errors += 1

    def record_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth
        self._g_depth.set(depth)
        if profiler.is_running():
            self._c_depth.set_value(depth)

    def record_batch(self, rows):
        with self._lock:
            self.batches += 1
            self.batched_rows += rows
            self._batch_hist[rows] = self._batch_hist.get(rows, 0) + 1

    def record_done(self, latency_s):
        with self._lock:
            self.completed += 1
            self._lat.append(latency_s)
        self._h_lat.observe(latency_s)

    def record_plan_bytes(self, resident_bytes, plans=None):
        """Engine plan-cache footprint (ServingEngine.plan_resident_bytes,
        devstats-measured). Called after each bucket admit and on batcher
        attach, so /metrics carries the live cache size next to QPS/p99."""
        with self._lock:
            self.plan_resident_bytes = int(resident_bytes)
            if plans is not None:
                self.plans = int(plans)
        self._g_plan_bytes.set(int(resident_bytes))

    # -- reading ------------------------------------------------------------

    def _percentile_ms(self, lat_sorted, p):
        if not lat_sorted:
            return None
        i = min(len(lat_sorted) - 1,
                int(round(p / 100.0 * (len(lat_sorted) - 1))))
        return round(lat_sorted[i] * 1e3, 3)

    def snapshot(self):
        with self._lock:
            elapsed = max(time.monotonic() - self._t0, 1e-9)
            lat = sorted(self._lat)
            return {
                "requests": self.requests,
                "completed": self.completed,
                "shed": self.shed,
                "timeouts": self.timeouts,
                "shed_by_class": dict(self.shed_by_class),
                "timeouts_by_class": dict(self.timeouts_by_class),
                "errors": self.errors,
                "batches": self.batches,
                "batched_rows": self.batched_rows,
                "avg_batch_rows": round(self.batched_rows
                                        / self.batches, 3)
                if self.batches else None,
                "batch_hist": {str(k): v for k, v in
                               sorted(self._batch_hist.items())},
                "queue_depth": self.queue_depth,
                "qps": round(self.completed / elapsed, 2),
                "p50_ms": self._percentile_ms(lat, 50),
                "p99_ms": self._percentile_ms(lat, 99),
                "uptime_s": round(elapsed, 3),
                "model": self.model,
                "plans": self.plans,
                "plan_resident_bytes": self.plan_resident_bytes,
            }

    def to_json(self):
        return json.dumps(self.snapshot())
