"""EnginePool — R replicated ServingEngines behind least-loaded dispatch.

One compiled-plan cache serializes device execution behind the engine
lock, so a single ServingEngine caps a model's throughput at one
in-flight batch. The pool runs `replicas` independent engines over the
same .mxa artifact — each with its OWN plan cache (distinct AOT
`Compiled` objects; on a multi-device host each replica is pinned to
`devices[i % n]`, on cpu the distinct caches are the replication) — and
one DynamicBatcher per engine, so R batches can be in flight at once.

Dispatch is least-loaded: `submit()` reads every replica's live
`depth()` (queued in both admission classes + taken-but-unresolved) and
routes to the emptiest queue, round-robin on ties so idle replicas share
warmup evenly. That is the same number the per-replica queue-depth
gauges export, so /metrics shows exactly what the dispatcher saw.

Each replica's ServingMetrics carries `model=<name>` and `replica=<i>`
labels; `stats()` aggregates the per-replica snapshots for the frontend,
and `resident_bytes()` sums the plan caches — the number the
ModelRouter's LRU charges this model for.
"""
from __future__ import annotations

import threading

from .batcher import DynamicBatcher
from .engine import ServingEngine
from .metrics import ServingMetrics


class EnginePool:
    """R ServingEngine replicas over one artifact, least-loaded dispatch.

    Parameters
    ----------
    model : path to a .mxa artifact (or anything ServingEngine accepts).
    replicas : number of engine replicas (>= 1).
    engine_factory : replaces ServingEngine construction (tests inject
        fakes); called as `engine_factory(model, replica=i)`.
    queue_depth / batch_queue_depth / max_wait_us / default_timeout_ms :
        per-replica DynamicBatcher knobs.
    engine_kw : extra ServingEngine kwargs (e.g. buckets=[1, 4, 8]).
    """

    def __init__(self, model, replicas=1, engine_factory=None,
                 queue_depth=64, batch_queue_depth=None, max_wait_us=2000,
                 default_timeout_ms=None, **engine_kw):
        self.replicas = max(1, int(replicas))
        self._rr = 0                    # round-robin tiebreak cursor
        self._lock = threading.Lock()   # guards _rr and close-once
        self._closed = False
        engines = []
        try:
            for i in range(self.replicas):
                if engine_factory is not None:
                    engines.append(engine_factory(model, replica=i))
                else:
                    engines.append(ServingEngine(
                        model, device=self._pick_device(i), **engine_kw))
        except Exception:
            for e in engines:
                close = getattr(e, "close", None)
                if close:
                    close()
            raise
        self.engines = engines
        self.model_name = getattr(engines[0], "model_name", None)
        self.batchers = [
            DynamicBatcher(
                eng, max_wait_us=max_wait_us, queue_depth=queue_depth,
                batch_queue_depth=batch_queue_depth,
                default_timeout_ms=default_timeout_ms,
                metrics=ServingMetrics(
                    model=getattr(eng, "model_name", None), replica=i))
            for i, eng in enumerate(engines)]

    @staticmethod
    def _pick_device(i):
        """Pin replica i to devices[i % n]; None (default device) when
        the device query is unavailable (fakes, partial stubs)."""
        try:
            import jax
            devs = jax.devices()
            return devs[i % len(devs)] if devs else None
        except Exception:
            return None

    # -- dispatch ------------------------------------------------------------

    def _least_loaded(self):
        depths = [b.depth() for b in self.batchers]
        lo = min(depths)
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % self.replicas
        for k in range(self.replicas):
            i = (start + k) % self.replicas
            if depths[i] == lo:
                return i
        return 0                        # pragma: no cover - lo in depths

    def submit(self, *arrays, timeout_ms=None, priority="interactive"):
        """Route one request to the least-loaded replica; returns
        (future, replica_index)."""
        i = self._least_loaded()
        fut = self.batchers[i].submit(*arrays, timeout_ms=timeout_ms,
                                      priority=priority)
        return fut, i

    def infer(self, *arrays, timeout_ms=None, priority="interactive"):
        fut, _ = self.submit(*arrays, timeout_ms=timeout_ms,
                             priority=priority)
        return fut.result()

    # -- accounting ----------------------------------------------------------

    def depth(self):
        return sum(b.depth() for b in self.batchers)

    def resident_bytes(self):
        """Summed plan-cache footprint across replicas — the model's
        LRU eviction cost in the ModelRouter."""
        return sum(int(getattr(e, "plan_resident_bytes", 0) or 0)
                   for e in self.engines)

    def plan_compiles(self):
        return sum(len(getattr(e, "plan_bytes", {}) or {})
                   for e in self.engines)

    def warmup(self):
        for e in self.engines:
            w = getattr(e, "warmup", None)
            if w:
                w()
        for b in self.batchers:
            b._sync_plan_bytes()

    def stats(self):
        per = [b.metrics.snapshot() for b in self.batchers]
        return {
            "model": self.model_name,
            "replicas": self.replicas,
            "depth": self.depth(),
            "resident_bytes": self.resident_bytes(),
            "plans": self.plan_compiles(),
            "requests": sum(s["requests"] for s in per),
            "completed": sum(s["completed"] for s in per),
            "shed": sum(s["shed"] for s in per),
            "timeouts": sum(s["timeouts"] for s in per),
            "per_replica": per,
        }

    # -- lifecycle -----------------------------------------------------------

    def close(self, drain=True):
        """Idempotent: joins every batcher worker, unregisters the
        per-replica metrics hooks, closes engines that support it."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for b in self.batchers:
            b.close(drain=drain)
            b.metrics.close()
        for e in self.engines:
            close = getattr(e, "close", None)
            if close:
                close()

    __enter__ = lambda self: self

    def __exit__(self, *exc):
        self.close()
        return False
