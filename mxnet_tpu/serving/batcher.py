"""DynamicBatcher — micro-batching request queue in front of a
ServingEngine.

Clipper-style adaptive batching: concurrent callers `submit()` small
request batches (usually 1 row); a single worker thread coalesces
whatever is queued — up to `max_batch` rows, waiting at most
`max_wait_us` after the first request of a batch for stragglers — and
runs ONE compiled-plan execution for the whole coalesced batch. Under
load the wait never happens (the queue is already deep when the worker
comes back from the device), so throughput rides the biggest bucket
while lightly-loaded latency stays within `max_wait_us` of raw engine
latency.

Overload protocol (the load-shedding / backpressure contract):
  - the queue is bounded at `queue_depth` requests: `submit()` on a full
    queue raises `ServingQueueFull` immediately (shed at the door — the
    caller can retry/back off; nothing is silently dropped once
    accepted);
  - every request carries a deadline (`timeout_ms`, default
    `default_timeout_ms`); a request whose deadline passed while queued
    fails with `RequestTimeout` when the worker reaches it, and never
    occupies device time. `Future.result()` applies the same deadline
    client-side as a backstop.

Admission classes: every request belongs to `interactive` (default) or
`batch`. The two classes queue separately — the worker always drains
interactive first, and the batch queue is bounded at the smaller
`batch_queue_depth` quota — so under overload the batch class sheds and
times out FIRST while interactive latency stays near the engine floor.
Shed/timeout metrics carry the class.

All outcomes (completed / shed / timeout / error), per-request latency,
batch-size histogram and live queue depth are recorded in a
`ServingMetrics` (metrics.py), reachable as `batcher.metrics`.
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from .metrics import ServingMetrics
from ..telemetry import tracing as _tracing


class ServingQueueFull(RuntimeError):
    """submit() on a full queue — shed; back off and retry."""


class RequestTimeout(TimeoutError):
    """The request's deadline expired before it reached the device."""


class Future:
    """Minimal completion handle (threading.Event based)."""

    __slots__ = ("_ev", "_value", "_exc", "_deadline")

    def __init__(self, deadline):
        self._ev = threading.Event()
        self._value = None
        self._exc = None
        self._deadline = deadline

    def _set(self, value):
        self._value = value
        self._ev.set()

    def _set_exception(self, exc):
        self._exc = exc
        self._ev.set()

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        if timeout is None and self._deadline is not None:
            # backstop: never block past the request's own deadline
            timeout = max(self._deadline - time.monotonic(), 0.0) + 1.0
        if not self._ev.wait(timeout):
            raise RequestTimeout("result() timed out")
        if self._exc is not None:
            raise self._exc
        return self._value


ADMISSION_CLASSES = ("interactive", "batch")


class _Request:
    __slots__ = ("arrays", "rows", "t_submit", "t_perf", "deadline",
                 "future", "klass")

    def __init__(self, arrays, rows, deadline, klass="interactive"):
        self.arrays = arrays
        self.rows = rows
        self.t_submit = time.monotonic()
        self.t_perf = time.perf_counter()   # tracing's clock (spans)
        self.deadline = deadline
        self.future = Future(deadline)
        self.klass = klass


class DynamicBatcher:
    """Coalesce concurrent requests into bucketed engine executions.

    Parameters
    ----------
    engine : object with `max_batch`, `input_names` and
        `infer(*arrays) -> [np.ndarray]` (normally a ServingEngine).
    max_batch : rows per coalesced execution; defaults to (and may not
        exceed) `engine.max_batch`.
    max_wait_us : how long the worker lingers for stragglers after the
        first request of a batch. 0 = never wait (pure greedy drain).
    queue_depth : bound on QUEUED interactive requests; submit() past
        it sheds.
    batch_queue_depth : bound on QUEUED batch-class requests; defaults
        to `max(1, queue_depth // 2)` so batch sheds first.
    default_timeout_ms : per-request deadline when submit() gives none;
        None = no deadline.
    """

    def __init__(self, engine, max_batch=None, max_wait_us=2000,
                 queue_depth=64, batch_queue_depth=None,
                 default_timeout_ms=None, metrics=None):
        self.engine = engine
        cap = int(getattr(engine, "max_batch", 0) or 0)
        self.max_batch = int(max_batch or cap or 1)
        if cap and self.max_batch > cap:
            raise ValueError(f"max_batch {self.max_batch} exceeds the "
                             f"engine's export batch {cap}")
        self.max_wait_s = max_wait_us / 1e6
        self.queue_depth = int(queue_depth)
        self.batch_queue_depth = int(batch_queue_depth
                                     if batch_queue_depth is not None
                                     else max(1, self.queue_depth // 2))
        self.default_timeout_ms = default_timeout_ms
        self.metrics = metrics or ServingMetrics(
            model=getattr(engine, "model_name", None))
        self._sync_plan_bytes()
        self._q = deque()           # interactive class (drained first)
        self._qb = deque()          # batch class
        self._inflight = 0          # requests taken but not yet resolved
        self._cond = threading.Condition()
        self._stopped = False
        self._worker = threading.Thread(target=self._loop,
                                        name="mxnet_tpu-serving-batcher",
                                        daemon=True)
        self._worker.start()

    def _sync_plan_bytes(self):
        """Mirror the engine's plan-cache footprint (devstats-measured
        resident bytes per admitted bucket plan) into the metrics, so the
        gauge tracks lazy bucket admits as infer() triggers them."""
        resident = getattr(self.engine, "plan_resident_bytes", None)
        if resident is not None:
            plans = getattr(self.engine, "plan_bytes", None)
            self.metrics.record_plan_bytes(
                resident, plans=len(plans) if plans is not None else None)

    # -- client side --------------------------------------------------------

    def submit(self, *arrays, timeout_ms=None, priority="interactive"):
        """Enqueue one request (rows <= max_batch, batch axis 0);
        returns a Future. Raises ServingQueueFull when the class's
        bounded queue is at capacity (batch-class quota is smaller, so
        overload sheds batch first)."""
        if self._stopped:
            raise RuntimeError("batcher is closed")
        klass = str(priority or "interactive")
        if klass not in ADMISSION_CLASSES:
            raise ValueError(f"priority {klass!r} not in "
                             f"{ADMISSION_CLASSES}")
        arrays = [np.asarray(getattr(a, "_data", a), np.float32)
                  for a in arrays]
        rows = int(arrays[0].shape[0]) if arrays and arrays[0].ndim else 1
        if rows < 1 or rows > self.max_batch:
            raise ValueError(f"request rows {rows} outside "
                             f"[1, {self.max_batch}]")
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = (time.monotonic() + timeout_ms / 1e3
                    if timeout_ms is not None else None)
        req = _Request(arrays, rows, deadline, klass=klass)
        q = self._q if klass == "interactive" else self._qb
        cap = self.queue_depth if klass == "interactive" \
            else self.batch_queue_depth
        with self._cond:
            if len(q) >= cap:
                self.metrics.record_shed(klass)
                raise ServingQueueFull(
                    f"{klass} queue at capacity ({cap}); shedding")
            q.append(req)
            self.metrics.record_submit()
            self.metrics.record_queue_depth(len(self._q)
                                            + len(self._qb))
            self._cond.notify()
        return req.future

    def infer(self, *arrays, timeout_ms=None, priority="interactive"):
        """Blocking convenience wrapper: submit + result."""
        return self.submit(*arrays, timeout_ms=timeout_ms,
                           priority=priority).result()

    def depth(self):
        """Live load: queued (both classes) + taken-but-unresolved.
        The EnginePool's least-loaded dispatch keys off this."""
        with self._cond:
            return len(self._q) + len(self._qb) + self._inflight

    def close(self, drain=True):
        """Stop the worker. With drain=True pending requests are served
        first; otherwise they fail with RuntimeError."""
        with self._cond:
            if self._stopped:
                return
            self._stopped = True
            if not drain:
                for q in (self._q, self._qb):
                    while q:
                        req = q.popleft()
                        req.future._set_exception(
                            RuntimeError("batcher closed"))
            self._cond.notify_all()
        self._worker.join(timeout=30)

    __enter__ = lambda self: self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- worker side --------------------------------------------------------

    def _pop_expired(self, req, now):
        """True (and fail the future) when req's deadline passed."""
        if req.deadline is not None and now > req.deadline:
            self.metrics.record_timeout(req.klass)
            req.future._set_exception(RequestTimeout(
                f"deadline exceeded after "
                f"{(now - req.t_submit) * 1e3:.1f} ms in queue"))
            return True
        return False

    def _take_batch(self):
        """Block until work (or stop); return the coalesced request
        list, honoring max_batch rows and the max_wait_us linger.
        Interactive requests are always taken before batch-class ones;
        when the interactive head doesn't fit the remaining rows the
        scan stops rather than letting batch work jump the line."""
        with self._cond:
            while not (self._q or self._qb) and not self._stopped:
                self._cond.wait()
            if not (self._q or self._qb):
                return None                      # stopped and drained
            batch, rows = [], 0
            t_first = time.monotonic()
            linger_until = t_first + self.max_wait_s
            while True:
                now = time.monotonic()
                full = False
                for q in (self._q, self._qb):
                    while q and not full:
                        req = q[0]
                        if self._pop_expired(req, now):
                            q.popleft()
                            continue
                        if rows + req.rows > self.max_batch:
                            full = True
                            break
                        q.popleft()
                        batch.append(req)
                        rows += req.rows
                        if rows == self.max_batch:
                            full = True
                    if full:
                        break
                remaining = linger_until - now
                if rows >= self.max_batch or full or remaining <= 0 \
                        or self._stopped:
                    break
                if not batch and not self._q and not self._qb:
                    # everything seen so far expired; wait fresh
                    t_first = time.monotonic()
                    linger_until = t_first + self.max_wait_s
                    self._cond.wait()
                    if self._stopped and not self._q and not self._qb:
                        return None
                    continue
                self._cond.wait(timeout=remaining)
            self._inflight += len(batch)
            self.metrics.record_queue_depth(len(self._q)
                                            + len(self._qb))
            return batch

    def _run_batch(self, batch):
        arrays = [np.concatenate([r.arrays[i] for r in batch], axis=0)
                  for i in range(len(batch[0].arrays))] \
            if len(batch) > 1 else list(batch[0].arrays)
        rows = sum(r.rows for r in batch)
        # queue->batch handoff: each request's time-in-queue becomes
        # a retrospective "serve" span; the engine's serve.compute
        # span follows inside infer()
        for r in batch:
            _tracing.event("serve.queue", r.t_perf, phase="serve",
                           rows=r.rows)
        try:
            outs = self.engine.infer(*arrays)
        except Exception as e:
            for r in batch:
                self.metrics.record_error()
                r.future._set_exception(e)
            return
        self.metrics.record_batch(rows)
        self._sync_plan_bytes()
        now = time.monotonic()
        off = 0
        for r in batch:
            sl = [o[off:off + r.rows]
                  if getattr(o, "ndim", 0) and o.shape[0] == rows
                  else o for o in outs]
            off += r.rows
            self.metrics.record_done(now - r.t_submit)
            r.future._set(sl)

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._inflight -= len(batch)
