"""ModelRouter — many hot .mxa models behind one name table, HBM-aware.

A serving process fronts MANY models but one device memory. The router
owns the name -> EnginePool table and makes the memory call:

  - **Admission** (`load`): before any plan is compiled — before the
    artifact is even opened by an engine — the model's footprint is
    ESTIMATED from its MANIFEST.json devstats block (export-time XLA
    `peak_bytes`, times the replica count) and checked against the HBM
    budget by `telemetry.devstats.preflight`. A model whose estimate
    alone exceeds the whole budget is rejected outright (HTTP 507 at the
    frontend) without evicting anything and without a single plan
    entering any cache.
  - **Eviction**: when the estimate fits the budget but not the current
    headroom, least-recently-USED models are unloaded — eviction cost is
    each model's *measured* summed `plan_resident_bytes` across replicas
    (devstats accounting, the same number on /metrics) — until the new
    model fits. `MXNET_SERVING_MAX_MODELS` bounds the table by count the
    same way (0 = unbounded).
  - **Routing** (`predict`): name -> pool lookup, LRU touch, least-loaded
    replica dispatch. Unknown names raise `UnknownModel` (HTTP 404).

Concurrency: ONE lock guards the table. Loads insert a LOADING
placeholder under the lock, then build the pool OUTSIDE it (compiles
take seconds; predictions for other models must not stall), then flip
the placeholder to READY. Concurrent `load` of the same name waits on
the placeholder's event instead of double-building. Evicted pools are
closed outside the lock too — their batcher workers join without
blocking the table.
"""
from __future__ import annotations

import io
import json
import os
import threading
import zipfile

from .pool import EnginePool
from ..telemetry import devstats


class UnknownModel(KeyError):
    """predict()/unload() against a name the router does not hold."""


def manifest_need_bytes(path):
    """Estimated per-replica HBM need of a .mxa artifact, WITHOUT
    loading it: the export-time devstats `peak_bytes` when the manifest
    carries it, else the parameter blob size (weights must at least be
    resident), else the file size."""
    try:
        with zipfile.ZipFile(path) as zf:
            try:
                with io.TextIOWrapper(zf.open("MANIFEST.json"),
                                      encoding="utf-8") as f:
                    man = json.load(f)
                peak = int((man.get("devstats") or {}).get("peak_bytes")
                           or 0)
                if peak > 0:
                    return peak
            except KeyError:
                pass
            for info in zf.infolist():
                if info.filename.endswith("params.bin"):
                    return int(info.file_size)
    except (OSError, zipfile.BadZipFile):
        pass
    try:
        return int(os.path.getsize(path))
    except OSError:
        return 0


def _manifest_decode_block(path):
    """The manifest `decode` block when `path` is a decode artifact
    (contrib.export.export_decode_model), else None."""
    try:
        with zipfile.ZipFile(path) as zf:
            with io.TextIOWrapper(zf.open("MANIFEST.json"),
                                  encoding="utf-8") as f:
                return json.load(f).get("decode")
    except (OSError, KeyError, ValueError, zipfile.BadZipFile):
        return None


class _DecodeAdapter:
    """Pool-shaped wrapper around one DecodeEngine so decode artifacts
    sit in the same name table as predict pools. A decode engine is its
    own concurrency domain (the KV-slot pool), so the router's replica
    knob does not apply — one engine per name. `submit` (the predict
    path) is refused with a 400-mapping error; `generate` is the
    entry point."""

    def __init__(self, path, name=None):
        from .decode import DecodeEngine
        self.engine = DecodeEngine(path, name=name)

    def submit(self, *arrays, timeout_ms=None, priority="interactive"):
        raise ValueError("decode model: POST :generate, not :predict")

    def generate(self, tokens, max_new_tokens=None, eos_id=None,
                 timeout_ms=None):
        return self.engine.submit(tokens, max_new_tokens=max_new_tokens,
                                  eos_id=eos_id, timeout_ms=timeout_ms)

    def resident_bytes(self):
        return self.engine.resident_bytes()

    def stats(self):
        st = self.engine.stats()
        st["decode"] = True
        return st

    def close(self, drain=True):
        self.engine.close(drain=drain)


class _Entry:
    __slots__ = ("path", "pool", "need", "last_used", "ready", "error")

    def __init__(self, path, need):
        self.path = path
        self.pool = None            # None while LOADING
        self.need = need            # admission-time estimate (bytes)
        self.last_used = 0
        self.ready = threading.Event()
        self.error = None           # load failure, for concurrent waiters


class ModelRouter:
    """Name table of hot models with HBM-budgeted LRU admission.

    Parameters
    ----------
    budget : HBM budget in bytes; None reads MXNET_SERVING_HBM_BUDGET,
        falling back to devstats.hbm_budget() (None = unbudgeted).
    max_models : table size bound (0 = unbounded).
    replicas : EnginePool replica count per model.
    pool_factory : replaces EnginePool construction (tests inject
        fakes); called as `pool_factory(path, replicas=r)`.
    need_fn : replaces `manifest_need_bytes` (per-replica estimate).
    pool_kw : extra EnginePool kwargs (queue_depth, buckets, ...).
    """

    def __init__(self, budget=None, max_models=None, replicas=None,
                 pool_factory=None, need_fn=None, **pool_kw):
        from .. import config
        if budget is None:
            budget = config.get("MXNET_SERVING_HBM_BUDGET")
        if budget is None:
            budget = devstats.hbm_budget()
        self.budget = int(budget) if budget else None
        if max_models is None:
            max_models = config.get("MXNET_SERVING_MAX_MODELS")
        self.max_models = int(max_models or 0)
        if replicas is None:
            replicas = config.get("MXNET_SERVING_REPLICAS")
        self.replicas = max(1, int(replicas or 1))
        self._pool_factory = pool_factory
        self._need_fn = need_fn or manifest_need_bytes
        self._pool_kw = pool_kw
        self._lock = threading.Lock()
        self._models = {}           # name -> _Entry
        self._tick = 0              # LRU clock (monotonic counter)
        self._closed = False

    # -- internals (callers hold self._lock) ---------------------------------

    def _touch(self, entry):
        self._tick += 1
        entry.last_used = self._tick

    def _resident_locked(self):
        return sum(e.pool.resident_bytes() for e in self._models.values()
                   if e.pool is not None)

    def _pick_victims(self, need, incoming):
        """Choose LRU READY entries to evict so `need` more bytes fit
        the budget (and the table stays under max_models). Returns the
        victim names; caller pops + closes them. LOADING entries are
        never victims (their cost is unknown and a waiter holds them)."""
        victims = []
        if self.budget is None and not self.max_models:
            return victims
        ready = sorted(
            ((e.last_used, name) for name, e in self._models.items()
             if e.pool is not None and name != incoming),
            key=lambda t: t[0])
        resident = self._resident_locked()
        count = sum(1 for e in self._models.values())
        for _, name in ready:
            over_bytes = (self.budget is not None
                          and resident + need > self.budget)
            over_count = (self.max_models
                          and count + 1 > self.max_models)
            if not over_bytes and not over_count:
                break
            victims.append(name)
            resident -= self._models[name].pool.resident_bytes()
            count -= 1
        if self.budget is not None and resident + need > self.budget:
            # unfittable even with every READY model gone
            devstats.preflight(incoming, need, resident_bytes=resident,
                               budget=self.budget, what="serving model")
        if self.max_models and count + 1 > self.max_models:
            raise RuntimeError(
                f"model table full ({self.max_models}) and nothing "
                f"evictable")
        return victims

    def _build_pool(self, path):
        if self._pool_factory is not None:
            return self._pool_factory(path, replicas=self.replicas)
        if _manifest_decode_block(path) is not None:
            return _DecodeAdapter(path)
        return EnginePool(path, replicas=self.replicas, **self._pool_kw)

    # -- public API ----------------------------------------------------------

    def load(self, name, path):
        """Hot-load `path` under `name`. Admission order is the
        contract: (1) whole-budget preflight on the manifest estimate —
        an over-budget model is rejected BEFORE eviction and BEFORE any
        plan enters any cache; (2) LRU eviction down to headroom;
        (3) pool build outside the lock. Returns the entry's stats."""
        name = str(name)
        need = int(self._need_fn(path)) * self.replicas
        # (1) the estimate alone must fit an empty device: a model that
        # can never fit must not evict everything else first
        devstats.preflight(name, need, resident_bytes=0,
                           budget=self.budget, what="serving model")
        victims = []
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            cur = self._models.get(name)
            if cur is not None:
                wait_for = cur
            else:
                wait_for = None
                for v in self._pick_victims(need, name):
                    victims.append((v, self._models.pop(v)))
                entry = _Entry(path, need)
                self._touch(entry)
                self._models[name] = entry
        for _, ve in victims:
            ve.pool.close()
        if wait_for is not None:
            # someone else holds/builds this name: wait, don't rebuild
            wait_for.ready.wait()
            if wait_for.error is not None:
                raise wait_for.error
            return self.stats(name)
        try:
            pool = self._build_pool(path)
        except BaseException as e:
            with self._lock:
                entry.error = e
                if self._models.get(name) is entry:
                    del self._models[name]
            entry.ready.set()
            raise
        with self._lock:
            # unload()/close() may have dropped the placeholder while we
            # compiled — the orphaned pool must not leak its workers
            orphaned = self._closed or self._models.get(name) is not entry
            if not orphaned:
                entry.pool = pool
                self._touch(entry)
        entry.ready.set()
        if orphaned:
            pool.close()
            raise RuntimeError(f"model {name!r} was unloaded during load")
        return self.stats(name)

    def predict(self, name, arrays, timeout_ms=None,
                priority="interactive"):
        """Route one request; returns the future from the least-loaded
        replica of `name`'s pool. UnknownModel when the name is absent
        (a LOADING entry is waited on, not 404'd)."""
        with self._lock:
            entry = self._models.get(str(name))
            if entry is not None and entry.pool is not None:
                self._touch(entry)
        if entry is None:
            raise UnknownModel(f"model {name!r} is not loaded")
        if entry.pool is None:
            entry.ready.wait()
            with self._lock:
                entry = self._models.get(str(name))
                if entry is None or entry.pool is None:
                    raise UnknownModel(f"model {name!r} is not loaded")
                self._touch(entry)
        fut, _ = entry.pool.submit(*arrays, timeout_ms=timeout_ms,
                                   priority=priority)
        return fut

    def generate(self, name, tokens, max_new_tokens=None, eos_id=None,
                 timeout_ms=None):
        """Route one autoregressive generation to a decode model;
        returns the engine's Session (future resolves to the token
        list). ValueError when the name holds a predict-only model
        (HTTP 400 at the frontend); UnknownModel when absent."""
        with self._lock:
            entry = self._models.get(str(name))
            if entry is not None and entry.pool is not None:
                self._touch(entry)
        if entry is None:
            raise UnknownModel(f"model {name!r} is not loaded")
        if entry.pool is None:
            entry.ready.wait()
            with self._lock:
                entry = self._models.get(str(name))
                if entry is None or entry.pool is None:
                    raise UnknownModel(f"model {name!r} is not loaded")
                self._touch(entry)
        gen = getattr(entry.pool, "generate", None)
        if gen is None:
            raise ValueError(f"model {name!r} is not a decode model "
                             "(use :predict)")
        return gen(tokens, max_new_tokens=max_new_tokens, eos_id=eos_id,
                   timeout_ms=timeout_ms)

    def unload(self, name):
        """Drop a model; its pool (and every compiled plan) is closed.
        UnknownModel when absent. A LOADING entry is waited out first so
        close() never races the build."""
        name = str(name)
        with self._lock:
            entry = self._models.get(name)
        if entry is None:
            raise UnknownModel(f"model {name!r} is not loaded")
        entry.ready.wait()
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is None:
            raise UnknownModel(f"model {name!r} is not loaded")
        if entry.pool is not None:
            entry.pool.close()

    def models(self):
        """Loaded names in LRU order (stalest first)."""
        with self._lock:
            return [name for _, name in sorted(
                (e.last_used, n) for n, e in self._models.items())]

    def resident_bytes(self):
        with self._lock:
            return self._resident_locked()

    def stats(self, name=None):
        """Stats for one model, or the full table + totals."""
        if name is not None:
            with self._lock:
                entry = self._models.get(str(name))
            if entry is None:
                raise UnknownModel(f"model {name!r} is not loaded")
            entry.ready.wait()
            if entry.pool is None:
                raise UnknownModel(f"model {name!r} is not loaded")
            st = entry.pool.stats()
            st["name"] = str(name)
            st["need_bytes"] = entry.need
            st["path"] = entry.path
            return st
        with self._lock:
            names = list(self._models)
        out = {"models": {}, "budget": self.budget,
               "max_models": self.max_models,
               "replicas": self.replicas}
        for n in names:
            with self._lock:
                e = self._models.get(n)
            if e is None:
                continue
            if e.pool is None:         # mid-load: report, don't block
                out["models"][n] = {"name": n, "loading": True,
                                    "need_bytes": e.need}
                continue
            try:
                out["models"][n] = self.stats(n)
            except UnknownModel:
                continue
        out["resident_bytes"] = self.resident_bytes()
        return out

    def close(self):
        """Idempotent; unloads everything."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._models.values())
            self._models.clear()
        for e in entries:
            e.ready.wait()
            if e.pool is not None:
                e.pool.close()

    __enter__ = lambda self: self

    def __exit__(self, *exc):
        self.close()
        return False
