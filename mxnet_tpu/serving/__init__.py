"""mxnet_tpu.serving — dynamic-batching inference runtime over .mxa
artifacts: the fourth pillar (train / export / predict / **serve**).

Composition (each piece is independently usable):

    engine.ServingEngine   bucketed compiled-plan cache over a Predictor
                           (power-of-two batch buckets, pad-and-slice,
                           warmup) — one XLA program per bucket.
    batcher.DynamicBatcher micro-batches concurrent requests up to
                           max_batch / max_wait_us over a bounded queue,
                           with per-request deadlines and load shedding.
    metrics.ServingMetrics QPS / p50 / p99 / batch histogram / queue
                           depth / shed count (per admission class),
                           exported through mx.profiler's counter-export
                           hook.
    pool.EnginePool        R engine replicas with distinct plan caches
                           behind least-loaded dispatch.
    router.ModelRouter     many hot models in one process: HBM-budgeted
                           admission preflight + LRU eviction by
                           measured plan_resident_bytes.
    frontend.ServingFrontend
                           the network tier: stdlib HTTP/1.1 JSON front
                           door (predict/load/unload/generate//metrics)
                           over a ModelRouter — docs/SERVING.md
                           "Network tier".
    decode.DecodeEngine    autoregressive decode runtime: per-session
                           KV-cache pool, prefill buckets, ONE compiled
                           decode-step plan continuous-batching every
                           live session (docs/SERVING.md "Decode").

Quick start:

    from mxnet_tpu import serving
    eng = serving.ServingEngine("model.mxa")          # warms all buckets
    with serving.DynamicBatcher(eng, max_wait_us=2000,
                                queue_depth=256) as bat:
        out = bat.infer(x_row)                        # from any thread
    print(bat.metrics.to_json())

    fe = serving.ServingFrontend(port=8080, replicas=2)
    fe.router.load("resnet", "resnet.mxa")
    # POST http://127.0.0.1:8080/v1/models/resnet:predict

CLI: `python -m mxnet_tpu.serving model.mxa --selftest` runs a
closed-loop load generator against the batcher and prints a one-line
perf JSON (tiny built-in convnet when no artifact is given);
`python -m mxnet_tpu.serving.frontend --selftest` drives the whole
network tier through real sockets.
"""
from __future__ import annotations

from .engine import ServingEngine
from .batcher import (ADMISSION_CLASSES, DynamicBatcher, Future,
                      RequestTimeout, ServingQueueFull)
from .metrics import ServingMetrics
from .pool import EnginePool
from .router import ModelRouter, UnknownModel


_LAZY = {"ServingFrontend": "frontend", "DecodeEngine": "decode",
         "DecodeModel": "decode", "SessionPool": "decode",
         "SessionPoolFull": "decode", "Session": "decode"}


def __getattr__(name):
    # lazy: `python -m mxnet_tpu.serving.frontend` (or .decode) would
    # otherwise see the submodule in sys.modules before runpy executes
    # it (RuntimeWarning)
    mod = _LAZY.get(name)
    if mod is not None:
        import importlib
        return getattr(importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["ServingEngine", "DynamicBatcher", "ServingMetrics",
           "Future", "RequestTimeout", "ServingQueueFull",
           "ADMISSION_CLASSES", "EnginePool", "ModelRouter",
           "UnknownModel", "ServingFrontend", "DecodeEngine",
           "DecodeModel", "SessionPool", "SessionPoolFull", "Session"]
