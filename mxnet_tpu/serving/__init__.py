"""mxnet_tpu.serving — dynamic-batching inference runtime over .mxa
artifacts: the fourth pillar (train / export / predict / **serve**).

Composition (each piece is independently usable):

    engine.ServingEngine   bucketed compiled-plan cache over a Predictor
                           (power-of-two batch buckets, pad-and-slice,
                           warmup) — one XLA program per bucket.
    batcher.DynamicBatcher micro-batches concurrent requests up to
                           max_batch / max_wait_us over a bounded queue,
                           with per-request deadlines and load shedding.
    metrics.ServingMetrics QPS / p50 / p99 / batch histogram / queue
                           depth / shed count, exported through
                           mx.profiler's counter-export hook.

Quick start:

    from mxnet_tpu import serving
    eng = serving.ServingEngine("model.mxa")          # warms all buckets
    with serving.DynamicBatcher(eng, max_wait_us=2000,
                                queue_depth=256) as bat:
        out = bat.infer(x_row)                        # from any thread
    print(bat.metrics.to_json())

CLI: `python -m mxnet_tpu.serving model.mxa --selftest` runs a
closed-loop load generator against the batcher and prints a one-line
perf JSON (tiny built-in convnet when no artifact is given).
"""
from __future__ import annotations

from .engine import ServingEngine
from .batcher import (DynamicBatcher, Future, RequestTimeout,
                      ServingQueueFull)
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "DynamicBatcher", "ServingMetrics",
           "Future", "RequestTimeout", "ServingQueueFull"]
