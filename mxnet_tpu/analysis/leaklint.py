"""leaklint — resource-lifecycle audit over the whole tree.

The cluster/tracing/devstats growth spurt added dozens of thread spawns,
HTTP servers, signal hooks and staging directories; only code review
watched their lifecycles. This pass checks the four shapes that actually
leak:

  - ``leak-unjoined-thread`` (P1): a ``threading.Thread`` that is
    started but neither daemonized (``daemon=True`` at construction, or
    a ``<t>.daemon = True`` assignment) nor ``join()``-ed anywhere in
    the module. Such a thread pins interpreter exit and outlives the
    object that spawned it.
  - ``leak-unclosed-server`` (P1): an ``HTTPServer``/``socketserver``
    server, raw ``socket``, ``TemporaryDirectory`` or ``open()`` handle
    bound outside a ``with`` block with no ``close``/``shutdown``/
    ``server_close``/``cleanup`` on the same binding in the module —
    the resource leaks on every exception path.
  - ``leak-double-atexit`` (P1): ``atexit.register``/``signal.signal``
    inside a re-callable function with no idempotence guard. A second
    call stacks handlers — and a signal chain that captures its own
    hook (``prev = signal.signal(...)`` twice) recurses forever when
    the signal finally arrives.
  - ``leak-staging-dir`` (P2): a ``tempfile.mkdtemp`` with no matching
    ``shutil.rmtree`` sweep in the module. Advisory: selftests leave
    artifact dirs for inspection deliberately (accepted P2s live in the
    baseline).

Heuristics honor the repo's idioms: ``join()`` anywhere in the module on
the same simple binding counts, as does a ``for t in threads: t.join()``
loop over a list-comprehension binding, a close through a one-level
alias (``f = self._file; f.close()``), or a close of elements appended
into a collection that a loop later drains. Registrations at module
level, under an ``if`` (restore/install-once patterns) or behind an
early ``if ...: return`` guard are exempt, as is registering a bound
method of a function-local object (per-object cleanup, e.g.
callback.py's ``atexit.register(manager.close)``). Reviewed intentional
sites use ``# analysis: allow=<rule>``.
"""
from __future__ import annotations

import ast
import os

from . import Finding
from .tracelint import _dotted, _apply_inline_allows, _dedupe

__all__ = ["scan_tree", "scan_modules", "scan_source"]

_SERVER_TYPES = {"HTTPServer", "ThreadingHTTPServer", "TCPServer",
                 "ThreadingTCPServer", "UDPServer", "ThreadingUDPServer",
                 "UnixStreamServer", "socket", "TemporaryDirectory",
                 "open"}
_CLOSERS = {"close", "shutdown", "server_close", "cleanup", "stop"}
_REGISTRARS = {"atexit.register", "signal.signal"}


def _last(name):
    return name.split(".")[-1] if name else None


def _binding_of(assign_target):
    """Simple name a resource is bound to: `t` for ``t = ...``, the attr
    for ``self._srv = ...``; None for anything fancier."""
    if isinstance(assign_target, ast.Name):
        return assign_target.id
    if isinstance(assign_target, ast.Attribute):
        return assign_target.attr
    return None


def _recv_name(expr):
    """Last segment of a call receiver: `_thread` for
    ``self._thread.join()``."""
    name = _dotted(expr)
    return _last(name)


class _FnCtx:
    __slots__ = ("name", "qualname", "node", "locals")

    def __init__(self, name, qualname, node):
        self.name = name
        self.qualname = qualname
        self.node = node
        self.locals = set()


def _iter_functions(tree):
    """(qualname, node) for every function/method, any nesting depth."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                out.append((qn, child))
                walk(child, qn)
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}.{child.name}" if prefix
                     else child.name)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _make_scope_of(tree):
    """Precomputed lineno -> innermost enclosing function qualname
    (functions come in lexical order, parents before children, so the
    last containing match is the innermost). One tree walk, then O(#fn)
    per lookup — never walk the tree per finding."""
    spans = [(node.lineno, getattr(node, "end_lineno", node.lineno), qn)
             for qn, node in _iter_functions(tree)]

    def scope_of(lineno):
        best = ""
        for lo, hi, qn in spans:
            if lo <= lineno <= hi:
                best = qn
        return best

    return scope_of


def _module_receivers(tree, attrs):
    """Names X where ``X.<attr>(...)`` is called anywhere in the module,
    for attr in `attrs` (receiver = last dotted segment)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in attrs:
            recv = _recv_name(node.func.value)
            if recv:
                names.add(recv)
    return names


def _daemon_assigned(tree):
    """Names X with ``X.daemon = True`` / ``X.setDaemon(True)``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon" and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value:
                    recv = _recv_name(tgt.value)
                    if recv:
                        names.add(recv)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "setDaemon" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                node.args[0].value:
            recv = _recv_name(node.func.value)
            if recv:
                names.add(recv)
    return names


def _with_context_calls(tree):
    """id()s of Call nodes used as a with-statement context manager
    (directly or through the first arg of a wrapper like closing())."""
    ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                ids.add(id(expr))
                if isinstance(expr, ast.Call):
                    for a in expr.args:
                        ids.add(id(a))
    return ids


def _kw_true(call, name):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant) and \
                kw.value.value:
            return True
    return False


def _loop_managed(tree, attrs):
    """Iterable names whose elements get ``<attr>()``-ed in a for loop:
    ``for t in threads: t.join()`` manages every thread in `threads`,
    ``for f, close in targets: ... f.close()`` manages `targets`."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        tgts = set()
        if isinstance(node.target, ast.Name):
            tgts.add(node.target.id)
        elif isinstance(node.target, ast.Tuple):
            tgts |= {e.id for e in node.target.elts
                     if isinstance(e, ast.Name)}
        it = _recv_name(node.iter)
        if not tgts or not it:
            continue
        for st in node.body:
            for n in ast.walk(st):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in attrs and \
                        _recv_name(n.func.value) in tgts:
                    names.add(it)
    return names


def _alias_sources(tree):
    """{alias: {source binding}} for ``f = self._file`` shapes — a close
    on the alias counts as a close on the source."""
    alias = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, (ast.Name, ast.Attribute)):
            src = _recv_name(node.value)
            if src:
                alias.setdefault(node.targets[0].id, set()).add(src)
    return alias


def _appended_calls(tree):
    """{id(call): collection name} for calls constructed inside an
    ``X.append(...)``/``X.add(...)`` argument — the resource is bound to
    the collection, and loop-managed closes on X count for it."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("append", "add"):
            recv = _recv_name(node.func.value)
            if not recv:
                continue
            for a in node.args:
                for n in ast.walk(a):
                    if isinstance(n, ast.Call):
                        out[id(n)] = recv
    return out


def _rmtree_roots(tree):
    """Root names mentioned in any shutil.rmtree(...) argument."""
    roots = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _last(_dotted(node.func)) == "rmtree":
            for a in node.args:
                for n in ast.walk(a):
                    if isinstance(n, ast.Name):
                        roots.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        roots.add(n.attr)
    return roots


# -- thread / server / staging rules -----------------------------------------

def _module_findings(source, relpath):
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    findings = []
    scope_of = _make_scope_of(tree)
    joiners = _module_receivers(tree, {"join"}) | \
        _loop_managed(tree, {"join"})
    closers = _module_receivers(tree, _CLOSERS) | \
        _loop_managed(tree, _CLOSERS)
    daemons = _daemon_assigned(tree)
    starters = _module_receivers(tree, {"start"}) | \
        _loop_managed(tree, {"start"})
    alias = _alias_sources(tree)
    for s in (joiners, closers):
        for r in list(s):
            s |= alias.get(r, set())
    appended = _appended_calls(tree)
    with_ids = _with_context_calls(tree)
    rmtrees = _rmtree_roots(tree)
    returned = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Return, ast.Raise)) and \
                getattr(node, "value", None) is not None:
            for n in ast.walk(node.value):
                returned.add(id(n))

    ctx = (scope_of, relpath, findings, joiners, closers, daemons,
           starters, appended, with_ids, rmtrees)
    seen_assign_values = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            binding = _binding_of(node.targets[0])
            direct = {id(node.value)}
            if isinstance(node.value, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp)):
                direct.add(id(node.value.elt))
            for call in ast.walk(node.value):
                if isinstance(call, ast.Call):
                    seen_assign_values.add(id(call))
                    _check_creation(call, binding if id(call) in direct
                                    else None, *ctx)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and id(node) not in \
                seen_assign_values and id(node) not in returned:
            _check_creation(node, None, *ctx)

    _check_registrations(tree, relpath, findings)
    return _apply_inline_allows(_dedupe(findings), source.splitlines())


def _check_creation(call, binding, scope_of, relpath, findings, joiners,
                    closers, daemons, starters, appended, with_ids,
                    rmtrees):
    last = _last(_dotted(call.func))
    if last is None or id(call) in with_ids:
        return
    if binding is None:
        binding = appended.get(id(call))
    scope = scope_of(call.lineno)
    if last == "Thread":
        if _kw_true(call, "daemon"):
            return
        if binding is not None and binding in daemons:
            return
        started = binding in starters if binding is not None else True
        if not started:
            return               # construction only — started elsewhere
        if binding is not None and binding in joiners:
            return
        what = f"thread bound to {binding!r}" if binding else \
            "anonymous thread"
        findings.append(Finding(
            "leak-unjoined-thread", "P1", relpath, call.lineno,
            f"{what} is started but neither daemonized nor joined in "
            f"this module — it pins interpreter exit and outlives its "
            f"owner", scope=scope))
    elif last in _SERVER_TYPES:
        name = _dotted(call.func)
        if last == "open" and name not in ("open", "io.open"):
            return
        if binding is None:
            # unbound server/handle: nothing can ever close it, but an
            # immediate method call (e.g. socket().getsockname()) in a
            # return/raise position was filtered by the caller
            findings.append(Finding(
                "leak-unclosed-server", "P1", relpath, call.lineno,
                f"{last}(...) handle is never bound, so it can never be "
                f"closed — leaks on every path", scope=scope))
            return
        if binding in closers:
            return
        findings.append(Finding(
            "leak-unclosed-server", "P1", relpath, call.lineno,
            f"{last}(...) bound to {binding!r} outside a `with` and "
            f"never closed/shut down in this module — leaks on "
            f"exception paths", scope=scope))
    elif last == "mkdtemp":
        if binding is not None and binding in rmtrees:
            return
        what = f"staging dir {binding!r}" if binding else \
            "anonymous staging dir"
        findings.append(Finding(
            "leak-staging-dir", "P2", relpath, call.lineno,
            f"{what} from tempfile.mkdtemp has no matching shutil.rmtree "
            f"sweep in this module (advisory: baseline deliberate "
            f"selftest artifact dirs)", scope=scope))


# -- registration idempotence ------------------------------------------------

def _check_registrations(tree, relpath, findings):
    for qn, fn_node in _iter_functions(tree):
        params = {a.arg for a in fn_node.args.args
                  + fn_node.args.posonlyargs + fn_node.args.kwonlyargs}
        local = set(params)
        guarded_ids = set()      # nodes under an If (install-once shape)
        saw_guard_return = []    # (lineno of an `if ...: return` guard)

        def collect(node, under_if):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn_node:
                return
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local.add(tgt.id)
            if isinstance(node, ast.If):
                if any(isinstance(n, ast.Return)
                       for st in node.body for n in ast.walk(st)):
                    saw_guard_return.append(node.lineno)
                for st in node.body + node.orelse:
                    collect(st, True)
                return
            if under_if:
                guarded_ids.add(id(node))
            for child in ast.iter_child_nodes(node):
                collect(child, under_if)

        for st in fn_node.body:
            collect(st, False)

        own = []
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            own.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name not in _REGISTRARS:
                continue
            if id(node) in guarded_ids:
                continue         # install-once / restore-previous shape
            if any(ln < node.lineno for ln in saw_guard_return):
                continue         # early `if already: return` guard
            handler = None
            if name == "atexit.register" and node.args:
                handler = node.args[0]
            elif name == "signal.signal" and len(node.args) > 1:
                handler = node.args[1]
            root = handler
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name) and root.id in local and \
                    root.id not in ("self", "cls"):
                continue         # per-object cleanup of a local resource
            findings.append(Finding(
                "leak-double-atexit", "P1", relpath, node.lineno,
                f"{name}(...) in re-callable {qn}() has no idempotence "
                f"guard — a second call stacks handlers (a signal chain "
                f"capturing its own hook recurses forever)", scope=qn))


# -- entry points ------------------------------------------------------------

def scan_modules(sources):
    findings = []
    for src, rel in sources:
        findings.extend(_module_findings(src, rel))
    return findings


def scan_source(source, relpath="<source>"):
    return _module_findings(source, relpath)


def scan_tree(root):
    findings = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__", ".git")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    findings.extend(_module_findings(f.read(),
                                                     os.path.relpath(
                                                         path, root)))
            except (OSError, UnicodeDecodeError):
                continue
    return findings
